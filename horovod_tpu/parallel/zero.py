"""ZeRO-1/2/3 sharded training over the Horovod data plane.

Horovod's data-parallel contract replicates optimizer state on every
worker. ZeRO stage-1 (Rajbhandari et al., 2020) keeps the same contract
— allreduced gradients into a wrapped optimizer — while sharding the
optimizer state 1/N ways, by decomposing the allreduce into

    reduce-scatter  ->  update on the local shard  ->  allgather

Same bytes on the wire as an allreduce (a ring allreduce IS a
reduce-scatter followed by an allgather), but each chip touches only
1/N of the optimizer state per step and holds only 1/N of it in HBM.

Stages 2 and 3 drop the "same bytes" part:

* **Stage 2** — gradients live only as the local 1/N shard.
  :func:`scatter_gradients` (or ``GradReleasePlan(reduce_scatter=True)``
  bucket-by-bucket during backprop) produces a :class:`ShardedGrads`,
  and the update functions consume it directly, skipping their internal
  reduce-scatter. A reduce-scatter moves (N-1)/N bytes per payload byte
  where an allreduce moves 2(N-1)/N — gradient wire bytes per step are
  halved (visible as busbw on the ``zero``/``bucket_wire`` comms
  lanes), and gradient HBM drops to 1/N (``grad_shards`` in the memory
  ledger).

* **Stage 3** — parameters are sharded at rest (:class:`ShardedParams`,
  built by :func:`shard_params`) and gathered on demand bucket-by-bucket
  (:func:`iter_param_buckets` / :func:`gather_params`): group k+1's
  allgather is dispatched while group k is being consumed, with the
  in-flight window bounded by ``HOROVOD_ZERO_PREFETCH_BUCKETS``.
  ``sharded_adamw.apply`` given ``ShardedParams`` updates the shards in
  place of the full tree and returns a new ``ShardedParams`` — no
  trailing param allgather at all; the forward pass re-gathers under
  compute. Gather stalls are charged to the goodput tracker's
  ``exposed_comm`` category, and the hidden (overlapped) fraction is
  exported as ``horovod_zero_gather_hidden_fraction``.

``HOROVOD_ZERO_STAGE`` selects the stage for the stock training-step
wiring (:func:`stage_from_env`); the functional API above works at any
stage explicitly.

The gradient pytree is flattened into one flat buffer per dtype group
(reusing the PR-3 size-bucket policy: per-rank shard lengths are padded
up to ``bucket_elems`` of ``HOROVOD_FUSION_BUCKET_QUANTUM``, so shard
boundaries land on even per-rank splits AND every step reuses the same
O(#buckets) compiled programs — zero new compiles after warmup). The pad
region holds zeros, the reduction identity for sum/average, and is
sliced off before unpacking, so padded results bit-match unpadded ones.

Two entry points:

* :func:`sharded_update` — wraps any *elementwise* optax transformation
  (sgd, adam, adamw, lamb, ...) as an ``optax.GradientTransformation``
  whose state lives on shards. It keeps the optax delta contract: the
  inner update runs on gradient/param *shards* and the resulting update
  deltas are allgathered back into the original pytree, so
  ``optax.apply_updates(params, updates)`` computes ``p + delta`` with
  the exact same bits as the replicated path (elementwise inner
  transforms only; global-norm clipping must run *before* the wrapper).
  This is what ``hvd.DistributedOptimizer(...,
  shard_optimizer_states=True)`` returns.

* :func:`sharded_adamw` — step-level fused AdamW
  (``opt.apply(params, state, grads)``) keeping flat fp32 master
  weights + moments in the local shard and emitting updated params in
  the parameter dtype (bf16 master-weight training). Step-level because
  the delta contract would break fp32-master semantics: in bf16,
  ``p + (cast(master') - p) != cast(master')``. The per-shard pass runs
  as one fused Pallas kernel
  (:mod:`horovod_tpu.ops.pallas.fused_optimizer`) on TPU local shards,
  gated by ``HOROVOD_SHARDED_FUSED_KERNEL``.

Three call modes, mirroring :mod:`horovod_tpu.ops.collectives`:

* **In-jit under ``shard_map``** — ``lax.psum_scatter`` /
  ``lax.all_gather`` over the bound mesh axes; the local shard is this
  device's slice at ``lax.axis_index``.
* **Eager single-controller** — cached jitted programs over the global
  mesh: pack+reduce-scatter (stacked ``(W, shard)`` output,
  worker-sharded), update, allgather+unpack. Gradient leaves must be
  uniformly worker-stacked or uniformly replicated.
* **Eager multi-process** — host-packed flat buffers ride the enqueue
  runtime's named lanes (``sharded.grads.g<i>`` /
  ``sharded.params.g<i>``), so negotiation, the response cache and the
  timeline see stable per-phase tensor names.

``Compression`` composes on the wire: the flat gradient buffer is
compressed before the reduce-scatter and decompressed on the shard.

Elastic integration: a sharded state snapshot holds only the local
shard (1/N of the bytes per commit); on a membership reform
``elastic.ArrayState.sync`` detects sharded leaves and calls
:func:`resync` instead of broadcasting them (a broadcast would clobber
the distinct per-rank shards).
"""

from __future__ import annotations

import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import comms, flight_recorder
from horovod_tpu.compression import Compression
from horovod_tpu.core import basics, mesh as mesh_mod
from horovod_tpu.metrics import LATENCY_BUCKETS, registry as _metrics
from horovod_tpu.ops import collectives
from horovod_tpu.ops.pallas import fused_optimizer as fused_mod
from horovod_tpu.parallel import sparse as sparse_mod
from horovod_tpu.runtime.fusion_buffer import bucket_elems
from horovod_tpu.utils import compat
from horovod_tpu.utils import env as env_mod

_UPDATES = _metrics().counter(
    "horovod_sharded_updates_total",
    "Sharded (ZeRO-1) optimizer updates applied.")
_UPDATE_SECONDS = _metrics().histogram(
    "horovod_sharded_update_seconds",
    "Wall time of one sharded optimizer update (reduce-scatter + shard "
    "update + allgather).", buckets=LATENCY_BUCKETS)
_STATE_BYTES = _metrics().gauge(
    "horovod_sharded_state_bytes",
    "Optimizer-state bytes resident per chip under sharding (~1/N of "
    "the replicated footprint).")
_RS_BYTES = _metrics().counter(
    "horovod_sharded_reducescatter_bytes_total",
    "Flat gradient bytes entering the sharded reduce-scatter phase.")
_AG_BYTES = _metrics().counter(
    "horovod_sharded_allgather_bytes_total",
    "Flat update/param bytes entering the sharded allgather phase.")
_PROGRAM_BUILDS = _metrics().counter(
    "horovod_sharded_program_builds_total",
    "Compiled sharded-step programs built (steady state goes flat: "
    "bucket-stable shapes mean zero new compiles after warmup).")
_GATHER_STALL_SECONDS = _metrics().counter(
    "horovod_zero_gather_stall_seconds_total",
    "Wall seconds the consumer was blocked waiting on a stage-3 "
    "parameter allgather (exposed communication).")
_GATHER_HIDDEN_SECONDS = _metrics().counter(
    "horovod_zero_gather_hidden_seconds_total",
    "Wall seconds of stage-3 parameter allgather transfer overlapped "
    "under consumer compute (hidden communication).")
_GATHER_HIDDEN_FRACTION = _metrics().gauge(
    "horovod_zero_gather_hidden_fraction",
    "Cumulative fraction of stage-3 gather transfer time hidden under "
    "compute: hidden / (hidden + stalled).")


# ---------------------------------------------------------------------------
# Stage selection + stage-3 prefetch window knobs
# ---------------------------------------------------------------------------

HOROVOD_ZERO_STAGE = "HOROVOD_ZERO_STAGE"
HOROVOD_ZERO_PREFETCH_BUCKETS = "HOROVOD_ZERO_PREFETCH_BUCKETS"
DEFAULT_ZERO_PREFETCH_BUCKETS = 2

_autotuned_prefetch_buckets = 0


def stage_from_env() -> int:
    """ZeRO stage for the stock wiring: 1 (optimizer state only, the
    default), 2 (+ gradient shards via reduce-scatter release), 3
    (+ params sharded at rest). Clamped to [1, 3]."""
    raw = env_mod._get_int(HOROVOD_ZERO_STAGE, 1)
    return max(1, min(3, raw))


def set_autotuned_prefetch_buckets(n: int) -> None:
    """Autotuner commit hook: override the stage-3 prefetch window
    (``parameter_manager`` sweeps ``zero_prefetch_buckets`` alongside
    bucket bytes and pipeline depth). 0 clears the override."""
    global _autotuned_prefetch_buckets
    _autotuned_prefetch_buckets = max(0, int(n))


def prefetch_buckets_from_env() -> int:
    """Stage-3 prefetch window: how many group allgathers may be in
    flight ahead of the consumer (bounds transient HBM to roughly
    window x group bytes). Autotuned value wins over the env knob."""
    if _autotuned_prefetch_buckets > 0:
        return _autotuned_prefetch_buckets
    raw = env_mod._get_int(HOROVOD_ZERO_PREFETCH_BUCKETS,
                           DEFAULT_ZERO_PREFETCH_BUCKETS)
    return max(1, raw)


# ---------------------------------------------------------------------------
# Flat layout spec
# ---------------------------------------------------------------------------

class LeafMeta(NamedTuple):
    """Shape/dtype stand-in for a pytree leaf — enough for
    :func:`build_spec` to lay out a flat buffer without holding the
    (possibly freed) array itself."""

    shape: tuple
    dtype: Any


class GroupSpec(NamedTuple):
    """Flat layout of one same-dtype group of pytree leaves."""

    dtype: str        # np.dtype(...).str
    indices: tuple    # positions in the flattened leaf list
    shapes: tuple     # per-leaf shapes
    sizes: tuple      # per-leaf element counts
    n: int            # total real elements
    shard_elems: int  # per-rank shard length (bucket-padded)
    padded: int       # shard_elems * world


class ZeroSpec(NamedTuple):
    """Static description of a sharded flat layout. Registered as a
    static pytree node: it rides inside optimizer state without
    contributing leaves, so ``tree_map``/``jit``/``device_get`` all pass
    it through untouched (and jit caches key on it)."""

    groups: tuple     # of GroupSpec
    world: int
    rank: int         # -1 in traced (shard_map) mode: slice at axis_index
    num_leaves: int


jax.tree_util.register_static(ZeroSpec)


def _quantum_bytes(st) -> int:
    cfg = getattr(st, "config", None)
    return int(getattr(cfg, "fusion_bucket_quantum",
                       env_mod.DEFAULT_FUSION_BUCKET_QUANTUM_BYTES))


def build_spec(leaves, world: int, rank: int,
               quantum_bytes: int, *, partition=None) -> ZeroSpec:
    """Group ``leaves`` by dtype and lay each group out as one flat
    buffer whose per-rank shard is a PR-3 size bucket (identity at or
    under ``quantum_bytes``, next power-of-two multiple above), so the
    padded total splits evenly into ``world`` bucket-stable shards.

    ``partition`` — optional ordered list of leaf-index cells (e.g. a
    ``GradReleasePlan``'s reverse-topological buckets). Each cell
    becomes its own group (split by dtype if mixed), preserving cell
    order, so bucket-wise reduce-scatters and the optimizer's shard
    layout line up 1:1. Omitted leaves form no group."""
    cells = []
    if partition is None:
        by_dtype: dict = {}
        for i, leaf in enumerate(leaves):
            # .name, not .str: extension dtypes (bfloat16) stringify to
            # a raw void ('<V2') under .str and would not round-trip
            by_dtype.setdefault(np.dtype(leaf.dtype).name, []).append(i)
        cells = [(dts, by_dtype[dts]) for dts in sorted(by_dtype)]
    else:
        for cell in partition:
            by_dtype = {}
            for i in cell:
                by_dtype.setdefault(
                    np.dtype(leaves[i].dtype).name, []).append(i)
            cells.extend((dts, by_dtype[dts]) for dts in sorted(by_dtype))
    groups = []
    for dts, idxs in cells:
        dt = np.dtype(dts)
        shapes = tuple(tuple(leaves[i].shape) for i in idxs)
        sizes = tuple(int(np.prod(s, dtype=np.int64)) for s in shapes)
        n = int(sum(sizes))
        per = -(-n // world)  # ceil
        shard = bucket_elems(per, dt.itemsize, quantum_bytes)
        groups.append(GroupSpec(
            dtype=dts, indices=tuple(idxs), shapes=shapes, sizes=sizes,
            n=n, shard_elems=shard, padded=shard * world))
    return ZeroSpec(groups=tuple(groups), world=int(world),
                    rank=int(rank), num_leaves=len(leaves))


def _pack_group(leaves, g: GroupSpec):
    """Flatten group leaves into one (padded,) vector; the pad holds
    zeros — the sum/average reduction identity (fusion_buffer.py)."""
    parts = [jnp.reshape(leaves[i], (-1,)) for i in g.indices]
    pad = g.padded - g.n
    if pad:
        parts.append(jnp.zeros((pad,), np.dtype(g.dtype)))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _pack_group_stacked(leaves, g: GroupSpec, world: int):
    """Per-worker pack: stacked (W, *shape) leaves -> (W, padded)."""
    parts = [jnp.reshape(leaves[i], (world, -1)) for i in g.indices]
    pad = g.padded - g.n
    if pad:
        parts.append(jnp.zeros((world, pad), np.dtype(g.dtype)))
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _np_pack_group(leaves, g: GroupSpec) -> np.ndarray:
    out = np.zeros((g.padded,), np.dtype(g.dtype))
    off = 0
    for i, size in zip(g.indices, g.sizes):
        out[off:off + size] = np.asarray(leaves[i]).reshape(-1)
        off += size
    return out


def _unpack_group(flat, g: GroupSpec, out: list) -> None:
    off = 0
    for i, shape, size in zip(g.indices, g.shapes, g.sizes):
        out[i] = jnp.reshape(flat[off:off + size], shape)
        off += size


def _bound_axes(axis_name=None) -> tuple:
    """Mesh axes bound in the current trace (empty outside shard_map)."""
    axes = axis_name if axis_name is not None else mesh_mod.GLOBAL_AXES
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    bound = []
    for a in axes:
        try:
            compat.axis_size(a)
        except NameError:
            continue
        bound.append(a)
    return tuple(bound)


def _check_dense(leaves) -> None:
    for leaf in leaves:
        if sparse_mod.is_sparse(leaf):
            raise ValueError(
                "shard_optimizer_states does not support SparseGrad "
                "leaves; pass sparse_as_dense=True (densify before the "
                "flat pack) or keep the replicated path for sparse "
                "models")


def _densify(leaves):
    return [sparse_mod.densify_leaf(g) if sparse_mod.is_sparse(g) else g
            for g in leaves]


def _mode(leaves, st) -> str:
    """'tracer' | 'local' (multi-process) | 'stacked' | 'replicated'."""
    if any(isinstance(x, jax.core.Tracer) for x in leaves):
        return "tracer"
    if collectives._multiprocess_world(st):
        return "local"
    stacked = [collectives._is_worker_stacked(collectives._to_plane(x))
               for x in leaves]
    if all(stacked):
        return "stacked"
    if not any(stacked):
        return "replicated"
    raise ValueError(
        "sharded update needs gradient leaves to be uniformly "
        "worker-stacked or uniformly replicated, got a mix")


def _emit_phase(op: str, phase: str, shard: int, nbytes: int, fn):
    """Flight-recorder bracket for one sharded data-plane phase
    (satellite: postmortems attribute stalls inside a sharded step to
    the reduce-scatter vs allgather phase, with shard index + bytes)."""
    flight_recorder.emit("op_dispatch", op=op, phase=phase,
                         shard=int(shard), bytes=int(nbytes))
    t0 = time.monotonic()
    out = fn()
    seconds = time.monotonic() - t0
    flight_recorder.emit("op_complete", op=op, phase=phase,
                         shard=int(shard), bytes=int(nbytes),
                         seconds=round(seconds, 6))
    # comms plane: the ZeRO reduce-scatter/allgather phases get their own
    # "zero" lane — end-to-end sharded-phase bandwidth, next to the wire
    # lane the bytes physically rode (docs/comms.md)
    comms.record(op, "zero", nbytes, seconds)
    return out


def _set_state_bytes(inner_state, world: int) -> None:
    total = 0
    for leaf in jax.tree_util.tree_leaves(inner_state):
        if not hasattr(leaf, "shape"):
            continue
        nbytes = int(np.prod(leaf.shape, dtype=np.int64)
                     * np.dtype(leaf.dtype).itemsize)
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == world:
            nbytes //= world  # stacked (W, shard): 1/W lives per chip
        total += nbytes
    _STATE_BYTES.set(total)
    from horovod_tpu import memory

    memory.tracker().set_bytes("optimizer_shards", total)


def _set_shard_bytes(subsystem: str, shards, world: int) -> int:
    """Memory-ledger accounting for grad/param shards (PR-13 satellite:
    ``grad_shards`` / ``param_shards`` are first-class subsystems).
    Stacked (W, shard) single-controller arrays count 1/W per chip."""
    total = 0
    for leaf in shards:
        if not hasattr(leaf, "shape"):
            continue
        nbytes = int(np.prod(leaf.shape, dtype=np.int64)
                     * np.dtype(leaf.dtype).itemsize)
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == world:
            nbytes //= world
        total += nbytes
    from horovod_tpu import memory

    memory.tracker().set_bytes(subsystem, total)
    return total


_MODULE_PROGS: dict = {}


def _module_prog(key, builder):
    """Module-level cached-program table for the stage-2/3 functional
    API (scatter_gradients / shard_params / gather) — same
    zero-steady-state-compile contract as the per-optimizer closures."""
    fn = _MODULE_PROGS.get(key)
    if fn is None:
        _PROGRAM_BUILDS.inc()
        fn = builder()
        _MODULE_PROGS[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Stage 2: gradients as shards (reduce-scatter, no full-gradient buffer)
# ---------------------------------------------------------------------------

class ShardedGrads(NamedTuple):
    """Gradients living only as the local 1/N shard (ZeRO-2): one flat
    array per dtype group — ``(shard,)`` local in multi-process/traced
    mode, ``(W, shard)`` worker-sharded single-controller. Produced by
    :func:`scatter_gradients` or a reduce-scatter
    ``GradReleasePlan``; consumed directly by ``sharded_update`` /
    ``sharded_adamw.apply`` (which then skip their internal
    reduce-scatter)."""

    spec: ZeroSpec
    shards: tuple


def _check_shard_spec(got: ZeroSpec, want: ZeroSpec, what: str) -> None:
    if got.groups == want.groups and got.world == want.world:
        return
    raise ValueError(
        f"{what} layout does not match the sharded optimizer state — "
        "build both from the same partition (e.g. sharded_adamw(..., "
        "partition=plan.zero_partition(params)) next to a "
        "reduce-scatter GradReleasePlan), and re-init/resync after an "
        "elastic reform")


def scatter_bucket_group(values: dict, spec: ZeroSpec, gi: int, st, *,
                         average: bool, stacked: bool):
    """Single-controller reduce-scatter of one group's leaves (``values``
    maps leaf index -> array) into a worker-sharded ``(W, shard)`` flat
    array. Replicated inputs take the same short-circuit (and the same
    bits) as the replicated allreduce path; worker-stacked inputs
    reduce across the stack. Cached per (mesh, spec, group)."""
    g = spec.groups[gi]

    def build():
        def f(vals):
            dt = np.dtype(g.dtype)
            if stacked:
                flat = _pack_group_stacked(vals, g, spec.world)
                r = (jnp.mean(flat, axis=0) if average
                     else jnp.sum(flat, axis=0))
            else:
                flat = _pack_group(vals, g)
                r = flat if average else flat * spec.world
            return jnp.reshape(r.astype(dt), (spec.world, g.shard_elems))

        return jax.jit(f, out_shardings=mesh_mod.worker_sharding(st.mesh))

    key = ("zb2s", st.mesh, spec, gi, stacked, average)
    return _module_prog(key, build)(values)


def scatter_gradients(grads, *, spec: ZeroSpec = None,
                      average: bool = True, compression=Compression.none,
                      axis_name=None, partition=None) -> ShardedGrads:
    """Reduce-scatter a full gradient pytree into :class:`ShardedGrads`
    — the stage-2 entry point when gradients arrive whole (for
    bucket-by-bucket release during backprop use
    ``GradReleasePlan(reduce_scatter=True)`` instead).

    ``spec`` aligns the shard layout with an existing optimizer state
    (pass ``state.spec``); otherwise a fresh spec is built (optionally
    from ``partition``). ``compression`` rides the wire exactly as in
    the stage-1 reduce-scatter phase."""
    leaves, _ = jax.tree_util.tree_flatten(grads)
    _check_dense(leaves)
    if any(isinstance(x, jax.core.Tracer) for x in leaves):
        axes = _bound_axes(axis_name)
        if not axes:
            raise ValueError(
                "scatter_gradients traced without a bound mesh axis — "
                "use shard_map (or run eagerly)")
        if spec is None:
            world = int(np.prod([compat.axis_size(a) for a in axes]))
            spec = build_spec(leaves, world, -1,
                              _quantum_bytes(basics._ensure_init()),
                              partition=partition)
        shards = []
        for g in spec.groups:
            flat = _pack_group(leaves, g)
            wire, ctx = compression.compress(flat)
            s = lax.psum_scatter(wire, tuple(axes), scatter_dimension=0,
                                 tiled=True)
            if average:
                s = s / spec.world
            shards.append(compression.decompress(s, ctx)
                          .astype(np.dtype(g.dtype)))
        return ShardedGrads(spec, tuple(shards))
    st = basics._ensure_init()
    mp = collectives._multiprocess_world(st)
    if spec is None:
        spec = build_spec(leaves, st.size, st.rank if mp else 0,
                          _quantum_bytes(st), partition=partition)
    if spec.world != st.size:
        raise ValueError(
            f"scatter_gradients spec was built for world {spec.world} "
            f"but the current world is {st.size}")
    if len(leaves) != spec.num_leaves:
        raise ValueError(
            f"gradient tree has {len(leaves)} leaves but the spec was "
            f"built for {spec.num_leaves}")
    mode = _mode(leaves, st)
    if mode == "local":
        from horovod_tpu.runtime.runtime import get_runtime

        if not collectives._runtime_capable(st):
            raise NotImplementedError(
                "scatter_gradients in a multi-process world needs the "
                "enqueue runtime (tpurun / HOROVOD_RANK env contract)")
        op_name = collectives._OP_NAMES[
            collectives.Average if average else collectives.Sum]
        handles = []
        for gi, g in enumerate(spec.groups):
            flat = _np_pack_group(leaves, g)
            wire, ctx = compression.compress(jnp.asarray(flat))
            nbytes = int(wire.size * np.dtype(wire.dtype).itemsize)
            _RS_BYTES.inc(nbytes)
            flight_recorder.emit(
                "op_dispatch", op="reducescatter", phase="grad_scatter",
                shard=spec.rank, group=gi, bytes=nbytes)
            handles.append((gi, g, ctx, nbytes, time.monotonic(),
                            get_runtime().enqueue_reducescatter(
                                f"zero2.grads.g{gi}", wire,
                                reduce_op=op_name)))
        shards = [None] * len(spec.groups)
        for gi, g, ctx, nbytes, t0, h in handles:
            out = compression.decompress(collectives.synchronize(h), ctx)
            seconds = time.monotonic() - t0
            flight_recorder.emit(
                "op_complete", op="reducescatter", phase="grad_scatter",
                shard=spec.rank, group=gi, seconds=round(seconds, 6))
            comms.record("reducescatter", "zero", nbytes, seconds,
                         world=spec.world)
            shards[gi] = jnp.asarray(out).astype(np.dtype(g.dtype))
        shards = tuple(shards)
    else:
        stacked = mode == "stacked"
        rs_bytes = sum(g.padded * np.dtype(g.dtype).itemsize
                       for g in spec.groups)
        _RS_BYTES.inc(rs_bytes)

        def build():
            def f(lvs):
                outs = []
                for g in spec.groups:
                    dt = np.dtype(g.dtype)
                    if stacked:
                        flat = _pack_group_stacked(lvs, g, spec.world)
                        wire, ctx = compression.compress(flat)
                        r = (jnp.mean(wire, axis=0) if average
                             else jnp.sum(wire, axis=0))
                    else:
                        flat = _pack_group(lvs, g)
                        wire, ctx = compression.compress(flat)
                        r = wire if average else wire * spec.world
                    r = compression.decompress(r, ctx)
                    outs.append(jnp.reshape(
                        r.astype(dt), (spec.world, g.shard_elems)))
                return tuple(outs)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(st.mesh))

        key = ("zg2s", st.mesh, spec, stacked, average, compression)
        shards = _emit_phase(
            "reducescatter", "grad_scatter", spec.rank, rs_bytes,
            lambda: _module_prog(key, build)(leaves))
    _set_shard_bytes("grad_shards", shards, spec.world)
    return ShardedGrads(spec, tuple(shards))


# ---------------------------------------------------------------------------
# Stage 3: params sharded at rest, gathered on demand with prefetch
# ---------------------------------------------------------------------------

class ShardedParams:
    """Parameters sharded at rest (ZeRO-3): one flat array per dtype
    group (``(shard,)`` local multi-process, ``(W, shard)``
    worker-sharded single-controller) plus the original tree structure.
    Registered as a pytree node whose children are the shards, so it
    rides through ``tree_map`` / checkpoint flattening; the elastic and
    checkpoint layers stop at it via :func:`is_sharded_state`."""

    __slots__ = ("spec", "treedef", "shards")

    def __init__(self, spec: ZeroSpec, treedef, shards: tuple):
        self.spec = spec
        self.treedef = treedef
        self.shards = tuple(shards)

    def __repr__(self):
        return (f"ShardedParams(world={self.spec.world}, "
                f"rank={self.spec.rank}, "
                f"groups={len(self.spec.groups)})")


jax.tree_util.register_pytree_node(
    ShardedParams,
    lambda sp: (sp.shards, (sp.spec, sp.treedef)),
    lambda aux, children: ShardedParams(aux[0], aux[1], tuple(children)))


def shard_params(params, *, partition=None) -> ShardedParams:
    """Shard a full parameter pytree at rest (stage-3 entry): keep only
    this rank's 1/N flat slice per dtype group and drop the full tree.
    Eager only — sharding-at-rest is a storage decision, not a traced
    op. The ``param_shards`` memory-ledger subsystem reflects the
    resident bytes."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    _check_dense(leaves)
    if any(isinstance(x, jax.core.Tracer) for x in leaves):
        raise ValueError(
            "shard_params is an eager (at-rest) operation; call it "
            "outside jit/shard_map")
    st = basics._ensure_init()
    mp = collectives._multiprocess_world(st)
    spec = build_spec(leaves, st.size, st.rank if mp else 0,
                      _quantum_bytes(st), partition=partition)
    if mp:
        shards = tuple(
            jnp.asarray(_np_pack_group(leaves, g)[
                spec.rank * g.shard_elems:
                (spec.rank + 1) * g.shard_elems])
            for g in spec.groups)
    else:
        def build():
            def f(lvs):
                return tuple(
                    jnp.reshape(_pack_group(lvs, g),
                                (spec.world, g.shard_elems))
                    for g in spec.groups)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(st.mesh))

        shards = _module_prog(("zp2s", st.mesh, spec), build)(leaves)
    sp = ShardedParams(spec, treedef, tuple(shards))
    _set_shard_bytes("param_shards", sp.shards, spec.world)
    flight_recorder.emit("zero_shard_params", rank=int(spec.rank),
                         world=int(spec.world),
                         groups=len(spec.groups))
    return sp


def _account_gather(stall: float, hidden: float) -> None:
    _GATHER_STALL_SECONDS.inc(stall)
    _GATHER_HIDDEN_SECONDS.inc(hidden)
    stall_total = _GATHER_STALL_SECONDS.value
    hidden_total = _GATHER_HIDDEN_SECONDS.value
    if stall_total + hidden_total > 0:
        _GATHER_HIDDEN_FRACTION.set(
            hidden_total / (stall_total + hidden_total))
    if stall > 0:
        # goodput satellite: a stage-3 gather stall is exposed
        # communication, not input idleness — the step was compute-ready
        # and waiting on the wire
        from horovod_tpu import goodput

        goodput.record_span("exposed_comm", stall)


def gather_hidden_fraction() -> float:
    """Cumulative fraction of stage-3 param-gather transfer time hidden
    under consumer compute (0.0 before any gather)."""
    total = _GATHER_STALL_SECONDS.value + _GATHER_HIDDEN_SECONDS.value
    return (_GATHER_HIDDEN_SECONDS.value / total) if total else 0.0


def _iter_group_gathers(sp: ShardedParams, prefetch=None):
    """Yield ``(group_index, full_flat_buffer)`` in group order, with up
    to ``prefetch`` group allgathers in flight ahead of the consumer —
    the PR-3 dispatch/drain split applied to parameter gathering: group
    k+1's wire time hides under group k's compute. Blocked time is
    charged to exposed_comm; overlapped time counts as hidden."""
    spec = sp.spec
    shards = sp.shards
    if any(isinstance(x, jax.core.Tracer) for x in shards):
        axes = _bound_axes(None)
        if not axes:
            raise ValueError(
                "gathering ShardedParams traced without a bound mesh "
                "axis — use shard_map (or run eagerly)")
        for gi in range(len(spec.groups)):
            yield gi, lax.all_gather(shards[gi], tuple(axes), axis=0,
                                     tiled=True)
        return
    st = basics._ensure_init()
    if spec.world != st.size:
        raise ValueError(
            f"ShardedParams were built for world {spec.world} but the "
            f"current world is {st.size}; re-form via zero.resync")
    mp = collectives._multiprocess_world(st)
    if mp and not collectives._runtime_capable(st):
        raise NotImplementedError(
            "gathering ShardedParams in a multi-process world needs "
            "the enqueue runtime (tpurun / HOROVOD_RANK env contract)")
    window = max(1, int(prefetch if prefetch is not None
                        else prefetch_buckets_from_env()))
    n = len(spec.groups)
    pending: dict = {}
    stall = hidden = 0.0

    def dispatch(gi):
        g = spec.groups[gi]
        nbytes = g.padded * np.dtype(g.dtype).itemsize
        _AG_BYTES.inc(int(nbytes))
        flight_recorder.emit(
            "op_dispatch", op="allgather", phase="param_gather",
            shard=spec.rank, group=gi, bytes=int(nbytes))
        if mp:
            from horovod_tpu.runtime.runtime import get_runtime

            h = get_runtime().enqueue_allgather(
                f"zero3.params.g{gi}", jnp.asarray(shards[gi]))
        else:
            def build():
                def f(shard):
                    return jnp.reshape(shard, (g.padded,))

                return jax.jit(
                    f,
                    out_shardings=mesh_mod.replicated_sharding(st.mesh))

            h = _module_prog(("zgather", st.mesh, spec, gi),
                             build)(shards[gi])
        pending[gi] = (h, time.monotonic(), nbytes)

    nxt = 0
    while nxt < min(window, n):
        dispatch(nxt)
        nxt += 1
    for gi in range(n):
        h, t_disp, nbytes = pending.pop(gi)
        t_wait = time.monotonic()
        if mp:
            full = jnp.asarray(collectives.synchronize(h))
        else:
            full = h
            full.block_until_ready()
        t_done = time.monotonic()
        if nxt < n:
            dispatch(nxt)
            nxt += 1
        waited = t_done - t_wait
        total = t_done - t_disp
        stall += waited
        hidden += max(0.0, total - waited)
        flight_recorder.emit(
            "op_complete", op="allgather", phase="param_gather",
            shard=spec.rank, group=gi, seconds=round(total, 6))
        comms.record("allgather", "zero", nbytes, max(total, 1e-9),
                     world=spec.world)
        yield gi, full
    _account_gather(stall, hidden)


def gather_params(sp: ShardedParams, *, prefetch=None):
    """Materialize the full parameter pytree from :class:`ShardedParams`
    (all groups gathered, prefetch-windowed). For bounded transient HBM
    consume :func:`iter_param_buckets` instead and release each bucket
    after use."""
    out = [None] * sp.spec.num_leaves
    for gi, full in _iter_group_gathers(sp, prefetch):
        _unpack_group(full, sp.spec.groups[gi], out)
    return jax.tree_util.tree_unflatten(sp.treedef, out)


def iter_param_buckets(sp: ShardedParams, *, prefetch=None):
    """Yield ``(group_index, {leaf_index: array})`` bucket-by-bucket in
    layout order, the next group's allgather already in flight under
    this group's compute. Transient HBM is bounded by roughly
    ``prefetch`` (default ``HOROVOD_ZERO_PREFETCH_BUCKETS``) group
    buffers as long as the consumer drops each dict after use."""
    for gi, full in _iter_group_gathers(sp, prefetch):
        g = sp.spec.groups[gi]
        out = {}
        off = 0
        for i, shape, size in zip(g.indices, g.shapes, g.sizes):
            out[i] = jnp.reshape(full[off:off + size], shape)
            off += size
        yield gi, out


# ---------------------------------------------------------------------------
# Generic elementwise wrapper (optax delta contract)
# ---------------------------------------------------------------------------

class ShardedOptState(NamedTuple):
    """State of :func:`sharded_update`: the static layout spec plus the
    inner optimizer's state over the shard tree (one flat array per
    dtype group). Snapshots/checkpoints of this state hold only the
    local shard — 1/N of the replicated bytes."""

    spec: ZeroSpec
    inner: Any


def sharded_update(optimizer, *, average: bool = True,
                   compression=Compression.none, axis_name=None,
                   sparse_as_dense: bool = False, partition=None):
    """Wrap an elementwise optax transformation with ZeRO sharding.

    Stage 2: ``update_fn`` also accepts a :class:`ShardedGrads` (from
    :func:`scatter_gradients` or a reduce-scatter release plan) in
    place of the gradient pytree — the internal reduce-scatter is
    skipped and the update runs straight on the shards (``params`` is
    then required for the output tree structure). ``partition`` aligns
    the shard layout with a release plan's buckets
    (``plan.zero_partition(params)``).

    Returns an ``optax.GradientTransformationExtraArgs`` whose state is
    :class:`ShardedOptState`. The update reduce-scatters the flat
    gradient buffer, runs ``optimizer.update`` on the gradient/param
    *shards*, and allgathers the update deltas back into the original
    pytree — so the returned updates compose with
    ``optax.apply_updates`` exactly like the replicated path, bit for
    bit for elementwise inner transforms (SGD, per-element Adam math).

    Non-elementwise inner transforms (``clip_by_global_norm``,
    ``scale_by_trust_ratio``...) are NOT valid inside the wrapper: they
    would see only 1/N of the elements. Apply them to the gradients
    before this wrapper instead.
    """
    import optax

    progs: dict = {}

    def _prog(key, builder):
        fn = progs.get(key)
        if fn is None:
            _PROGRAM_BUILDS.inc()
            fn = builder()
            progs[key] = fn
        return fn

    # -- eager single-controller programs (bucket-keyed; built once per
    #    (mesh, spec) and reused every step: zero steady-state compiles)

    def _grads_to_shards_prog(mesh, spec, stacked: bool):
        def build():
            def f(leaves):
                outs = []
                for g in spec.groups:
                    dt = np.dtype(g.dtype)
                    if stacked:
                        flat = _pack_group_stacked(leaves, g, spec.world)
                        wire, ctx = compression.compress(flat)
                        r = (jnp.mean(wire, axis=0) if average
                             else jnp.sum(wire, axis=0))
                    else:
                        # replicated input: every worker holds the same
                        # grads, so average == copy and sum == x * W —
                        # the same short-circuit (and the same bits) as
                        # the replicated allreduce path.
                        flat = _pack_group(leaves, g)
                        wire, ctx = compression.compress(flat)
                        r = wire if average else wire * spec.world
                    r = compression.decompress(r, ctx)
                    outs.append(jnp.reshape(
                        r.astype(dt), (spec.world, g.shard_elems)))
                return tuple(outs)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(mesh))

        return _prog(("g2s", mesh, spec, stacked, average, compression),
                     build)

    def _params_to_shards_prog(mesh, spec):
        def build():
            def f(leaves):
                return tuple(
                    jnp.reshape(_pack_group(leaves, g),
                                (spec.world, g.shard_elems))
                    for g in spec.groups)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(mesh))

        return _prog(("p2s", mesh, spec), build)

    def _update_prog(mesh, spec):
        def build():
            def f(gshards, inner, pshards, extra):
                return optimizer.update(gshards, inner, pshards, **extra)

            return jax.jit(f)

        return _prog(("upd", mesh, spec), build)

    def _shards_to_updates_prog(mesh, spec):
        def build():
            def f(deltas):
                out = [None] * spec.num_leaves
                for g, d in zip(spec.groups, deltas):
                    _unpack_group(jnp.reshape(d, (g.padded,)), g, out)
                return tuple(out)

            return jax.jit(
                f, out_shardings=mesh_mod.replicated_sharding(mesh))

        return _prog(("s2u", mesh, spec), build)

    # -- shard extraction per mode ----------------------------------------

    def _tracer_shards(leaves, spec, axes):
        idx = lax.axis_index(tuple(axes))
        shards = []
        for g in spec.groups:
            flat = _pack_group(leaves, g)
            shards.append(lax.dynamic_slice(
                flat, (idx * g.shard_elems,), (g.shard_elems,)))
        return tuple(shards)

    def _local_shards(leaves, spec):
        return tuple(
            jnp.asarray(_np_pack_group(leaves, g)[
                spec.rank * g.shard_elems:(spec.rank + 1) * g.shard_elems])
            for g in spec.groups)

    # -- init --------------------------------------------------------------

    def init_fn(params):
        leaves, _ = jax.tree_util.tree_flatten(params)
        _check_dense(leaves)
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError(
                    "shard_optimizer_states under plain jit/pjit has no "
                    "mesh axis to shard over — call it under shard_map, "
                    "eagerly, or in multi-process mode")
            world = int(np.prod([compat.axis_size(a) for a in axes]))
            spec = build_spec(leaves, world, -1,
                              _quantum_bytes(basics._ensure_init()),
                              partition=partition)
            shards = _tracer_shards(leaves, spec, axes)
            return ShardedOptState(spec, optimizer.init(shards))
        st = basics._ensure_init()
        spec = build_spec(leaves, st.size,
                          st.rank if collectives._multiprocess_world(st)
                          else 0,
                          _quantum_bytes(st), partition=partition)
        if collectives._multiprocess_world(st):
            shards = _local_shards(leaves, spec)
        else:
            shards = _params_to_shards_prog(st.mesh, spec)(leaves)
        inner = optimizer.init(shards)
        _set_state_bytes(inner, spec.world)
        return ShardedOptState(spec, inner)

    # -- update ------------------------------------------------------------

    def _update_tracer(leaves, state, pleaves, extra, axes,
                       gshards=None):
        spec = state.spec
        if gshards is None:
            gshards = []
            for g in spec.groups:
                flat = _pack_group(leaves, g)
                wire, ctx = compression.compress(flat)
                s = lax.psum_scatter(wire, tuple(axes),
                                     scatter_dimension=0, tiled=True)
                if average:
                    s = s / spec.world
                gshards.append(compression.decompress(s, ctx)
                               .astype(np.dtype(g.dtype)))
        pshards = (_tracer_shards(pleaves, spec, axes)
                   if pleaves is not None else None)
        deltas, new_inner = optimizer.update(
            tuple(gshards), state.inner, pshards, **extra)
        out = [None] * spec.num_leaves
        for g, d in zip(spec.groups, deltas):
            full = lax.all_gather(d, tuple(axes), axis=0, tiled=True)
            _unpack_group(full, g, out)
        return tuple(out), ShardedOptState(spec, new_inner)

    def _update_single_controller(leaves, state, pleaves, extra, st,
                                  stacked: bool, gshards=None):
        spec = state.spec
        mesh = st.mesh
        if gshards is None:
            rs_bytes = sum(g.padded * np.dtype(g.dtype).itemsize
                           for g in spec.groups)
            _RS_BYTES.inc(rs_bytes)
            gshards = _emit_phase(
                "reducescatter", "sharded_grads", spec.rank, rs_bytes,
                lambda: _grads_to_shards_prog(mesh, spec,
                                              stacked)(leaves))
        pshards = (_params_to_shards_prog(mesh, spec)(pleaves)
                   if pleaves is not None else None)
        deltas, new_inner = _update_prog(mesh, spec)(
            gshards, state.inner, pshards, extra)
        ag_bytes = sum(g.padded * np.dtype(np.dtype(g.dtype)).itemsize
                       for g in spec.groups)
        _AG_BYTES.inc(ag_bytes)
        updates = _emit_phase(
            "allgather", "sharded_updates", spec.rank, ag_bytes,
            lambda: _shards_to_updates_prog(mesh, spec)(deltas))
        return updates, ShardedOptState(spec, new_inner)

    def _update_multiprocess(leaves, state, pleaves, extra, st,
                             gshards=None):
        from horovod_tpu.runtime.runtime import get_runtime

        spec = state.spec
        if not collectives._runtime_capable(st):
            raise NotImplementedError(
                "sharded update in a multi-process world needs the "
                "enqueue runtime (tpurun / HOROVOD_RANK env contract); "
                "for externally-initialized jax.distributed use the "
                "shard_map path")
        op_name = collectives._OP_NAMES[
            collectives.Average if average else collectives.Sum]
        if gshards is None:
            handles = []
            for gi, g in enumerate(spec.groups):
                flat = _np_pack_group(leaves, g)
                wire, ctx = compression.compress(jnp.asarray(flat))
                nbytes = (wire.size * np.dtype(wire.dtype).itemsize)
                _RS_BYTES.inc(int(nbytes))
                flight_recorder.emit(
                    "op_dispatch", op="reducescatter",
                    phase="sharded_grads", shard=spec.rank, group=gi,
                    bytes=int(nbytes))
                # stable per-group names: the negotiation response cache
                # and the timeline see the same tensor lane every step
                handles.append((gi, g, ctx, int(nbytes),
                                time.monotonic(),
                                get_runtime().enqueue_reducescatter(
                                    f"sharded.grads.g{gi}", wire,
                                    reduce_op=op_name)))
            gshards = [None] * len(spec.groups)
            for gi, g, ctx, nbytes, t0, h in handles:
                out = compression.decompress(
                    collectives.synchronize(h), ctx)
                seconds = time.monotonic() - t0
                flight_recorder.emit(
                    "op_complete", op="reducescatter",
                    phase="sharded_grads", shard=spec.rank, group=gi,
                    seconds=round(seconds, 6))
                comms.record("reducescatter", "zero", nbytes, seconds,
                             world=spec.world)
                gshards[gi] = jnp.asarray(out).astype(np.dtype(g.dtype))
        pshards = (_local_shards(pleaves, spec)
                   if pleaves is not None else None)
        deltas, new_inner = optimizer.update(
            tuple(gshards), state.inner, pshards, **extra)
        ag_handles = []
        for gi, (g, d) in enumerate(zip(spec.groups, deltas)):
            nbytes = g.shard_elems * np.dtype(g.dtype).itemsize
            _AG_BYTES.inc(int(nbytes) * spec.world)
            flight_recorder.emit(
                "op_dispatch", op="allgather", phase="sharded_updates",
                shard=spec.rank, group=gi,
                bytes=int(nbytes) * spec.world)
            ag_handles.append((gi, g, int(nbytes) * spec.world,
                               time.monotonic(),
                               get_runtime().enqueue_allgather(
                                   f"sharded.updates.g{gi}",
                                   jnp.asarray(d))))
        out = [None] * spec.num_leaves
        for gi, g, nbytes, t0, h in ag_handles:
            full = jnp.asarray(collectives.synchronize(h))
            seconds = time.monotonic() - t0
            flight_recorder.emit(
                "op_complete", op="allgather", phase="sharded_updates",
                shard=spec.rank, group=gi, seconds=round(seconds, 6))
            comms.record("allgather", "zero", nbytes, seconds,
                         world=spec.world)
            _unpack_group(full, g, out)
        return tuple(out), ShardedOptState(spec, new_inner)

    def _integrity_check_leaves(leaves, st, mode):
        """Single-controller digest over the eager gradient leaves (the
        multi-process path is covered in band by the runtime's
        reduce-scatter digest instead — a caller-thread check there
        could diverge across ranks). Worker-stacked leaves attribute
        the non-finite row to its rank."""
        from horovod_tpu.integrity import digest as integ_digest

        if collectives._multiprocess_world(st):
            return
        if not integ_digest.cadence_due("zero.update"):
            return
        total = 0
        suspect = None
        bad_leaf = None
        for i, leaf in enumerate(leaves):
            if np.dtype(leaf.dtype).kind not in ("f", "V"):
                continue
            if mode == "stacked":
                counts = np.asarray(jnp.sum(
                    ~jnp.isfinite(jnp.reshape(leaf, (leaf.shape[0], -1))),
                    axis=1, dtype=jnp.int32))
                bad = np.nonzero(counts)[0]
                if bad.size and suspect is None:
                    suspect = int(bad[0])
                n = int(counts.sum())
            else:
                n = int(jnp.sum(~jnp.isfinite(leaf)))
            if n and bad_leaf is None:
                bad_leaf = i
            total += n
        integ_digest.verify_local(
            total, bucket="zero.grads",
            tensor=None if bad_leaf is None else f"leaf[{bad_leaf}]",
            suspect_rank=suspect)

    def update_fn(grads, state, params=None, **extra):
        if not isinstance(state, ShardedOptState):
            raise TypeError(
                "sharded_update state must be ShardedOptState (was this "
                "optimizer initialized with shard_optimizer_states?)")
        spec = state.spec
        pre = None  # stage-2: gradients arrive already reduce-scattered
        if isinstance(grads, ShardedGrads):
            _check_shard_spec(grads.spec, spec,
                              "pre-scattered gradient (ShardedGrads)")
            if params is None:
                raise ValueError(
                    "sharded_update over ShardedGrads needs params= "
                    "(the update pytree structure)")
            pre = tuple(grads.shards)
            leaves = None
            treedef = jax.tree_util.tree_structure(params)
            probe = pre
        else:
            leaves, treedef = jax.tree_util.tree_flatten(
                grads, is_leaf=sparse_mod.is_sparse)
            if sparse_as_dense:
                leaves = _densify(leaves)
            _check_dense(leaves)
            if len(leaves) != spec.num_leaves:
                raise ValueError(
                    f"gradient tree has {len(leaves)} leaves but the "
                    f"sharded state was built for {spec.num_leaves}")
            probe = leaves
        pleaves = None
        if params is not None:
            pleaves = jax.tree_util.tree_flatten(params)[0]
        if any(isinstance(x, jax.core.Tracer) for x in probe):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError(
                    "sharded update traced without a bound mesh axis — "
                    "use shard_map (or run eagerly)")
            out, new_state = _update_tracer(leaves, state, pleaves,
                                            extra, axes, gshards=pre)
            return treedef.unflatten(out), new_state
        st = basics._ensure_init()
        if spec.world != st.size:
            raise ValueError(
                f"sharded state was built for world {spec.world} but the "
                f"current world is {st.size}; re-init (elastic re-forms "
                "go through elastic.ArrayState.sync / zero.resync)")
        if pre is None:
            mode = _mode(leaves, st)
            _integrity_check_leaves(leaves, st, mode)
        else:
            # pre-scattered shards carry their own in-band digests
            # (bucket wire / runtime reduce-scatter lanes)
            mode = ("local" if collectives._multiprocess_world(st)
                    else "stacked")
        t0 = time.monotonic()
        if mode == "local":
            out, new_state = _update_multiprocess(leaves, state, pleaves,
                                                  extra, st, gshards=pre)
        else:
            out, new_state = _update_single_controller(
                leaves, state, pleaves, extra, st, mode == "stacked",
                gshards=pre)
        _UPDATES.inc()
        _UPDATE_SECONDS.observe(time.monotonic() - t0)
        return treedef.unflatten(out), new_state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


# ---------------------------------------------------------------------------
# Fused flat AdamW (fp32 master shards, step-level API)
# ---------------------------------------------------------------------------

class FlatAdamState(NamedTuple):
    """State of :func:`sharded_adamw`: per-dtype-group flat fp32 master
    weights and Adam moments, local shard only (~12 bytes/param / N per
    chip vs 12 replicated)."""

    spec: ZeroSpec
    count: Any
    master: Any  # tuple per group, f32 (shard,) / (W, shard) / traced
    mu: Any
    nu: Any


class ShardedAdamW(NamedTuple):
    """Step-level sharded fused AdamW: ``apply(params, state, grads) ->
    (new_params, new_state)`` (same shape of API as
    ``ops.pallas.fused_adamw`` — the delta contract would break fp32
    master-weight semantics in bf16)."""

    init: callable
    apply: callable


def sharded_adamw(learning_rate: float, b1: float = 0.9,
                  b2: float = 0.999, eps: float = 1e-8,
                  weight_decay: float = 1e-4, *, average: bool = True,
                  compression=Compression.none,
                  axis_name=None, partition=None) -> ShardedAdamW:
    """ZeRO-1/2/3 fused AdamW: reduce-scatter grads, one fused Pallas
    pass over the local fp32 master/moment shards
    (:mod:`horovod_tpu.ops.pallas.fused_optimizer`, gated by
    ``HOROVOD_SHARDED_FUSED_KERNEL``), allgather the updated params
    back in the parameter dtype.

    Stage 2: ``apply`` accepts a :class:`ShardedGrads` in place of the
    gradient pytree and skips its internal reduce-scatter. Stage 3:
    ``apply`` given :class:`ShardedParams` (and ``init`` over them)
    updates the shards and returns a new ``ShardedParams`` — the
    trailing param allgather disappears entirely; the forward pass
    re-gathers on demand. ``partition`` aligns the layout with a
    reduce-scatter release plan (``plan.zero_partition(params)``)."""
    import optax

    progs: dict = {}

    def _prog(key, builder):
        fn = progs.get(key)
        if fn is None:
            _PROGRAM_BUILDS.inc()
            fn = builder()
            progs[key] = fn
        return fn

    def _scalars(count):
        t = count.astype(jnp.float32)
        return jnp.stack([
            jnp.float32(b1), jnp.float32(b2),
            1.0 / (1.0 - jnp.float32(b1) ** t),
            1.0 / (1.0 - jnp.float32(b2) ** t),
            jnp.float32(learning_rate), jnp.float32(weight_decay)])

    def _master_prog(mesh, spec):
        def build():
            def f(leaves):
                return tuple(
                    jnp.reshape(_pack_group(leaves, g),
                                (spec.world, g.shard_elems))
                    .astype(jnp.float32)
                    for g in spec.groups)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(mesh))

        return _prog(("master", mesh, spec), build)

    def _apply_prog(mesh, spec):
        def build():
            def f(scalars, master, mu, nu, gshards):
                ps, ws, ms, vs = [], [], [], []
                for g, w, m, v, gr in zip(spec.groups, master, mu, nu,
                                          gshards):
                    p2, w2, m2, v2 = fused_mod.flat_adamw_shard(
                        w, m, v, gr, scalars, eps=eps,
                        out_dtype=np.dtype(g.dtype))
                    ps.append(p2)
                    ws.append(w2)
                    ms.append(m2)
                    vs.append(v2)
                return tuple(ps), tuple(ws), tuple(ms), tuple(vs)

            return jax.jit(f)

        return _prog(("apply", mesh, spec), build)

    def _gather_prog(mesh, spec):
        def build():
            def f(pshards):
                out = [None] * spec.num_leaves
                for g, p in zip(spec.groups, pshards):
                    _unpack_group(jnp.reshape(p, (g.padded,)), g, out)
                return tuple(out)

            return jax.jit(
                f, out_shardings=mesh_mod.replicated_sharding(mesh))

        return _prog(("gather", mesh, spec), build)

    def init(params):
        if isinstance(params, ShardedParams):
            # stage 3: params already live as shards — the fp32 masters
            # are a cast of the local slices, no pack/scatter needed
            spec = params.spec
            master = tuple(jnp.asarray(s).astype(jnp.float32)
                           for s in params.shards)
            zeros = tuple(jnp.zeros_like(w) for w in master)
            state = FlatAdamState(
                spec=spec, count=jnp.zeros([], jnp.int32), master=master,
                mu=zeros, nu=tuple(jnp.zeros_like(w) for w in master))
            _set_state_bytes((state.master, state.mu, state.nu),
                             spec.world)
            return state
        leaves, _ = jax.tree_util.tree_flatten(params)
        _check_dense(leaves)
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError(
                    "sharded_adamw under plain jit/pjit has no mesh axis "
                    "to shard over — use shard_map, eager, or "
                    "multi-process mode")
            world = int(np.prod([compat.axis_size(a) for a in axes]))
            spec = build_spec(leaves, world, -1,
                              _quantum_bytes(basics._ensure_init()),
                              partition=partition)
            idx = lax.axis_index(tuple(axes))
            master = tuple(
                lax.dynamic_slice(_pack_group(leaves, g),
                                  (idx * g.shard_elems,),
                                  (g.shard_elems,)).astype(jnp.float32)
                for g in spec.groups)
        else:
            st = basics._ensure_init()
            mp = collectives._multiprocess_world(st)
            spec = build_spec(leaves, st.size, st.rank if mp else 0,
                              _quantum_bytes(st), partition=partition)
            if mp:
                master = tuple(
                    jnp.asarray(_np_pack_group(leaves, g)[
                        spec.rank * g.shard_elems:
                        (spec.rank + 1) * g.shard_elems])
                    .astype(jnp.float32)
                    for g in spec.groups)
            else:
                master = _master_prog(st.mesh, spec)(leaves)
        zeros = tuple(jnp.zeros_like(w) for w in master)
        state = FlatAdamState(spec=spec, count=jnp.zeros([], jnp.int32),
                              master=master, mu=zeros,
                              nu=tuple(jnp.zeros_like(w) for w in master))
        if not any(isinstance(x, jax.core.Tracer) for x in leaves):
            _set_state_bytes((state.master, state.mu, state.nu),
                             spec.world)
        return state

    def _grad_shards_eager(leaves, spec, st, stacked):
        # one cached program: pack + reduce-scatter (see sharded_update)
        key = ("fg2s", st.mesh, spec, stacked)

        def build():
            def f(lvs):
                outs = []
                for g in spec.groups:
                    if stacked:
                        flat = _pack_group_stacked(lvs, g, spec.world)
                        wire, ctx = compression.compress(flat)
                        r = (jnp.mean(wire, axis=0) if average
                             else jnp.sum(wire, axis=0))
                    else:
                        flat = _pack_group(lvs, g)
                        wire, ctx = compression.compress(flat)
                        r = wire if average else wire * spec.world
                    r = compression.decompress(r, ctx)
                    outs.append(jnp.reshape(
                        r.astype(np.dtype(g.dtype)),
                        (spec.world, g.shard_elems)))
                return tuple(outs)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(st.mesh))

        return _prog(key, build)(leaves)

    def apply(params, state, grads):
        spec = state.spec
        sharded_out = isinstance(params, ShardedParams)
        if sharded_out:
            # stage 3: the updated params stay sharded — no trailing
            # allgather; the forward re-gathers on demand
            _check_shard_spec(params.spec, spec,
                              "ShardedParams (stage-3 params)")
        pre = None
        if isinstance(grads, ShardedGrads):
            _check_shard_spec(grads.spec, spec,
                              "pre-scattered gradient (ShardedGrads)")
            pre = tuple(grads.shards)
            gleaves = None
            probe = pre
        else:
            gleaves, _gt = jax.tree_util.tree_flatten(grads)
            _check_dense(gleaves)
            if len(gleaves) != spec.num_leaves:
                raise ValueError(
                    f"gradient tree has {len(gleaves)} leaves but the "
                    f"sharded state was built for {spec.num_leaves}")
            probe = gleaves
        count = optax.safe_int32_increment(state.count)
        scalars = _scalars(count)

        def _pack_params(ps, ws, ms, vs):
            new_state = FlatAdamState(
                spec, count, tuple(ws), tuple(ms), tuple(vs))
            if sharded_out:
                new_params = ShardedParams(params.spec, params.treedef,
                                           tuple(ps))
                if not any(isinstance(x, jax.core.Tracer) for x in ps):
                    _set_shard_bytes("param_shards", new_params.shards,
                                     spec.world)
                return new_params, new_state
            return None, new_state  # caller gathers + unflattens

        if any(isinstance(x, jax.core.Tracer) for x in probe):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError("sharded_adamw traced without a bound "
                                 "mesh axis — use shard_map")
            ps, ws, ms, vs = [], [], [], []
            for gi, (g, w, m, v) in enumerate(zip(
                    spec.groups, state.master, state.mu, state.nu)):
                if pre is not None:
                    gr = pre[gi]
                else:
                    flat = _pack_group(gleaves, g)
                    wire, ctx = compression.compress(flat)
                    s = lax.psum_scatter(wire, tuple(axes),
                                         scatter_dimension=0, tiled=True)
                    if average:
                        s = s / spec.world
                    gr = compression.decompress(s, ctx)
                p2, w2, m2, v2 = fused_mod.flat_adamw_shard(
                    w, m, v, gr, scalars, eps=eps,
                    out_dtype=np.dtype(g.dtype))
                ps.append(p2)
                ws.append(w2)
                ms.append(m2)
                vs.append(v2)
            new_params, new_state = _pack_params(ps, ws, ms, vs)
            if new_params is not None:
                return new_params, new_state
            out = [None] * spec.num_leaves
            for g, p in zip(spec.groups, ps):
                full = lax.all_gather(p, tuple(axes), axis=0, tiled=True)
                _unpack_group(full, g, out)
            pt = jax.tree_util.tree_flatten(params)[1]
            return pt.unflatten(out), new_state
        st = basics._ensure_init()
        if spec.world != st.size:
            raise ValueError(
                f"sharded state was built for world {spec.world} but the "
                f"current world is {st.size}")
        t0 = time.monotonic()
        if pre is not None:
            mode = ("local" if collectives._multiprocess_world(st)
                    else "stacked")
        else:
            mode = _mode(gleaves, st)
        rs_bytes = sum(g.padded * np.dtype(g.dtype).itemsize
                       for g in spec.groups)
        if mode == "local":
            from horovod_tpu.runtime.runtime import get_runtime

            if not collectives._runtime_capable(st):
                raise NotImplementedError(
                    "sharded_adamw in a multi-process world needs the "
                    "enqueue runtime (tpurun / HOROVOD_RANK)")
            if pre is not None:
                gshards = list(pre)
            else:
                op_name = collectives._OP_NAMES[
                    collectives.Average if average else collectives.Sum]
                handles = []
                for gi, g in enumerate(spec.groups):
                    flat = _np_pack_group(gleaves, g)
                    wire, ctx = compression.compress(jnp.asarray(flat))
                    _RS_BYTES.inc(int(wire.size
                                      * np.dtype(wire.dtype).itemsize))
                    flight_recorder.emit(
                        "op_dispatch", op="reducescatter",
                        phase="sharded_grads", shard=spec.rank, group=gi,
                        bytes=int(wire.size
                                  * np.dtype(wire.dtype).itemsize))
                    handles.append((gi, g, ctx, time.monotonic(),
                                    get_runtime().enqueue_reducescatter(
                                        f"sharded.adamw.grads.g{gi}",
                                        wire, reduce_op=op_name)))
                gshards = [None] * len(spec.groups)
                for gi, g, ctx, ht0, h in handles:
                    gr = compression.decompress(
                        collectives.synchronize(h), ctx)
                    flight_recorder.emit(
                        "op_complete", op="reducescatter",
                        phase="sharded_grads", shard=spec.rank, group=gi,
                        seconds=round(time.monotonic() - ht0, 6))
                    gshards[gi] = jnp.asarray(gr).astype(
                        np.dtype(g.dtype))
            ps, ws, ms, vs = [], [], [], []
            for g, w, m, v, gr in zip(spec.groups, state.master,
                                      state.mu, state.nu, gshards):
                p2, w2, m2, v2 = fused_mod.flat_adamw_shard(
                    w, m, v, gr, scalars, eps=eps,
                    out_dtype=np.dtype(g.dtype))
                ps.append(p2)
                ws.append(w2)
                ms.append(m2)
                vs.append(v2)
            if not sharded_out:
                out = [None] * spec.num_leaves
                ag_handles = []
                for gi, (g, p) in enumerate(zip(spec.groups, ps)):
                    nbytes = g.padded * np.dtype(g.dtype).itemsize
                    _AG_BYTES.inc(int(nbytes))
                    flight_recorder.emit(
                        "op_dispatch", op="allgather",
                        phase="sharded_params", shard=spec.rank,
                        group=gi, bytes=int(nbytes))
                    ag_handles.append((gi, g, time.monotonic(),
                                       get_runtime().enqueue_allgather(
                                           f"sharded.adamw.params.g{gi}",
                                           jnp.asarray(p))))
                for gi, g, ht0, h in ag_handles:
                    full = jnp.asarray(collectives.synchronize(h))
                    flight_recorder.emit(
                        "op_complete", op="allgather",
                        phase="sharded_params", shard=spec.rank,
                        group=gi,
                        seconds=round(time.monotonic() - ht0, 6))
                    _unpack_group(full, g, out)
        else:
            if pre is not None:
                gshards = pre
            else:
                stacked = mode == "stacked"
                _RS_BYTES.inc(rs_bytes)
                gshards = _emit_phase(
                    "reducescatter", "sharded_grads", spec.rank,
                    rs_bytes,
                    lambda: _grad_shards_eager(gleaves, spec, st,
                                               stacked))
            ps, ws, ms, vs = _apply_prog(st.mesh, spec)(
                scalars, state.master, state.mu, state.nu, gshards)
            if not sharded_out:
                ag_bytes = sum(g.padded * np.dtype(g.dtype).itemsize
                               for g in spec.groups)
                _AG_BYTES.inc(ag_bytes)
                out = _emit_phase(
                    "allgather", "sharded_params", spec.rank, ag_bytes,
                    lambda: _gather_prog(st.mesh, spec)(ps))
        _UPDATES.inc()
        _UPDATE_SECONDS.observe(time.monotonic() - t0)
        new_params, new_state = _pack_params(ps, ws, ms, vs)
        if new_params is not None:
            return new_params, new_state
        pt = jax.tree_util.tree_flatten(params)[1]
        return pt.unflatten(list(out)), new_state

    return ShardedAdamW(init=init, apply=apply)


# ---------------------------------------------------------------------------
# Elastic integration: shard-aware sync after a membership reform
# ---------------------------------------------------------------------------

def is_sharded_state(x) -> bool:
    """True for leaves that hold per-rank shards — optimizer states,
    stage-3 parameter shards and stage-2 gradient shards.
    ``elastic.ArrayState.sync`` must NOT broadcast these (rank 0's shard
    would clobber every other rank's); it calls :func:`resync`."""
    return isinstance(x, (ShardedOptState, FlatAdamState, ShardedParams,
                          ShardedGrads))


def _kind_of(state) -> str:
    if isinstance(state, FlatAdamState):
        return "flat_adamw"
    if isinstance(state, ShardedParams):
        return "sharded_params"
    if isinstance(state, ShardedGrads):
        return "sharded_grads"
    return "generic"


def layout_of(state) -> dict:
    """JSON-serializable shard layout of a sharded state — recorded in
    checkpoint manifests so restore can re-flatten/re-scatter into a
    different world size (``from_full_buffers``)."""
    spec = state.spec
    return {
        "kind": _kind_of(state),
        "world": int(spec.world),
        "groups": [[g.dtype, int(g.n), int(g.shard_elems), int(g.padded)]
                   for g in spec.groups],
    }


def export_shard_arrays(state) -> dict:
    """Host-resident copies of a sharded state's local arrays, in a
    stable named layout — the unit the checkpoint writer serializes and
    the neighbor-replica exchange ships. Parallel to
    :func:`from_full_buffers` / the resync replica path."""
    if isinstance(state, FlatAdamState):
        return {"kind": "flat_adamw",
                "count": np.asarray(state.count),
                "master": [np.asarray(m) for m in state.master],
                "mu": [np.asarray(m) for m in state.mu],
                "nu": [np.asarray(m) for m in state.nu]}
    if isinstance(state, (ShardedParams, ShardedGrads)):
        # one local flat slice per dtype group: the writer's generic
        # "leaves" path serializes them as {key}#leaf/{gi}
        return {"kind": _kind_of(state),
                "leaves": [np.asarray(s) for s in state.shards]}
    leaves, _ = jax.tree_util.tree_flatten(state.inner)
    return {"kind": "generic",
            "leaves": [np.asarray(x) for x in leaves]}


def _slice_new_shard(full_old: np.ndarray, old_n: int, g_new: GroupSpec,
                     new_rank: int, dtype) -> jnp.ndarray:
    return _reshard(full_old, GroupSpec(
        dtype=g_new.dtype, indices=(), shapes=(), sizes=(), n=old_n,
        shard_elems=0, padded=full_old.shape[0]), g_new, new_rank, dtype)


def from_full_buffers(target, full: dict, old_groups):
    """Rebuild a sharded state from FULL old flat buffers (one per
    dtype group), slicing this rank's shard under ``target``'s (new)
    layout — the disk-restore analogue of :func:`resync`, with the
    gathers replaced by buffers read from shard files.

    ``target`` supplies the new spec (typically a freshly-initialized
    state); ``full`` is the named-array dict shape of
    :func:`export_shard_arrays` but with *full* (old_padded,) buffers;
    ``old_groups`` is the manifest's ``groups`` layout list."""
    spec = target.spec
    if len(old_groups) != len(spec.groups):
        raise ValueError(
            "checkpoint restore: parameter structure changed (dtype "
            "group count mismatch between manifest and target)")
    if isinstance(target, FlatAdamState):
        master, mu, nu = [], [], []
        for gi, g_new in enumerate(spec.groups):
            _dt, old_n, _s, _p = old_groups[gi]
            master.append(_slice_new_shard(
                np.asarray(full["master"][gi]), old_n, g_new, spec.rank,
                np.float32))
            mu.append(_slice_new_shard(
                np.asarray(full["mu"][gi]), old_n, g_new, spec.rank,
                np.float32))
            nu.append(_slice_new_shard(
                np.asarray(full["nu"][gi]), old_n, g_new, spec.rank,
                np.float32))
        count = jnp.asarray(np.asarray(full["count"]).astype(np.int32))
        new_state = FlatAdamState(spec=spec, count=count,
                                  master=tuple(master), mu=tuple(mu),
                                  nu=tuple(nu))
        _set_state_bytes((new_state.master, new_state.mu, new_state.nu),
                         spec.world)
        return new_state
    if isinstance(target, (ShardedParams, ShardedGrads)):
        shards = []
        for gi, g_new in enumerate(spec.groups):
            _dt, old_n, _s, _p = old_groups[gi]
            shards.append(_slice_new_shard(
                np.asarray(full["leaves"][gi]).reshape(-1), old_n,
                g_new, spec.rank, np.dtype(g_new.dtype)))
        if isinstance(target, ShardedParams):
            new_state = ShardedParams(spec, target.treedef,
                                      tuple(shards))
            _set_shard_bytes("param_shards", new_state.shards,
                             spec.world)
        else:
            new_state = ShardedGrads(spec, tuple(shards))
            _set_shard_bytes("grad_shards", new_state.shards,
                             spec.world)
        return new_state
    leaves, treedef = jax.tree_util.tree_flatten(target.inner)
    by_shard: dict = {}
    for gi, g in enumerate(spec.groups):
        by_shard.setdefault(int(g.shard_elems), []).append(gi)
    new_leaves = []
    for li, leaf in enumerate(leaves):
        stored = full["leaves"][li]
        if not hasattr(leaf, "shape") or np.ndim(leaf) == 0:
            val = np.asarray(stored).reshape(-1)[0]
            new_leaves.append(jnp.asarray(val).astype(
                leaf.dtype if hasattr(leaf, "dtype") else np.float64))
            continue
        cand = by_shard.get(int(np.shape(leaf)[0]), [])
        if np.ndim(leaf) != 1 or len(cand) != 1:
            raise ValueError(
                "checkpoint restore of a generic sharded inner state "
                "needs unambiguous 1-D shard leaves (one dtype group "
                f"per shard length); got leaf shape {np.shape(leaf)}")
        gi = cand[0]
        _dt, old_n, _s, _p = old_groups[gi]
        new_leaves.append(_slice_new_shard(
            np.asarray(stored), old_n, spec.groups[gi], spec.rank,
            leaf.dtype))
    new_inner = treedef.unflatten(new_leaves)
    new_state = ShardedOptState(spec=spec, inner=new_inner)
    _set_state_bytes(new_inner, spec.world)
    return new_state


def _gather_old_segments(local: np.ndarray, old_rank: int,
                         old_world: int, old_shard: int,
                         fill: np.ndarray, replica_rank: int = -1,
                         replica_local=None):
    """Rebuild the full old flat buffer from surviving shards: allgather
    (length, old_rank, shard) from every current rank, place each
    surviving old rank's segment, and leave ``fill`` in segments whose
    owner died. First claim wins — survivors occupy the lowest new
    ranks, so a fresh joiner can never shadow a survivor's segment.

    A second gather round collects neighbor REPLICAS
    (:mod:`horovod_tpu.ckpt.replica`): a survivor holding the dead
    rank's shard bytes contributes them, so the dead segment gets its
    true last-commit values instead of ``fill``. Every rank joins both
    rounds (collective uniformity) — ranks with nothing to offer send a
    one-element dummy tagged rank -1. Returns ``(full,
    replica_restored_ranks)``."""
    lens = np.asarray(collectives.allgather(
        np.array([local.shape[0]], np.int64))).reshape(-1)
    ranks = np.asarray(collectives.allgather(
        np.array([old_rank], np.int64))).reshape(-1)
    cat = np.asarray(collectives.allgather(np.ascontiguousarray(local)))
    full = np.array(fill, copy=True)
    claimed = set()
    off = 0
    for j in range(len(ranks)):
        ln = int(lens[j])
        r = int(ranks[j])
        seg = cat[off:off + ln]
        off += ln
        if 0 <= r < old_world and ln == old_shard and r not in claimed:
            full[r * old_shard:(r + 1) * old_shard] = seg
            claimed.add(r)
    rep = (np.zeros((1,), local.dtype) if replica_local is None
           else np.ascontiguousarray(
               np.asarray(replica_local).reshape(-1).astype(
                   local.dtype, copy=False)))
    rlens = np.asarray(collectives.allgather(
        np.array([rep.shape[0]], np.int64))).reshape(-1)
    rranks = np.asarray(collectives.allgather(
        np.array([replica_rank if replica_local is not None else -1],
                 np.int64))).reshape(-1)
    rcat = np.asarray(collectives.allgather(rep))
    replica_restored = set()
    off = 0
    for j in range(len(rranks)):
        ln = int(rlens[j])
        r = int(rranks[j])
        seg = rcat[off:off + ln]
        off += ln
        if 0 <= r < old_world and ln == old_shard and r not in claimed:
            full[r * old_shard:(r + 1) * old_shard] = seg
            claimed.add(r)
            replica_restored.add(r)
    return full, replica_restored


def _reshard(full_old: np.ndarray, g_old: GroupSpec, g_new: GroupSpec,
             new_rank: int, dtype) -> jnp.ndarray:
    real = full_old[:g_old.n]
    flat = np.zeros((g_new.padded,), np.dtype(dtype))
    flat[:g_new.n] = real
    return jnp.asarray(
        flat[new_rank * g_new.shard_elems:
             (new_rank + 1) * g_new.shard_elems])


def _meta_leaves_from_spec(spec: ZeroSpec):
    """Shape/dtype stand-ins for every leaf covered by ``spec`` — lets
    resync re-lay-out grad/param shards whose full tree no longer
    exists anywhere (that is the point of stages 2/3)."""
    metas = [None] * spec.num_leaves
    for g in spec.groups:
        for i, shape in zip(g.indices, g.shapes):
            metas[i] = LeafMeta(shape=tuple(shape),
                                dtype=np.dtype(g.dtype))
    return metas


def _resync_needed(spec: ZeroSpec, st) -> bool:
    """Collective-uniform decision: a rank-local layout mismatch on ANY
    rank re-shards on ALL ranks (a survivor keeping its old rank must
    still join the allgathers of a renumbered peer)."""
    local = int(spec.world != st.size or spec.rank != st.rank)
    if not collectives._multiprocess_world(st):
        return bool(local)
    total = np.asarray(collectives.allreduce(
        np.array([local], np.int32), op=collectives.Sum))
    return int(total.reshape(-1)[0]) > 0


def resync(state, params=None, root_rank: int = 0, replica=None):
    """Re-shard a sharded optimizer state after an elastic membership
    reform: allgather the surviving old shards, rebuild the full flat
    buffers (dead ranks' segments fall back to the neutral value —
    zeros for moments, the current params for fp32 masters; exact for
    stateless inners like SGD), and slice the new world's shard.

    ``replica`` — ``(src_old_rank, exported_arrays)`` from
    ``horovod_tpu.ckpt.replica.lookup`` when this rank holds a neighbor
    replica of a (possibly dead) old rank's shard. A second gather
    round offers those bytes to every rank, so a dead rank's moment
    segments restore to their true last-commit values instead of the
    neutral fill. Ranks without a replica pass None and still join the
    round (collective uniformity).

    ``params`` must already be synced (ArrayState.sync broadcasts
    params before the optimizer tree). It may be ``None`` when
    ``state`` is a :class:`ShardedParams` / :class:`ShardedGrads` —
    those carry their own leaf metadata. No-op when the layout still
    matches on every rank."""
    from horovod_tpu.elastic.state import broadcast_object_wire

    st = basics._ensure_init()
    spec = state.spec
    if not _resync_needed(spec, st):
        return state
    if not collectives._multiprocess_world(st):
        raise ValueError(
            "sharded-state resync needs a multi-process world (a "
            "single-controller mesh cannot change size under elastic); "
            f"state layout was world={spec.world} rank={spec.rank}, "
            f"current world={st.size} rank={st.rank}")
    # preserve the old grouping (default dtype cells or a release
    # plan's bucket partition) so bucket-aligned layouts survive the
    # reform with the same group structure
    part = [list(g.indices) for g in spec.groups]
    if isinstance(state, (ShardedParams, ShardedGrads)):
        # grad/param shards describe their own leaves: rebuild layout
        # metadata from the spec (the full tree exists nowhere)
        pleaves = _meta_leaves_from_spec(spec)
    elif isinstance(params, ShardedParams):
        # stage-3: the (already-resynced) param shards are the only
        # full copy — gather them to seed the master fills below
        pleaves = jax.tree_util.tree_flatten(gather_params(params))[0]
    else:
        pleaves, _ = jax.tree_util.tree_flatten(params)
    new_spec = build_spec(pleaves, st.size, st.rank, _quantum_bytes(st),
                          partition=part)
    # survivors (incl. the root) share the authoritative old layout;
    # fresh joiners adopt it so everyone parses the gathers identically
    old_world, old_groups = broadcast_object_wire(
        (spec.world,
         tuple((g.dtype, g.n, g.shard_elems, g.padded)
               for g in spec.groups)),
        root_rank)
    if len(old_groups) != len(new_spec.groups):
        raise ValueError(
            "elastic resync: parameter structure changed across the "
            "reform (dtype group count mismatch)")
    flight_recorder.emit("sharded_resync", old_world=int(old_world),
                         new_world=int(st.size), rank=int(st.rank))
    rep_rank = -1
    rep_entries = None
    want_kind = _kind_of(state)
    if replica is not None:
        rep_rank, rep_entries = replica
        if (not isinstance(rep_entries, dict)
                or rep_entries.get("kind") != want_kind):
            rep_rank, rep_entries = -1, None
    replica_restored: set = set()  # (component, old_rank) placements

    def regroup(leaf, gi, fill_np, rep_arr=None, tag=""):
        _dt, old_n, old_shard, old_padded = old_groups[gi]
        g_new = new_spec.groups[gi]
        g_old = GroupSpec(dtype=_dt, indices=(), shapes=(), sizes=(),
                          n=old_n, shard_elems=old_shard,
                          padded=old_padded)
        local = np.asarray(leaf).reshape(-1)
        full, from_replica = _gather_old_segments(
            local, spec.rank, old_world, old_shard, fill_np,
            replica_rank=(rep_rank if rep_arr is not None else -1),
            replica_local=rep_arr)
        replica_restored.update((tag, r) for r in from_replica)
        return _reshard(full, g_old, g_new, st.rank, leaf.dtype)

    def _rep(component, idx):
        if rep_entries is None:
            return None
        try:
            arr = rep_entries[component][idx]
        except (KeyError, IndexError, TypeError):
            return None
        return None if arr is None else np.asarray(arr)

    def _finish_replica_accounting():
        if replica_restored:
            try:
                from horovod_tpu.ckpt import stats as ckpt_stats
                ckpt_stats.REPLICA_RESTORES.inc(len(replica_restored))
            except Exception:  # pragma: no cover - metrics must not kill
                pass
            flight_recorder.emit(
                "sharded_resync_replica",
                restored_old_ranks=sorted(
                    {r for _t, r in replica_restored}),
                segments=len(replica_restored), rank=int(st.rank))

    if isinstance(state, (ShardedParams, ShardedGrads)):
        # dead ranks' segments fall back to zeros unless a neighbor
        # replica offers the true bytes — for params prefer a
        # checkpoint restore when no replica covered the dead rank
        tag0 = "param" if isinstance(state, ShardedParams) else "grad"
        new_shards = []
        for gi, g_new in enumerate(new_spec.groups):
            _dt, _n, _s, old_padded = old_groups[gi]
            zfill = np.zeros((old_padded,), np.dtype(g_new.dtype))
            new_shards.append(regroup(state.shards[gi], gi, zfill,
                                      _rep("leaves", gi),
                                      tag=f"{tag0}/{gi}"))
        if isinstance(state, ShardedParams):
            new_state = ShardedParams(new_spec, state.treedef,
                                      tuple(new_shards))
            _set_shard_bytes("param_shards", new_state.shards,
                             new_spec.world)
        else:
            new_state = ShardedGrads(new_spec, tuple(new_shards))
            _set_shard_bytes("grad_shards", new_state.shards,
                             new_spec.world)
        _finish_replica_accounting()
        return new_state

    if isinstance(state, FlatAdamState):
        new_master, new_mu, new_nu = [], [], []
        for gi, g_new in enumerate(new_spec.groups):
            _dt, old_n, old_shard, old_padded = old_groups[gi]
            # master fill: the just-synced params (cast to f32) — a dead
            # rank's master segment is reconstructed exactly
            pfill = _np_pack_group(pleaves, GroupSpec(
                dtype=g_new.dtype, indices=g_new.indices,
                shapes=g_new.shapes, sizes=g_new.sizes, n=old_n,
                shard_elems=old_shard, padded=old_padded)
            ).astype(np.float32)
            zfill = np.zeros((old_padded,), np.float32)
            new_master.append(regroup(state.master[gi], gi, pfill,
                                      _rep("master", gi),
                                      tag=f"master/{gi}"))
            new_mu.append(regroup(state.mu[gi], gi, zfill,
                                  _rep("mu", gi), tag=f"mu/{gi}"))
            new_nu.append(regroup(state.nu[gi], gi, zfill,
                                  _rep("nu", gi), tag=f"nu/{gi}"))
        count = jnp.asarray(np.asarray(collectives.broadcast(
            np.array([int(state.count)], np.int64),
            root_rank)).reshape(-1)[0].astype(np.int32))
        new_state = FlatAdamState(
            spec=new_spec, count=count, master=tuple(new_master),
            mu=tuple(new_mu), nu=tuple(new_nu))
        _set_state_bytes((new_state.master, new_state.mu, new_state.nu),
                         new_spec.world)
        _finish_replica_accounting()
        return new_state

    # generic ShardedOptState: re-shard every array leaf of the inner
    # state by matching its length to the (unique) old group shard;
    # scalar leaves (step counts) broadcast from the root
    leaves, treedef = jax.tree_util.tree_flatten(state.inner)
    by_shard: dict = {}
    for gi, (_dt, _n, old_shard, _p) in enumerate(old_groups):
        by_shard.setdefault(old_shard, []).append(gi)
    new_leaves = []
    for li, leaf in enumerate(leaves):
        if not hasattr(leaf, "shape") or np.ndim(leaf) == 0:
            val = np.asarray(collectives.broadcast(
                np.asarray(leaf).reshape(1).astype(np.float64),
                root_rank)).reshape(-1)[0]
            new_leaves.append(jnp.asarray(val).astype(
                leaf.dtype if hasattr(leaf, "dtype") else np.float64))
            continue
        cand = by_shard.get(int(np.shape(leaf)[0]), [])
        if np.ndim(leaf) != 1 or len(cand) != 1:
            raise ValueError(
                "elastic resync of a generic sharded inner state needs "
                "unambiguous 1-D shard leaves (one dtype group per "
                "shard length); use sharded_adamw or a stateless inner "
                f"(got leaf shape {np.shape(leaf)})")
        gi = cand[0]
        _dt, _n, _s, old_padded = old_groups[gi]
        zfill = np.zeros((old_padded,), np.dtype(leaf.dtype))
        new_leaves.append(regroup(leaf, gi, zfill, _rep("leaves", li),
                                  tag=f"leaf/{li}"))
    new_inner = treedef.unflatten(new_leaves)
    new_state = ShardedOptState(spec=new_spec, inner=new_inner)
    _set_state_bytes(new_inner, new_spec.world)
    _finish_replica_accounting()
    return new_state
