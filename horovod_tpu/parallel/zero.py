"""ZeRO-1 sharded optimizer state over the Horovod data plane.

Horovod's data-parallel contract replicates optimizer state on every
worker. ZeRO stage-1 (Rajbhandari et al., 2020) keeps the same contract
— allreduced gradients into a wrapped optimizer — while sharding the
optimizer state 1/N ways, by decomposing the allreduce into

    reduce-scatter  ->  update on the local shard  ->  allgather

Same bytes on the wire as an allreduce (a ring allreduce IS a
reduce-scatter followed by an allgather), but each chip touches only
1/N of the optimizer state per step and holds only 1/N of it in HBM.

The gradient pytree is flattened into one flat buffer per dtype group
(reusing the PR-3 size-bucket policy: per-rank shard lengths are padded
up to ``bucket_elems`` of ``HOROVOD_FUSION_BUCKET_QUANTUM``, so shard
boundaries land on even per-rank splits AND every step reuses the same
O(#buckets) compiled programs — zero new compiles after warmup). The pad
region holds zeros, the reduction identity for sum/average, and is
sliced off before unpacking, so padded results bit-match unpadded ones.

Two entry points:

* :func:`sharded_update` — wraps any *elementwise* optax transformation
  (sgd, adam, adamw, lamb, ...) as an ``optax.GradientTransformation``
  whose state lives on shards. It keeps the optax delta contract: the
  inner update runs on gradient/param *shards* and the resulting update
  deltas are allgathered back into the original pytree, so
  ``optax.apply_updates(params, updates)`` computes ``p + delta`` with
  the exact same bits as the replicated path (elementwise inner
  transforms only; global-norm clipping must run *before* the wrapper).
  This is what ``hvd.DistributedOptimizer(...,
  shard_optimizer_states=True)`` returns.

* :func:`sharded_adamw` — step-level fused AdamW
  (``opt.apply(params, state, grads)``) keeping flat fp32 master
  weights + moments in the local shard and emitting updated params in
  the parameter dtype (bf16 master-weight training). Step-level because
  the delta contract would break fp32-master semantics: in bf16,
  ``p + (cast(master') - p) != cast(master')``. The per-shard pass runs
  as one fused Pallas kernel
  (:mod:`horovod_tpu.ops.pallas.fused_optimizer`) on TPU local shards,
  gated by ``HOROVOD_SHARDED_FUSED_KERNEL``.

Three call modes, mirroring :mod:`horovod_tpu.ops.collectives`:

* **In-jit under ``shard_map``** — ``lax.psum_scatter`` /
  ``lax.all_gather`` over the bound mesh axes; the local shard is this
  device's slice at ``lax.axis_index``.
* **Eager single-controller** — cached jitted programs over the global
  mesh: pack+reduce-scatter (stacked ``(W, shard)`` output,
  worker-sharded), update, allgather+unpack. Gradient leaves must be
  uniformly worker-stacked or uniformly replicated.
* **Eager multi-process** — host-packed flat buffers ride the enqueue
  runtime's named lanes (``sharded.grads.g<i>`` /
  ``sharded.params.g<i>``), so negotiation, the response cache and the
  timeline see stable per-phase tensor names.

``Compression`` composes on the wire: the flat gradient buffer is
compressed before the reduce-scatter and decompressed on the shard.

Elastic integration: a sharded state snapshot holds only the local
shard (1/N of the bytes per commit); on a membership reform
``elastic.ArrayState.sync`` detects sharded leaves and calls
:func:`resync` instead of broadcasting them (a broadcast would clobber
the distinct per-rank shards).
"""

from __future__ import annotations

import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import comms, flight_recorder
from horovod_tpu.compression import Compression
from horovod_tpu.core import basics, mesh as mesh_mod
from horovod_tpu.metrics import LATENCY_BUCKETS, registry as _metrics
from horovod_tpu.ops import collectives
from horovod_tpu.ops.pallas import fused_optimizer as fused_mod
from horovod_tpu.parallel import sparse as sparse_mod
from horovod_tpu.runtime.fusion_buffer import bucket_elems
from horovod_tpu.utils import compat
from horovod_tpu.utils import env as env_mod

_UPDATES = _metrics().counter(
    "horovod_sharded_updates_total",
    "Sharded (ZeRO-1) optimizer updates applied.")
_UPDATE_SECONDS = _metrics().histogram(
    "horovod_sharded_update_seconds",
    "Wall time of one sharded optimizer update (reduce-scatter + shard "
    "update + allgather).", buckets=LATENCY_BUCKETS)
_STATE_BYTES = _metrics().gauge(
    "horovod_sharded_state_bytes",
    "Optimizer-state bytes resident per chip under sharding (~1/N of "
    "the replicated footprint).")
_RS_BYTES = _metrics().counter(
    "horovod_sharded_reducescatter_bytes_total",
    "Flat gradient bytes entering the sharded reduce-scatter phase.")
_AG_BYTES = _metrics().counter(
    "horovod_sharded_allgather_bytes_total",
    "Flat update/param bytes entering the sharded allgather phase.")
_PROGRAM_BUILDS = _metrics().counter(
    "horovod_sharded_program_builds_total",
    "Compiled sharded-step programs built (steady state goes flat: "
    "bucket-stable shapes mean zero new compiles after warmup).")


# ---------------------------------------------------------------------------
# Flat layout spec
# ---------------------------------------------------------------------------

class GroupSpec(NamedTuple):
    """Flat layout of one same-dtype group of pytree leaves."""

    dtype: str        # np.dtype(...).str
    indices: tuple    # positions in the flattened leaf list
    shapes: tuple     # per-leaf shapes
    sizes: tuple      # per-leaf element counts
    n: int            # total real elements
    shard_elems: int  # per-rank shard length (bucket-padded)
    padded: int       # shard_elems * world


class ZeroSpec(NamedTuple):
    """Static description of a sharded flat layout. Registered as a
    static pytree node: it rides inside optimizer state without
    contributing leaves, so ``tree_map``/``jit``/``device_get`` all pass
    it through untouched (and jit caches key on it)."""

    groups: tuple     # of GroupSpec
    world: int
    rank: int         # -1 in traced (shard_map) mode: slice at axis_index
    num_leaves: int


jax.tree_util.register_static(ZeroSpec)


def _quantum_bytes(st) -> int:
    cfg = getattr(st, "config", None)
    return int(getattr(cfg, "fusion_bucket_quantum",
                       env_mod.DEFAULT_FUSION_BUCKET_QUANTUM_BYTES))


def build_spec(leaves, world: int, rank: int,
               quantum_bytes: int) -> ZeroSpec:
    """Group ``leaves`` by dtype and lay each group out as one flat
    buffer whose per-rank shard is a PR-3 size bucket (identity at or
    under ``quantum_bytes``, next power-of-two multiple above), so the
    padded total splits evenly into ``world`` bucket-stable shards."""
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        # .name, not .str: extension dtypes (bfloat16) stringify to a
        # raw void ('<V2') under .str and would not round-trip
        by_dtype.setdefault(np.dtype(leaf.dtype).name, []).append(i)
    groups = []
    for dts in sorted(by_dtype):
        idxs = by_dtype[dts]
        dt = np.dtype(dts)
        shapes = tuple(tuple(leaves[i].shape) for i in idxs)
        sizes = tuple(int(np.prod(s, dtype=np.int64)) for s in shapes)
        n = int(sum(sizes))
        per = -(-n // world)  # ceil
        shard = bucket_elems(per, dt.itemsize, quantum_bytes)
        groups.append(GroupSpec(
            dtype=dts, indices=tuple(idxs), shapes=shapes, sizes=sizes,
            n=n, shard_elems=shard, padded=shard * world))
    return ZeroSpec(groups=tuple(groups), world=int(world),
                    rank=int(rank), num_leaves=len(leaves))


def _pack_group(leaves, g: GroupSpec):
    """Flatten group leaves into one (padded,) vector; the pad holds
    zeros — the sum/average reduction identity (fusion_buffer.py)."""
    parts = [jnp.reshape(leaves[i], (-1,)) for i in g.indices]
    pad = g.padded - g.n
    if pad:
        parts.append(jnp.zeros((pad,), np.dtype(g.dtype)))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _pack_group_stacked(leaves, g: GroupSpec, world: int):
    """Per-worker pack: stacked (W, *shape) leaves -> (W, padded)."""
    parts = [jnp.reshape(leaves[i], (world, -1)) for i in g.indices]
    pad = g.padded - g.n
    if pad:
        parts.append(jnp.zeros((world, pad), np.dtype(g.dtype)))
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _np_pack_group(leaves, g: GroupSpec) -> np.ndarray:
    out = np.zeros((g.padded,), np.dtype(g.dtype))
    off = 0
    for i, size in zip(g.indices, g.sizes):
        out[off:off + size] = np.asarray(leaves[i]).reshape(-1)
        off += size
    return out


def _unpack_group(flat, g: GroupSpec, out: list) -> None:
    off = 0
    for i, shape, size in zip(g.indices, g.shapes, g.sizes):
        out[i] = jnp.reshape(flat[off:off + size], shape)
        off += size


def _bound_axes(axis_name=None) -> tuple:
    """Mesh axes bound in the current trace (empty outside shard_map)."""
    axes = axis_name if axis_name is not None else mesh_mod.GLOBAL_AXES
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    bound = []
    for a in axes:
        try:
            compat.axis_size(a)
        except NameError:
            continue
        bound.append(a)
    return tuple(bound)


def _check_dense(leaves) -> None:
    for leaf in leaves:
        if sparse_mod.is_sparse(leaf):
            raise ValueError(
                "shard_optimizer_states does not support SparseGrad "
                "leaves; pass sparse_as_dense=True (densify before the "
                "flat pack) or keep the replicated path for sparse "
                "models")


def _densify(leaves):
    return [sparse_mod.densify_leaf(g) if sparse_mod.is_sparse(g) else g
            for g in leaves]


def _mode(leaves, st) -> str:
    """'tracer' | 'local' (multi-process) | 'stacked' | 'replicated'."""
    if any(isinstance(x, jax.core.Tracer) for x in leaves):
        return "tracer"
    if collectives._multiprocess_world(st):
        return "local"
    stacked = [collectives._is_worker_stacked(collectives._to_plane(x))
               for x in leaves]
    if all(stacked):
        return "stacked"
    if not any(stacked):
        return "replicated"
    raise ValueError(
        "sharded update needs gradient leaves to be uniformly "
        "worker-stacked or uniformly replicated, got a mix")


def _emit_phase(op: str, phase: str, shard: int, nbytes: int, fn):
    """Flight-recorder bracket for one sharded data-plane phase
    (satellite: postmortems attribute stalls inside a sharded step to
    the reduce-scatter vs allgather phase, with shard index + bytes)."""
    flight_recorder.emit("op_dispatch", op=op, phase=phase,
                         shard=int(shard), bytes=int(nbytes))
    t0 = time.monotonic()
    out = fn()
    seconds = time.monotonic() - t0
    flight_recorder.emit("op_complete", op=op, phase=phase,
                         shard=int(shard), bytes=int(nbytes),
                         seconds=round(seconds, 6))
    # comms plane: the ZeRO reduce-scatter/allgather phases get their own
    # "zero" lane — end-to-end sharded-phase bandwidth, next to the wire
    # lane the bytes physically rode (docs/comms.md)
    comms.record(op, "zero", nbytes, seconds)
    return out


def _set_state_bytes(inner_state, world: int) -> None:
    total = 0
    for leaf in jax.tree_util.tree_leaves(inner_state):
        if not hasattr(leaf, "shape"):
            continue
        nbytes = int(np.prod(leaf.shape, dtype=np.int64)
                     * np.dtype(leaf.dtype).itemsize)
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == world:
            nbytes //= world  # stacked (W, shard): 1/W lives per chip
        total += nbytes
    _STATE_BYTES.set(total)
    from horovod_tpu import memory

    memory.tracker().set_bytes("optimizer_shards", total)


# ---------------------------------------------------------------------------
# Generic elementwise wrapper (optax delta contract)
# ---------------------------------------------------------------------------

class ShardedOptState(NamedTuple):
    """State of :func:`sharded_update`: the static layout spec plus the
    inner optimizer's state over the shard tree (one flat array per
    dtype group). Snapshots/checkpoints of this state hold only the
    local shard — 1/N of the replicated bytes."""

    spec: ZeroSpec
    inner: Any


def sharded_update(optimizer, *, average: bool = True,
                   compression=Compression.none, axis_name=None,
                   sparse_as_dense: bool = False):
    """Wrap an elementwise optax transformation with ZeRO-1 sharding.

    Returns an ``optax.GradientTransformationExtraArgs`` whose state is
    :class:`ShardedOptState`. The update reduce-scatters the flat
    gradient buffer, runs ``optimizer.update`` on the gradient/param
    *shards*, and allgathers the update deltas back into the original
    pytree — so the returned updates compose with
    ``optax.apply_updates`` exactly like the replicated path, bit for
    bit for elementwise inner transforms (SGD, per-element Adam math).

    Non-elementwise inner transforms (``clip_by_global_norm``,
    ``scale_by_trust_ratio``...) are NOT valid inside the wrapper: they
    would see only 1/N of the elements. Apply them to the gradients
    before this wrapper instead.
    """
    import optax

    progs: dict = {}

    def _prog(key, builder):
        fn = progs.get(key)
        if fn is None:
            _PROGRAM_BUILDS.inc()
            fn = builder()
            progs[key] = fn
        return fn

    # -- eager single-controller programs (bucket-keyed; built once per
    #    (mesh, spec) and reused every step: zero steady-state compiles)

    def _grads_to_shards_prog(mesh, spec, stacked: bool):
        def build():
            def f(leaves):
                outs = []
                for g in spec.groups:
                    dt = np.dtype(g.dtype)
                    if stacked:
                        flat = _pack_group_stacked(leaves, g, spec.world)
                        wire, ctx = compression.compress(flat)
                        r = (jnp.mean(wire, axis=0) if average
                             else jnp.sum(wire, axis=0))
                    else:
                        # replicated input: every worker holds the same
                        # grads, so average == copy and sum == x * W —
                        # the same short-circuit (and the same bits) as
                        # the replicated allreduce path.
                        flat = _pack_group(leaves, g)
                        wire, ctx = compression.compress(flat)
                        r = wire if average else wire * spec.world
                    r = compression.decompress(r, ctx)
                    outs.append(jnp.reshape(
                        r.astype(dt), (spec.world, g.shard_elems)))
                return tuple(outs)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(mesh))

        return _prog(("g2s", mesh, spec, stacked, average, compression),
                     build)

    def _params_to_shards_prog(mesh, spec):
        def build():
            def f(leaves):
                return tuple(
                    jnp.reshape(_pack_group(leaves, g),
                                (spec.world, g.shard_elems))
                    for g in spec.groups)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(mesh))

        return _prog(("p2s", mesh, spec), build)

    def _update_prog(mesh, spec):
        def build():
            def f(gshards, inner, pshards, extra):
                return optimizer.update(gshards, inner, pshards, **extra)

            return jax.jit(f)

        return _prog(("upd", mesh, spec), build)

    def _shards_to_updates_prog(mesh, spec):
        def build():
            def f(deltas):
                out = [None] * spec.num_leaves
                for g, d in zip(spec.groups, deltas):
                    _unpack_group(jnp.reshape(d, (g.padded,)), g, out)
                return tuple(out)

            return jax.jit(
                f, out_shardings=mesh_mod.replicated_sharding(mesh))

        return _prog(("s2u", mesh, spec), build)

    # -- shard extraction per mode ----------------------------------------

    def _tracer_shards(leaves, spec, axes):
        idx = lax.axis_index(tuple(axes))
        shards = []
        for g in spec.groups:
            flat = _pack_group(leaves, g)
            shards.append(lax.dynamic_slice(
                flat, (idx * g.shard_elems,), (g.shard_elems,)))
        return tuple(shards)

    def _local_shards(leaves, spec):
        return tuple(
            jnp.asarray(_np_pack_group(leaves, g)[
                spec.rank * g.shard_elems:(spec.rank + 1) * g.shard_elems])
            for g in spec.groups)

    # -- init --------------------------------------------------------------

    def init_fn(params):
        leaves, _ = jax.tree_util.tree_flatten(params)
        _check_dense(leaves)
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError(
                    "shard_optimizer_states under plain jit/pjit has no "
                    "mesh axis to shard over — call it under shard_map, "
                    "eagerly, or in multi-process mode")
            world = int(np.prod([compat.axis_size(a) for a in axes]))
            spec = build_spec(leaves, world, -1,
                              _quantum_bytes(basics._ensure_init()))
            shards = _tracer_shards(leaves, spec, axes)
            return ShardedOptState(spec, optimizer.init(shards))
        st = basics._ensure_init()
        spec = build_spec(leaves, st.size,
                          st.rank if collectives._multiprocess_world(st)
                          else 0,
                          _quantum_bytes(st))
        if collectives._multiprocess_world(st):
            shards = _local_shards(leaves, spec)
        else:
            shards = _params_to_shards_prog(st.mesh, spec)(leaves)
        inner = optimizer.init(shards)
        _set_state_bytes(inner, spec.world)
        return ShardedOptState(spec, inner)

    # -- update ------------------------------------------------------------

    def _update_tracer(leaves, state, pleaves, extra, axes):
        spec = state.spec
        gshards = []
        for g in spec.groups:
            flat = _pack_group(leaves, g)
            wire, ctx = compression.compress(flat)
            s = lax.psum_scatter(wire, tuple(axes), scatter_dimension=0,
                                 tiled=True)
            if average:
                s = s / spec.world
            gshards.append(compression.decompress(s, ctx)
                           .astype(np.dtype(g.dtype)))
        pshards = (_tracer_shards(pleaves, spec, axes)
                   if pleaves is not None else None)
        deltas, new_inner = optimizer.update(
            tuple(gshards), state.inner, pshards, **extra)
        out = [None] * spec.num_leaves
        for g, d in zip(spec.groups, deltas):
            full = lax.all_gather(d, tuple(axes), axis=0, tiled=True)
            _unpack_group(full, g, out)
        return tuple(out), ShardedOptState(spec, new_inner)

    def _update_single_controller(leaves, state, pleaves, extra, st,
                                  stacked: bool):
        spec = state.spec
        mesh = st.mesh
        rs_bytes = sum(g.padded * np.dtype(g.dtype).itemsize
                       for g in spec.groups)
        _RS_BYTES.inc(rs_bytes)
        gshards = _emit_phase(
            "reducescatter", "sharded_grads", spec.rank, rs_bytes,
            lambda: _grads_to_shards_prog(mesh, spec, stacked)(leaves))
        pshards = (_params_to_shards_prog(mesh, spec)(pleaves)
                   if pleaves is not None else None)
        deltas, new_inner = _update_prog(mesh, spec)(
            gshards, state.inner, pshards, extra)
        ag_bytes = sum(g.padded * np.dtype(np.dtype(g.dtype)).itemsize
                       for g in spec.groups)
        _AG_BYTES.inc(ag_bytes)
        updates = _emit_phase(
            "allgather", "sharded_updates", spec.rank, ag_bytes,
            lambda: _shards_to_updates_prog(mesh, spec)(deltas))
        return updates, ShardedOptState(spec, new_inner)

    def _update_multiprocess(leaves, state, pleaves, extra, st):
        from horovod_tpu.runtime.runtime import get_runtime

        spec = state.spec
        if not collectives._runtime_capable(st):
            raise NotImplementedError(
                "sharded update in a multi-process world needs the "
                "enqueue runtime (tpurun / HOROVOD_RANK env contract); "
                "for externally-initialized jax.distributed use the "
                "shard_map path")
        op_name = collectives._OP_NAMES[
            collectives.Average if average else collectives.Sum]
        handles = []
        for gi, g in enumerate(spec.groups):
            flat = _np_pack_group(leaves, g)
            wire, ctx = compression.compress(jnp.asarray(flat))
            nbytes = (wire.size * np.dtype(wire.dtype).itemsize)
            _RS_BYTES.inc(int(nbytes))
            flight_recorder.emit(
                "op_dispatch", op="reducescatter", phase="sharded_grads",
                shard=spec.rank, group=gi, bytes=int(nbytes))
            # stable per-group names: the negotiation response cache and
            # the timeline see the same tensor lane every step
            handles.append((gi, g, ctx, int(nbytes), time.monotonic(),
                            get_runtime().enqueue_reducescatter(
                                f"sharded.grads.g{gi}", wire,
                                reduce_op=op_name)))
        gshards = [None] * len(spec.groups)
        for gi, g, ctx, nbytes, t0, h in handles:
            out = compression.decompress(collectives.synchronize(h), ctx)
            seconds = time.monotonic() - t0
            flight_recorder.emit(
                "op_complete", op="reducescatter", phase="sharded_grads",
                shard=spec.rank, group=gi, seconds=round(seconds, 6))
            comms.record("reducescatter", "zero", nbytes, seconds,
                         world=spec.world)
            gshards[gi] = jnp.asarray(out).astype(np.dtype(g.dtype))
        pshards = (_local_shards(pleaves, spec)
                   if pleaves is not None else None)
        deltas, new_inner = optimizer.update(
            tuple(gshards), state.inner, pshards, **extra)
        ag_handles = []
        for gi, (g, d) in enumerate(zip(spec.groups, deltas)):
            nbytes = g.shard_elems * np.dtype(g.dtype).itemsize
            _AG_BYTES.inc(int(nbytes) * spec.world)
            flight_recorder.emit(
                "op_dispatch", op="allgather", phase="sharded_updates",
                shard=spec.rank, group=gi,
                bytes=int(nbytes) * spec.world)
            ag_handles.append((gi, g, int(nbytes) * spec.world,
                               time.monotonic(),
                               get_runtime().enqueue_allgather(
                                   f"sharded.updates.g{gi}",
                                   jnp.asarray(d))))
        out = [None] * spec.num_leaves
        for gi, g, nbytes, t0, h in ag_handles:
            full = jnp.asarray(collectives.synchronize(h))
            seconds = time.monotonic() - t0
            flight_recorder.emit(
                "op_complete", op="allgather", phase="sharded_updates",
                shard=spec.rank, group=gi, seconds=round(seconds, 6))
            comms.record("allgather", "zero", nbytes, seconds,
                         world=spec.world)
            _unpack_group(full, g, out)
        return tuple(out), ShardedOptState(spec, new_inner)

    def _integrity_check_leaves(leaves, st, mode):
        """Single-controller digest over the eager gradient leaves (the
        multi-process path is covered in band by the runtime's
        reduce-scatter digest instead — a caller-thread check there
        could diverge across ranks). Worker-stacked leaves attribute
        the non-finite row to its rank."""
        from horovod_tpu.integrity import digest as integ_digest

        if collectives._multiprocess_world(st):
            return
        if not integ_digest.cadence_due("zero.update"):
            return
        total = 0
        suspect = None
        bad_leaf = None
        for i, leaf in enumerate(leaves):
            if np.dtype(leaf.dtype).kind not in ("f", "V"):
                continue
            if mode == "stacked":
                counts = np.asarray(jnp.sum(
                    ~jnp.isfinite(jnp.reshape(leaf, (leaf.shape[0], -1))),
                    axis=1, dtype=jnp.int32))
                bad = np.nonzero(counts)[0]
                if bad.size and suspect is None:
                    suspect = int(bad[0])
                n = int(counts.sum())
            else:
                n = int(jnp.sum(~jnp.isfinite(leaf)))
            if n and bad_leaf is None:
                bad_leaf = i
            total += n
        integ_digest.verify_local(
            total, bucket="zero.grads",
            tensor=None if bad_leaf is None else f"leaf[{bad_leaf}]",
            suspect_rank=suspect)

    def update_fn(grads, state, params=None, **extra):
        if not isinstance(state, ShardedOptState):
            raise TypeError(
                "sharded_update state must be ShardedOptState (was this "
                "optimizer initialized with shard_optimizer_states?)")
        leaves, treedef = jax.tree_util.tree_flatten(
            grads, is_leaf=sparse_mod.is_sparse)
        if sparse_as_dense:
            leaves = _densify(leaves)
        _check_dense(leaves)
        spec = state.spec
        if len(leaves) != spec.num_leaves:
            raise ValueError(
                f"gradient tree has {len(leaves)} leaves but the sharded "
                f"state was built for {spec.num_leaves}")
        pleaves = None
        if params is not None:
            pleaves = jax.tree_util.tree_flatten(params)[0]
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError(
                    "sharded update traced without a bound mesh axis — "
                    "use shard_map (or run eagerly)")
            out, new_state = _update_tracer(leaves, state, pleaves,
                                            extra, axes)
            return treedef.unflatten(out), new_state
        st = basics._ensure_init()
        if spec.world != st.size:
            raise ValueError(
                f"sharded state was built for world {spec.world} but the "
                f"current world is {st.size}; re-init (elastic re-forms "
                "go through elastic.ArrayState.sync / zero.resync)")
        mode = _mode(leaves, st)
        _integrity_check_leaves(leaves, st, mode)
        t0 = time.monotonic()
        if mode == "local":
            out, new_state = _update_multiprocess(leaves, state, pleaves,
                                                  extra, st)
        else:
            out, new_state = _update_single_controller(
                leaves, state, pleaves, extra, st, mode == "stacked")
        _UPDATES.inc()
        _UPDATE_SECONDS.observe(time.monotonic() - t0)
        return treedef.unflatten(out), new_state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


# ---------------------------------------------------------------------------
# Fused flat AdamW (fp32 master shards, step-level API)
# ---------------------------------------------------------------------------

class FlatAdamState(NamedTuple):
    """State of :func:`sharded_adamw`: per-dtype-group flat fp32 master
    weights and Adam moments, local shard only (~12 bytes/param / N per
    chip vs 12 replicated)."""

    spec: ZeroSpec
    count: Any
    master: Any  # tuple per group, f32 (shard,) / (W, shard) / traced
    mu: Any
    nu: Any


class ShardedAdamW(NamedTuple):
    """Step-level sharded fused AdamW: ``apply(params, state, grads) ->
    (new_params, new_state)`` (same shape of API as
    ``ops.pallas.fused_adamw`` — the delta contract would break fp32
    master-weight semantics in bf16)."""

    init: callable
    apply: callable


def sharded_adamw(learning_rate: float, b1: float = 0.9,
                  b2: float = 0.999, eps: float = 1e-8,
                  weight_decay: float = 1e-4, *, average: bool = True,
                  compression=Compression.none,
                  axis_name=None) -> ShardedAdamW:
    """ZeRO-1 fused AdamW: reduce-scatter grads, one fused Pallas pass
    over the local fp32 master/moment shards
    (:mod:`horovod_tpu.ops.pallas.fused_optimizer`, gated by
    ``HOROVOD_SHARDED_FUSED_KERNEL``), allgather the updated params
    back in the parameter dtype."""
    import optax

    progs: dict = {}

    def _prog(key, builder):
        fn = progs.get(key)
        if fn is None:
            _PROGRAM_BUILDS.inc()
            fn = builder()
            progs[key] = fn
        return fn

    def _scalars(count):
        t = count.astype(jnp.float32)
        return jnp.stack([
            jnp.float32(b1), jnp.float32(b2),
            1.0 / (1.0 - jnp.float32(b1) ** t),
            1.0 / (1.0 - jnp.float32(b2) ** t),
            jnp.float32(learning_rate), jnp.float32(weight_decay)])

    def _master_prog(mesh, spec):
        def build():
            def f(leaves):
                return tuple(
                    jnp.reshape(_pack_group(leaves, g),
                                (spec.world, g.shard_elems))
                    .astype(jnp.float32)
                    for g in spec.groups)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(mesh))

        return _prog(("master", mesh, spec), build)

    def _apply_prog(mesh, spec):
        def build():
            def f(scalars, master, mu, nu, gshards):
                ps, ws, ms, vs = [], [], [], []
                for g, w, m, v, gr in zip(spec.groups, master, mu, nu,
                                          gshards):
                    p2, w2, m2, v2 = fused_mod.flat_adamw_shard(
                        w, m, v, gr, scalars, eps=eps,
                        out_dtype=np.dtype(g.dtype))
                    ps.append(p2)
                    ws.append(w2)
                    ms.append(m2)
                    vs.append(v2)
                return tuple(ps), tuple(ws), tuple(ms), tuple(vs)

            return jax.jit(f)

        return _prog(("apply", mesh, spec), build)

    def _gather_prog(mesh, spec):
        def build():
            def f(pshards):
                out = [None] * spec.num_leaves
                for g, p in zip(spec.groups, pshards):
                    _unpack_group(jnp.reshape(p, (g.padded,)), g, out)
                return tuple(out)

            return jax.jit(
                f, out_shardings=mesh_mod.replicated_sharding(mesh))

        return _prog(("gather", mesh, spec), build)

    def init(params):
        leaves, _ = jax.tree_util.tree_flatten(params)
        _check_dense(leaves)
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError(
                    "sharded_adamw under plain jit/pjit has no mesh axis "
                    "to shard over — use shard_map, eager, or "
                    "multi-process mode")
            world = int(np.prod([compat.axis_size(a) for a in axes]))
            spec = build_spec(leaves, world, -1,
                              _quantum_bytes(basics._ensure_init()))
            idx = lax.axis_index(tuple(axes))
            master = tuple(
                lax.dynamic_slice(_pack_group(leaves, g),
                                  (idx * g.shard_elems,),
                                  (g.shard_elems,)).astype(jnp.float32)
                for g in spec.groups)
        else:
            st = basics._ensure_init()
            mp = collectives._multiprocess_world(st)
            spec = build_spec(leaves, st.size, st.rank if mp else 0,
                              _quantum_bytes(st))
            if mp:
                master = tuple(
                    jnp.asarray(_np_pack_group(leaves, g)[
                        spec.rank * g.shard_elems:
                        (spec.rank + 1) * g.shard_elems])
                    .astype(jnp.float32)
                    for g in spec.groups)
            else:
                master = _master_prog(st.mesh, spec)(leaves)
        zeros = tuple(jnp.zeros_like(w) for w in master)
        state = FlatAdamState(spec=spec, count=jnp.zeros([], jnp.int32),
                              master=master, mu=zeros,
                              nu=tuple(jnp.zeros_like(w) for w in master))
        if not any(isinstance(x, jax.core.Tracer) for x in leaves):
            _set_state_bytes((state.master, state.mu, state.nu),
                             spec.world)
        return state

    def _grad_shards_eager(leaves, spec, st, stacked):
        # one cached program: pack + reduce-scatter (see sharded_update)
        key = ("fg2s", st.mesh, spec, stacked)

        def build():
            def f(lvs):
                outs = []
                for g in spec.groups:
                    if stacked:
                        flat = _pack_group_stacked(lvs, g, spec.world)
                        wire, ctx = compression.compress(flat)
                        r = (jnp.mean(wire, axis=0) if average
                             else jnp.sum(wire, axis=0))
                    else:
                        flat = _pack_group(lvs, g)
                        wire, ctx = compression.compress(flat)
                        r = wire if average else wire * spec.world
                    r = compression.decompress(r, ctx)
                    outs.append(jnp.reshape(
                        r.astype(np.dtype(g.dtype)),
                        (spec.world, g.shard_elems)))
                return tuple(outs)

            return jax.jit(
                f, out_shardings=mesh_mod.worker_sharding(st.mesh))

        return _prog(key, build)(leaves)

    def apply(params, state, grads):
        spec = state.spec
        gleaves, treedef = jax.tree_util.tree_flatten(grads)
        _check_dense(gleaves)
        if len(gleaves) != spec.num_leaves:
            raise ValueError(
                f"gradient tree has {len(gleaves)} leaves but the "
                f"sharded state was built for {spec.num_leaves}")
        count = optax.safe_int32_increment(state.count)
        scalars = _scalars(count)
        if any(isinstance(x, jax.core.Tracer) for x in gleaves):
            axes = _bound_axes(axis_name)
            if not axes:
                raise ValueError("sharded_adamw traced without a bound "
                                 "mesh axis — use shard_map")
            ps, ws, ms, vs = [], [], [], []
            for g, w, m, v in zip(spec.groups, state.master, state.mu,
                                  state.nu):
                flat = _pack_group(gleaves, g)
                wire, ctx = compression.compress(flat)
                s = lax.psum_scatter(wire, tuple(axes),
                                     scatter_dimension=0, tiled=True)
                if average:
                    s = s / spec.world
                gr = compression.decompress(s, ctx)
                p2, w2, m2, v2 = fused_mod.flat_adamw_shard(
                    w, m, v, gr, scalars, eps=eps,
                    out_dtype=np.dtype(g.dtype))
                ps.append(p2)
                ws.append(w2)
                ms.append(m2)
                vs.append(v2)
            out = [None] * spec.num_leaves
            for g, p in zip(spec.groups, ps):
                full = lax.all_gather(p, tuple(axes), axis=0, tiled=True)
                _unpack_group(full, g, out)
            pt = jax.tree_util.tree_flatten(params)[1]
            return pt.unflatten(out), FlatAdamState(
                spec, count, tuple(ws), tuple(ms), tuple(vs))
        st = basics._ensure_init()
        if spec.world != st.size:
            raise ValueError(
                f"sharded state was built for world {spec.world} but the "
                f"current world is {st.size}")
        t0 = time.monotonic()
        mode = _mode(gleaves, st)
        rs_bytes = sum(g.padded * np.dtype(g.dtype).itemsize
                       for g in spec.groups)
        if mode == "local":
            from horovod_tpu.runtime.runtime import get_runtime

            if not collectives._runtime_capable(st):
                raise NotImplementedError(
                    "sharded_adamw in a multi-process world needs the "
                    "enqueue runtime (tpurun / HOROVOD_RANK)")
            op_name = collectives._OP_NAMES[
                collectives.Average if average else collectives.Sum]
            handles = []
            for gi, g in enumerate(spec.groups):
                flat = _np_pack_group(gleaves, g)
                wire, ctx = compression.compress(jnp.asarray(flat))
                _RS_BYTES.inc(int(wire.size
                                  * np.dtype(wire.dtype).itemsize))
                flight_recorder.emit(
                    "op_dispatch", op="reducescatter",
                    phase="sharded_grads", shard=spec.rank, group=gi,
                    bytes=int(wire.size * np.dtype(wire.dtype).itemsize))
                handles.append((gi, g, ctx, time.monotonic(),
                                get_runtime().enqueue_reducescatter(
                                    f"sharded.adamw.grads.g{gi}", wire,
                                    reduce_op=op_name)))
            gshards = [None] * len(spec.groups)
            for gi, g, ctx, ht0, h in handles:
                gr = compression.decompress(collectives.synchronize(h),
                                            ctx)
                flight_recorder.emit(
                    "op_complete", op="reducescatter",
                    phase="sharded_grads", shard=spec.rank, group=gi,
                    seconds=round(time.monotonic() - ht0, 6))
                gshards[gi] = jnp.asarray(gr).astype(np.dtype(g.dtype))
            ps, ws, ms, vs = [], [], [], []
            for g, w, m, v, gr in zip(spec.groups, state.master,
                                      state.mu, state.nu, gshards):
                p2, w2, m2, v2 = fused_mod.flat_adamw_shard(
                    w, m, v, gr, scalars, eps=eps,
                    out_dtype=np.dtype(g.dtype))
                ps.append(p2)
                ws.append(w2)
                ms.append(m2)
                vs.append(v2)
            out = [None] * spec.num_leaves
            ag_handles = []
            for gi, (g, p) in enumerate(zip(spec.groups, ps)):
                nbytes = g.padded * np.dtype(g.dtype).itemsize
                _AG_BYTES.inc(int(nbytes))
                flight_recorder.emit(
                    "op_dispatch", op="allgather",
                    phase="sharded_params", shard=spec.rank, group=gi,
                    bytes=int(nbytes))
                ag_handles.append((gi, g, time.monotonic(),
                                   get_runtime().enqueue_allgather(
                                       f"sharded.adamw.params.g{gi}",
                                       jnp.asarray(p))))
            for gi, g, ht0, h in ag_handles:
                full = jnp.asarray(collectives.synchronize(h))
                flight_recorder.emit(
                    "op_complete", op="allgather",
                    phase="sharded_params", shard=spec.rank, group=gi,
                    seconds=round(time.monotonic() - ht0, 6))
                _unpack_group(full, g, out)
        else:
            stacked = mode == "stacked"
            _RS_BYTES.inc(rs_bytes)
            gshards = _emit_phase(
                "reducescatter", "sharded_grads", spec.rank, rs_bytes,
                lambda: _grad_shards_eager(gleaves, spec, st, stacked))
            ps, ws, ms, vs = _apply_prog(st.mesh, spec)(
                scalars, state.master, state.mu, state.nu, gshards)
            ag_bytes = sum(g.padded * np.dtype(g.dtype).itemsize
                           for g in spec.groups)
            _AG_BYTES.inc(ag_bytes)
            out = _emit_phase(
                "allgather", "sharded_params", spec.rank, ag_bytes,
                lambda: _gather_prog(st.mesh, spec)(ps))
        _UPDATES.inc()
        _UPDATE_SECONDS.observe(time.monotonic() - t0)
        pt = jax.tree_util.tree_flatten(params)[1]
        return pt.unflatten(list(out)), FlatAdamState(
            spec, count, tuple(ws), tuple(ms), tuple(vs))

    return ShardedAdamW(init=init, apply=apply)


# ---------------------------------------------------------------------------
# Elastic integration: shard-aware sync after a membership reform
# ---------------------------------------------------------------------------

def is_sharded_state(x) -> bool:
    """True for optimizer-state leaves that hold per-rank shards —
    ``elastic.ArrayState.sync`` must NOT broadcast these (rank 0's shard
    would clobber every other rank's); it calls :func:`resync`."""
    return isinstance(x, (ShardedOptState, FlatAdamState))


def layout_of(state) -> dict:
    """JSON-serializable shard layout of a sharded state — recorded in
    checkpoint manifests so restore can re-flatten/re-scatter into a
    different world size (``from_full_buffers``)."""
    spec = state.spec
    return {
        "kind": ("flat_adamw" if isinstance(state, FlatAdamState)
                 else "generic"),
        "world": int(spec.world),
        "groups": [[g.dtype, int(g.n), int(g.shard_elems), int(g.padded)]
                   for g in spec.groups],
    }


def export_shard_arrays(state) -> dict:
    """Host-resident copies of a sharded state's local arrays, in a
    stable named layout — the unit the checkpoint writer serializes and
    the neighbor-replica exchange ships. Parallel to
    :func:`from_full_buffers` / the resync replica path."""
    if isinstance(state, FlatAdamState):
        return {"kind": "flat_adamw",
                "count": np.asarray(state.count),
                "master": [np.asarray(m) for m in state.master],
                "mu": [np.asarray(m) for m in state.mu],
                "nu": [np.asarray(m) for m in state.nu]}
    leaves, _ = jax.tree_util.tree_flatten(state.inner)
    return {"kind": "generic",
            "leaves": [np.asarray(x) for x in leaves]}


def _slice_new_shard(full_old: np.ndarray, old_n: int, g_new: GroupSpec,
                     new_rank: int, dtype) -> jnp.ndarray:
    return _reshard(full_old, GroupSpec(
        dtype=g_new.dtype, indices=(), shapes=(), sizes=(), n=old_n,
        shard_elems=0, padded=full_old.shape[0]), g_new, new_rank, dtype)


def from_full_buffers(target, full: dict, old_groups):
    """Rebuild a sharded state from FULL old flat buffers (one per
    dtype group), slicing this rank's shard under ``target``'s (new)
    layout — the disk-restore analogue of :func:`resync`, with the
    gathers replaced by buffers read from shard files.

    ``target`` supplies the new spec (typically a freshly-initialized
    state); ``full`` is the named-array dict shape of
    :func:`export_shard_arrays` but with *full* (old_padded,) buffers;
    ``old_groups`` is the manifest's ``groups`` layout list."""
    spec = target.spec
    if len(old_groups) != len(spec.groups):
        raise ValueError(
            "checkpoint restore: parameter structure changed (dtype "
            "group count mismatch between manifest and target)")
    if isinstance(target, FlatAdamState):
        master, mu, nu = [], [], []
        for gi, g_new in enumerate(spec.groups):
            _dt, old_n, _s, _p = old_groups[gi]
            master.append(_slice_new_shard(
                np.asarray(full["master"][gi]), old_n, g_new, spec.rank,
                np.float32))
            mu.append(_slice_new_shard(
                np.asarray(full["mu"][gi]), old_n, g_new, spec.rank,
                np.float32))
            nu.append(_slice_new_shard(
                np.asarray(full["nu"][gi]), old_n, g_new, spec.rank,
                np.float32))
        count = jnp.asarray(np.asarray(full["count"]).astype(np.int32))
        new_state = FlatAdamState(spec=spec, count=count,
                                  master=tuple(master), mu=tuple(mu),
                                  nu=tuple(nu))
        _set_state_bytes((new_state.master, new_state.mu, new_state.nu),
                         spec.world)
        return new_state
    leaves, treedef = jax.tree_util.tree_flatten(target.inner)
    by_shard: dict = {}
    for gi, g in enumerate(spec.groups):
        by_shard.setdefault(int(g.shard_elems), []).append(gi)
    new_leaves = []
    for li, leaf in enumerate(leaves):
        stored = full["leaves"][li]
        if not hasattr(leaf, "shape") or np.ndim(leaf) == 0:
            val = np.asarray(stored).reshape(-1)[0]
            new_leaves.append(jnp.asarray(val).astype(
                leaf.dtype if hasattr(leaf, "dtype") else np.float64))
            continue
        cand = by_shard.get(int(np.shape(leaf)[0]), [])
        if np.ndim(leaf) != 1 or len(cand) != 1:
            raise ValueError(
                "checkpoint restore of a generic sharded inner state "
                "needs unambiguous 1-D shard leaves (one dtype group "
                f"per shard length); got leaf shape {np.shape(leaf)}")
        gi = cand[0]
        _dt, old_n, _s, _p = old_groups[gi]
        new_leaves.append(_slice_new_shard(
            np.asarray(stored), old_n, spec.groups[gi], spec.rank,
            leaf.dtype))
    new_inner = treedef.unflatten(new_leaves)
    new_state = ShardedOptState(spec=spec, inner=new_inner)
    _set_state_bytes(new_inner, spec.world)
    return new_state


def _gather_old_segments(local: np.ndarray, old_rank: int,
                         old_world: int, old_shard: int,
                         fill: np.ndarray, replica_rank: int = -1,
                         replica_local=None):
    """Rebuild the full old flat buffer from surviving shards: allgather
    (length, old_rank, shard) from every current rank, place each
    surviving old rank's segment, and leave ``fill`` in segments whose
    owner died. First claim wins — survivors occupy the lowest new
    ranks, so a fresh joiner can never shadow a survivor's segment.

    A second gather round collects neighbor REPLICAS
    (:mod:`horovod_tpu.ckpt.replica`): a survivor holding the dead
    rank's shard bytes contributes them, so the dead segment gets its
    true last-commit values instead of ``fill``. Every rank joins both
    rounds (collective uniformity) — ranks with nothing to offer send a
    one-element dummy tagged rank -1. Returns ``(full,
    replica_restored_ranks)``."""
    lens = np.asarray(collectives.allgather(
        np.array([local.shape[0]], np.int64))).reshape(-1)
    ranks = np.asarray(collectives.allgather(
        np.array([old_rank], np.int64))).reshape(-1)
    cat = np.asarray(collectives.allgather(np.ascontiguousarray(local)))
    full = np.array(fill, copy=True)
    claimed = set()
    off = 0
    for j in range(len(ranks)):
        ln = int(lens[j])
        r = int(ranks[j])
        seg = cat[off:off + ln]
        off += ln
        if 0 <= r < old_world and ln == old_shard and r not in claimed:
            full[r * old_shard:(r + 1) * old_shard] = seg
            claimed.add(r)
    rep = (np.zeros((1,), local.dtype) if replica_local is None
           else np.ascontiguousarray(
               np.asarray(replica_local).reshape(-1).astype(
                   local.dtype, copy=False)))
    rlens = np.asarray(collectives.allgather(
        np.array([rep.shape[0]], np.int64))).reshape(-1)
    rranks = np.asarray(collectives.allgather(
        np.array([replica_rank if replica_local is not None else -1],
                 np.int64))).reshape(-1)
    rcat = np.asarray(collectives.allgather(rep))
    replica_restored = set()
    off = 0
    for j in range(len(rranks)):
        ln = int(rlens[j])
        r = int(rranks[j])
        seg = rcat[off:off + ln]
        off += ln
        if 0 <= r < old_world and ln == old_shard and r not in claimed:
            full[r * old_shard:(r + 1) * old_shard] = seg
            claimed.add(r)
            replica_restored.add(r)
    return full, replica_restored


def _reshard(full_old: np.ndarray, g_old: GroupSpec, g_new: GroupSpec,
             new_rank: int, dtype) -> jnp.ndarray:
    real = full_old[:g_old.n]
    flat = np.zeros((g_new.padded,), np.dtype(dtype))
    flat[:g_new.n] = real
    return jnp.asarray(
        flat[new_rank * g_new.shard_elems:
             (new_rank + 1) * g_new.shard_elems])


def _resync_needed(spec: ZeroSpec, st) -> bool:
    """Collective-uniform decision: a rank-local layout mismatch on ANY
    rank re-shards on ALL ranks (a survivor keeping its old rank must
    still join the allgathers of a renumbered peer)."""
    local = int(spec.world != st.size or spec.rank != st.rank)
    if not collectives._multiprocess_world(st):
        return bool(local)
    total = np.asarray(collectives.allreduce(
        np.array([local], np.int32), op=collectives.Sum))
    return int(total.reshape(-1)[0]) > 0


def resync(state, params, root_rank: int = 0, replica=None):
    """Re-shard a sharded optimizer state after an elastic membership
    reform: allgather the surviving old shards, rebuild the full flat
    buffers (dead ranks' segments fall back to the neutral value —
    zeros for moments, the current params for fp32 masters; exact for
    stateless inners like SGD), and slice the new world's shard.

    ``replica`` — ``(src_old_rank, exported_arrays)`` from
    ``horovod_tpu.ckpt.replica.lookup`` when this rank holds a neighbor
    replica of a (possibly dead) old rank's shard. A second gather
    round offers those bytes to every rank, so a dead rank's moment
    segments restore to their true last-commit values instead of the
    neutral fill. Ranks without a replica pass None and still join the
    round (collective uniformity).

    ``params`` must already be synced (ArrayState.sync broadcasts
    params before the optimizer tree). No-op when the layout still
    matches on every rank."""
    from horovod_tpu.elastic.state import broadcast_object_wire

    st = basics._ensure_init()
    spec = state.spec
    if not _resync_needed(spec, st):
        return state
    if not collectives._multiprocess_world(st):
        raise ValueError(
            "sharded-state resync needs a multi-process world (a "
            "single-controller mesh cannot change size under elastic); "
            f"state layout was world={spec.world} rank={spec.rank}, "
            f"current world={st.size} rank={st.rank}")
    pleaves, _ = jax.tree_util.tree_flatten(params)
    new_spec = build_spec(pleaves, st.size, st.rank, _quantum_bytes(st))
    # survivors (incl. the root) share the authoritative old layout;
    # fresh joiners adopt it so everyone parses the gathers identically
    old_world, old_groups = broadcast_object_wire(
        (spec.world,
         tuple((g.dtype, g.n, g.shard_elems, g.padded)
               for g in spec.groups)),
        root_rank)
    if len(old_groups) != len(new_spec.groups):
        raise ValueError(
            "elastic resync: parameter structure changed across the "
            "reform (dtype group count mismatch)")
    flight_recorder.emit("sharded_resync", old_world=int(old_world),
                         new_world=int(st.size), rank=int(st.rank))
    rep_rank = -1
    rep_entries = None
    want_kind = ("flat_adamw" if isinstance(state, FlatAdamState)
                 else "generic")
    if replica is not None:
        rep_rank, rep_entries = replica
        if (not isinstance(rep_entries, dict)
                or rep_entries.get("kind") != want_kind):
            rep_rank, rep_entries = -1, None
    replica_restored: set = set()  # (component, old_rank) placements

    def regroup(leaf, gi, fill_np, rep_arr=None, tag=""):
        _dt, old_n, old_shard, old_padded = old_groups[gi]
        g_new = new_spec.groups[gi]
        g_old = GroupSpec(dtype=_dt, indices=(), shapes=(), sizes=(),
                          n=old_n, shard_elems=old_shard,
                          padded=old_padded)
        local = np.asarray(leaf).reshape(-1)
        full, from_replica = _gather_old_segments(
            local, spec.rank, old_world, old_shard, fill_np,
            replica_rank=(rep_rank if rep_arr is not None else -1),
            replica_local=rep_arr)
        replica_restored.update((tag, r) for r in from_replica)
        return _reshard(full, g_old, g_new, st.rank, leaf.dtype)

    def _rep(component, idx):
        if rep_entries is None:
            return None
        try:
            arr = rep_entries[component][idx]
        except (KeyError, IndexError, TypeError):
            return None
        return None if arr is None else np.asarray(arr)

    def _finish_replica_accounting():
        if replica_restored:
            try:
                from horovod_tpu.ckpt import stats as ckpt_stats
                ckpt_stats.REPLICA_RESTORES.inc(len(replica_restored))
            except Exception:  # pragma: no cover - metrics must not kill
                pass
            flight_recorder.emit(
                "sharded_resync_replica",
                restored_old_ranks=sorted(
                    {r for _t, r in replica_restored}),
                segments=len(replica_restored), rank=int(st.rank))

    if isinstance(state, FlatAdamState):
        new_master, new_mu, new_nu = [], [], []
        for gi, g_new in enumerate(new_spec.groups):
            _dt, old_n, old_shard, old_padded = old_groups[gi]
            # master fill: the just-synced params (cast to f32) — a dead
            # rank's master segment is reconstructed exactly
            pfill = _np_pack_group(pleaves, GroupSpec(
                dtype=g_new.dtype, indices=g_new.indices,
                shapes=g_new.shapes, sizes=g_new.sizes, n=old_n,
                shard_elems=old_shard, padded=old_padded)
            ).astype(np.float32)
            zfill = np.zeros((old_padded,), np.float32)
            new_master.append(regroup(state.master[gi], gi, pfill,
                                      _rep("master", gi),
                                      tag=f"master/{gi}"))
            new_mu.append(regroup(state.mu[gi], gi, zfill,
                                  _rep("mu", gi), tag=f"mu/{gi}"))
            new_nu.append(regroup(state.nu[gi], gi, zfill,
                                  _rep("nu", gi), tag=f"nu/{gi}"))
        count = jnp.asarray(np.asarray(collectives.broadcast(
            np.array([int(state.count)], np.int64),
            root_rank)).reshape(-1)[0].astype(np.int32))
        new_state = FlatAdamState(
            spec=new_spec, count=count, master=tuple(new_master),
            mu=tuple(new_mu), nu=tuple(new_nu))
        _set_state_bytes((new_state.master, new_state.mu, new_state.nu),
                         new_spec.world)
        _finish_replica_accounting()
        return new_state

    # generic ShardedOptState: re-shard every array leaf of the inner
    # state by matching its length to the (unique) old group shard;
    # scalar leaves (step counts) broadcast from the root
    leaves, treedef = jax.tree_util.tree_flatten(state.inner)
    by_shard: dict = {}
    for gi, (_dt, _n, old_shard, _p) in enumerate(old_groups):
        by_shard.setdefault(old_shard, []).append(gi)
    new_leaves = []
    for li, leaf in enumerate(leaves):
        if not hasattr(leaf, "shape") or np.ndim(leaf) == 0:
            val = np.asarray(collectives.broadcast(
                np.asarray(leaf).reshape(1).astype(np.float64),
                root_rank)).reshape(-1)[0]
            new_leaves.append(jnp.asarray(val).astype(
                leaf.dtype if hasattr(leaf, "dtype") else np.float64))
            continue
        cand = by_shard.get(int(np.shape(leaf)[0]), [])
        if np.ndim(leaf) != 1 or len(cand) != 1:
            raise ValueError(
                "elastic resync of a generic sharded inner state needs "
                "unambiguous 1-D shard leaves (one dtype group per "
                "shard length); use sharded_adamw or a stateless inner "
                f"(got leaf shape {np.shape(leaf)})")
        gi = cand[0]
        _dt, _n, _s, old_padded = old_groups[gi]
        zfill = np.zeros((old_padded,), np.dtype(leaf.dtype))
        new_leaves.append(regroup(leaf, gi, zfill, _rep("leaves", li),
                                  tag=f"leaf/{li}"))
    new_inner = treedef.unflatten(new_leaves)
    new_state = ShardedOptState(spec=new_spec, inner=new_inner)
    _set_state_bytes(new_inner, new_spec.world)
    _finish_replica_accounting()
    return new_state
