"""Step-level performance introspection: phase attribution, comm-overlap
accounting, rolling MFU, and the merged cross-rank trace.

Metrics (metrics.py) answer "what are my cumulative rates", the timeline
(timeline.py) answers "what happened to tensor X", and the flight
recorder (flight_recorder.py) answers "what was in flight when we died".
This module answers the live performance question none of them do: *per
training step*, how much wall time was host/input work, compute, exposed
collective time, and optimizer work — and how much collective time was
hidden behind other in-flight work. That is exactly the measurement the
gradient/backward overlap campaign (ROADMAP item 5, acceptance ">70% of
allreduce bytes overlapped") needs before any overlap can be attempted,
and the objective signal the autotuner reboot (ROADMAP item 2) optimizes.

Mechanics
---------

``hvd.profiler.step()`` brackets one training step.  At the boundaries
the profiler diffs cheap cumulative accumulators rather than tracing
anything:

* **exposed_comm** — the ``horovod_handle_wait_seconds`` sum (caller
  time actually blocked in ``RuntimeHandle.wait()``) diffed across the
  step, clamped to the step wall time;
* **host** / **optimizer** — accumulated by ``annotate("host")`` /
  ``annotate("optimizer")`` context managers (``DistributedOptimizer``
  annotates its inner update automatically on the eager path);
* **compute** — the remainder, so the four phases sum to the step wall
  time by construction.

Independently, the executor's comm clock (``executor.comm_totals()``)
splits every collective's lifetime into dispatch-busy, a pipeline
overlap window, and drain-busy; the **comm-hidden fraction** is
``1 − exposed ÷ total`` over the step (plus a bytes-weighted variant).
At pipeline depth 1 the overlap window is empty — a synchronous
allreduce reports ~0; at depth ≥ 2 the window of bin k contains bin
k+1's whole dispatch, so overlap shows up as a positive fraction.

``set_flops_per_step()`` (wired by bench.py, which knows model FLOPs and
the per-chip peak) turns step wall time into a rolling in-process MFU.

Every rank with profiling enabled dumps ``profile-rank-N.json`` — the
last ``HOROVOD_PROFILE_HISTORY`` step breakdowns plus Chrome-trace step
markers and a slice of flight-recorder events — into
``HOROVOD_PROFILE_DIR`` and ships a copy to the launcher's rendezvous
store (scope ``profile``).  ``tpurun --profile-dir`` harvests the dumps,
merges them with the per-rank runtime timelines (and any
``jax.profiler`` device traces under the directory) onto one clock using
the flight recorder's ``/_time`` offset estimate, and prints a
cross-rank step-time report naming the slowest phase and rank.

Knobs: ``HOROVOD_PROFILE`` (enable), ``HOROVOD_PROFILE_DIR`` (dump/
harvest directory; implies enable), ``HOROVOD_PROFILE_HISTORY`` (step
ring size, default 64), ``HOROVOD_PROFILE_JAX`` (also capture a
``jax.profiler`` device trace into the profile dir).
"""

from __future__ import annotations

import glob
import json
import os
import socket
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from horovod_tpu import flight_recorder
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import (DEFAULT_PROFILE_HISTORY, HOROVOD_PROFILE,
                                   HOROVOD_PROFILE_DIR,
                                   HOROVOD_PROFILE_HISTORY,
                                   HOROVOD_PROFILE_JAX, _get_bool, _get_int)

SCHEMA = "horovod-profiler-v1"
RENDEZVOUS_SCOPE = "profile"
DUMP_PREFIX = "profile-rank-"
MERGED_TRACE = "merged-trace.json"
PHASES = ("host", "compute", "exposed_comm", "optimizer")
# flight-recorder events carried into the merged trace per dump
_FLIGHT_TRACE_EVENTS = 200

_STEP_SECONDS = _metrics().histogram(
    "horovod_step_seconds",
    "Wall time of one profiled training step (hvd.profiler.step()).")
_HIDDEN_FRACTION = _metrics().gauge(
    "horovod_comm_hidden_fraction",
    "Fraction of collective time hidden behind other in-flight work over "
    "the last profiled step (1 - exposed/total; 0 when the step ran no "
    "collectives).")
_MFU = _metrics().gauge(
    "horovod_mfu",
    "Rolling model-FLOPs utilization over the profiled step history "
    "(needs hvd.profiler.set_flops_per_step with a peak-FLOPs hint).")


def _comm_totals() -> dict:
    try:
        from horovod_tpu.runtime import executor

        return executor.comm_totals()
    except Exception:
        return {"total_seconds": 0.0, "exposed_seconds": 0.0,
                "total_bytes": 0, "hidden_bytes": 0.0, "ops": 0}


def _handle_wait_seconds() -> float:
    try:
        from horovod_tpu.runtime import runtime as runtime_mod

        return runtime_mod._HANDLE_WAIT.labels().sum
    except Exception:
        return 0.0


class _StepRecord:
    """Open bookkeeping for one in-flight step."""

    __slots__ = ("index", "name", "auto", "t0", "t0_epoch", "comm0",
                 "wait0", "phase_seconds", "breakdown")

    def __init__(self, index: int, name: Optional[str], auto: bool):
        self.index = index
        self.name = name or f"step {index}"
        self.auto = auto
        self.t0 = time.perf_counter()
        self.t0_epoch = time.time()
        self.comm0 = _comm_totals()
        self.wait0 = _handle_wait_seconds()
        self.phase_seconds = {"host": 0.0, "optimizer": 0.0}
        self.breakdown: Optional[dict] = None  # filled at close


class StepProfiler:
    """Process-wide step profiler (one instance, see ``profiler()``)."""

    def __init__(self) -> None:
        self.enabled = False
        self.dir = ""
        self.history_cap = DEFAULT_PROFILE_HISTORY
        self.launch_rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        self.rank = self.launch_rank
        self._steps: deque = deque(maxlen=self.history_cap)
        self._trace_events: deque = deque(maxlen=4 * self.history_cap)
        self._mfu_window: deque = deque(maxlen=self.history_cap)
        self._flops_per_step: Optional[float] = None
        self._peak_flops: Optional[float] = None
        self._step_index = 0
        self._active: Optional[_StepRecord] = None  # explicit step() CM
        self._auto_rec: Optional[_StepRecord] = None
        self._dump_lock = threading.Lock()
        self._jax_tracing = False
        self._profile_state_cache: Optional[Tuple[float, dict]] = None

    # -- configuration ------------------------------------------------------
    def configure(self, rank: Optional[int] = None) -> None:
        """Re-read env knobs (called from ``hvd.init()``, including elastic
        re-init). Enabling registers the flight-recorder state provider so
        every postmortem dump carries the recent step breakdowns."""
        self.dir = os.environ.get(HOROVOD_PROFILE_DIR, "")
        self.enabled = _get_bool(HOROVOD_PROFILE) or bool(self.dir)
        cap = max(1, _get_int(HOROVOD_PROFILE_HISTORY,
                              DEFAULT_PROFILE_HISTORY))
        if cap != self.history_cap:
            self.history_cap = cap
            self._steps = deque(self._steps, maxlen=cap)
            self._trace_events = deque(self._trace_events, maxlen=4 * cap)
            self._mfu_window = deque(self._mfu_window, maxlen=cap)
        if rank is not None:
            self.rank = rank
        if self.enabled:
            flight_recorder.set_state_provider("profiler", self._debug_state)
            if self.dir and _get_bool(HOROVOD_PROFILE_JAX):
                self._start_jax_trace()

    def _start_jax_trace(self) -> None:
        if self._jax_tracing:
            return
        try:
            import jax

            jax.profiler.start_trace(
                os.path.join(self.dir, f"jax-rank-{self.launch_rank}"))
            self._jax_tracing = True
        except Exception as exc:
            log.warning("profiler: jax.profiler trace unavailable: %s", exc)

    def _stop_jax_trace(self) -> None:
        if not self._jax_tracing:
            return
        self._jax_tracing = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            log.debug("profiler: jax.profiler stop failed: %s", exc)

    def set_flops_per_step(self, flops: Optional[float],
                           peak_flops_per_chip: Optional[float] = None
                           ) -> None:
        """Model-FLOPs hint: per-chip FLOPs executed by one profiled step
        (forward + backward + update). With a per-chip peak the profiler
        maintains the rolling ``horovod_mfu`` gauge; without one MFU stays
        unset (the CPU fallback in bench.py does the same)."""
        self._flops_per_step = flops
        if peak_flops_per_chip is not None:
            self._peak_flops = peak_flops_per_chip

    # -- step bracketing ----------------------------------------------------
    def auto_step(self) -> None:
        """Implicit step boundary (hooked into ``DistributedOptimizer`` /
        ``training.make_train_step``): each call closes the previous
        implicit step and opens the next, so plain training loops get
        breakdowns without touching ``hvd.profiler.step()``. No-op while
        an explicit step is open, or when profiling is off."""
        if not self.enabled or self._active is not None:
            return
        if self._auto_rec is not None:
            self._finish(self._auto_rec)
        self._auto_rec = _StepRecord(self._next_index(), None, auto=True)

    @contextmanager
    def step(self, name: Optional[str] = None):
        """Bracket one training step; yields the finished breakdown dict
        holder (``rec.breakdown`` is filled on exit). Nested use is a
        no-op on the inner level."""
        if not self.enabled or self._active is not None:
            yield None
            return
        if self._auto_rec is not None:  # explicit bracketing wins
            self._finish(self._auto_rec)
            self._auto_rec = None
        rec = _StepRecord(self._next_index(), name, auto=False)
        self._active = rec
        tl = self._timeline()
        if tl is not None:
            tl.start("step", f"STEP_{rec.index}")
        try:
            yield rec
        finally:
            self._active = None
            if tl is not None:
                tl.end("step")
            self._finish(rec)

    @contextmanager
    def annotate(self, phase: str):
        """Attribute the enclosed wall time to ``phase`` ("host"/"input"
        for the data pipeline, "optimizer" for the update) within the
        current step."""
        key = {"input": "host", "host": "host",
               "optimizer": "optimizer"}.get(phase)
        if key is None:
            raise ValueError(f"unknown profiler phase {phase!r}; expected "
                             "'host', 'input' or 'optimizer'")
        rec = self._active or self._auto_rec
        if not self.enabled or rec is None:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            rec.phase_seconds[key] += time.perf_counter() - t0

    def _next_index(self) -> int:
        self._step_index += 1
        return self._step_index

    def _timeline(self):
        try:
            from horovod_tpu.core import state as state_mod

            return state_mod.global_state().timeline
        except Exception:
            return None

    # -- attribution --------------------------------------------------------
    def _finish(self, rec: _StepRecord) -> None:
        wall = max(time.perf_counter() - rec.t0, 1e-9)
        comm1 = _comm_totals()
        comm_total = max(0.0, comm1["total_seconds"]
                         - rec.comm0["total_seconds"])
        comm_exposed = max(0.0, comm1["exposed_seconds"]
                           - rec.comm0["exposed_seconds"])
        comm_bytes = max(0, comm1["total_bytes"] - rec.comm0["total_bytes"])
        hidden_bytes = max(0.0, comm1["hidden_bytes"]
                           - rec.comm0["hidden_bytes"])
        comm_ops = max(0, comm1.get("ops", 0) - rec.comm0.get("ops", 0))
        hidden_fraction = 0.0
        if comm_total > 0.0:
            hidden_fraction = min(1.0, max(0.0,
                                           1.0 - comm_exposed / comm_total))
        hidden_fraction_bytes = 0.0
        if comm_bytes > 0:
            hidden_fraction_bytes = min(1.0, max(0.0,
                                                 hidden_bytes / comm_bytes))

        # phase attribution: annotated host/optimizer + caller-blocked
        # collective time; compute is the remainder so the four phases sum
        # to the step wall time exactly (scaled down proportionally in the
        # rare case annotations overlap the wait)
        host = max(0.0, rec.phase_seconds["host"])
        optimizer = max(0.0, rec.phase_seconds["optimizer"])
        exposed_phase = max(0.0, _handle_wait_seconds() - rec.wait0)
        accounted = host + optimizer + exposed_phase
        if accounted > wall and accounted > 0.0:
            scale = wall / accounted
            host *= scale
            optimizer *= scale
            exposed_phase *= scale
            accounted = wall
        phases = {"host": host,
                  "compute": wall - accounted,
                  "exposed_comm": exposed_phase,
                  "optimizer": optimizer}

        mfu = None
        if self._flops_per_step and self._peak_flops:
            mfu = self._flops_per_step / wall / self._peak_flops
            self._mfu_window.append(mfu)
            _MFU.set(sum(self._mfu_window) / len(self._mfu_window))
        _STEP_SECONDS.observe(wall)
        _HIDDEN_FRACTION.set(hidden_fraction)

        # memory plane: HBM high watermark observed by the end of this
        # step (device peak_bytes_in_use where reported, the tracker's
        # claimed-total watermark on stat-less backends). Cumulative —
        # the allocator does not reset its peak per step.
        peak_hbm = None
        try:
            from horovod_tpu import memory

            peak_hbm = memory.tracker().peak_hbm_bytes()
        except Exception:
            pass

        rec.breakdown = {
            "step": rec.index,
            "name": rec.name,
            "auto": rec.auto,
            "t_start": rec.t0_epoch,
            "wall_seconds": wall,
            "peak_hbm_bytes": peak_hbm,
            "phases": phases,
            "comm": {"total_seconds": comm_total,
                     "exposed_seconds": comm_exposed,
                     "bytes": comm_bytes,
                     # fused executor dispatches this step: a bucketed
                     # backward shows one per released bucket, the
                     # unbucketed path at most a handful
                     "dispatches": comm_ops,
                     "hidden_fraction": hidden_fraction,
                     "hidden_fraction_bytes": hidden_fraction_bytes},
            "mfu": mfu,
        }
        self._steps.append(rec.breakdown)
        # Chrome step marker on the profiler's own lane (epoch us, the
        # package-wide trace clock domain) — merged with the runtime
        # timeline and device traces by merge_profile_dir
        self._trace_events.append({
            "ph": "X", "pid": 0, "tid": 0, "ts": rec.t0_epoch * 1e6,
            "dur": wall * 1e6, "name": rec.name,
            "args": {"phases_ms": {k: round(v * 1e3, 3)
                                   for k, v in phases.items()},
                     "comm_hidden_fraction": round(hidden_fraction, 4)}})
        flight_recorder.emit(
            "profiler_step", step=rec.index,
            wall_ms=round(wall * 1e3, 3),
            hidden_fraction=round(hidden_fraction, 4))
        try:
            # goodput ledger: the measured step wall is productive time,
            # the exposed-comm phase is badput. The tracker's own frontier
            # guard dedups against the State.commit step source.
            from horovod_tpu import goodput

            goodput.record_step(wall, exposed_comm=exposed_phase,
                                step=rec.index)
        except Exception:
            pass  # accounting must never fail a step

    # -- introspection ------------------------------------------------------
    def history(self) -> List[dict]:
        """The last N completed step breakdowns, oldest first."""
        return list(self._steps)

    def summary(self) -> dict:
        """Aggregate over the step history: mean wall/phase seconds and
        comm-hidden fractions (what bench.py embeds per headline)."""
        steps = list(self._steps)
        if not steps:
            return {"steps": 0, "wall_seconds": 0.0,
                    "step_breakdown": {k: 0.0 for k in PHASES},
                    "comm_hidden_fraction": 0.0,
                    "comm_hidden_fraction_bytes": 0.0, "mfu": None}
        n = len(steps)
        breakdown = {k: sum(s["phases"][k] for s in steps) / n
                     for k in PHASES}
        comm_total = sum(s["comm"]["total_seconds"] for s in steps)
        comm_exposed = sum(s["comm"]["exposed_seconds"] for s in steps)
        comm_bytes = sum(s["comm"]["bytes"] for s in steps)
        hidden_bytes = sum(s["comm"]["bytes"]
                           * s["comm"]["hidden_fraction_bytes"]
                           for s in steps)
        mfus = [s["mfu"] for s in steps if s.get("mfu") is not None]
        return {
            "steps": n,
            "wall_seconds": sum(s["wall_seconds"] for s in steps) / n,
            "step_breakdown": breakdown,
            "comm_hidden_fraction": (
                min(1.0, max(0.0, 1.0 - comm_exposed / comm_total))
                if comm_total > 0 else 0.0),
            "comm_hidden_fraction_bytes": (
                min(1.0, max(0.0, hidden_bytes / comm_bytes))
                if comm_bytes > 0 else 0.0),
            "mfu": (sum(mfus) / len(mfus)) if mfus else None,
        }

    def _debug_state(self) -> dict:
        """Flight-recorder state provider: recent step breakdowns ride in
        every postmortem dump."""
        return {"flops_per_step": self._flops_per_step,
                "peak_flops_per_chip": self._peak_flops,
                "steps": list(self._steps)}

    def profile_state(self) -> dict:
        """Document for the metrics server's ``GET /profile`` endpoint.
        Rate-limited like the failure-dump path: at most one fresh
        snapshot per second, cached in between, so a scrape loop cannot
        contend with the training loop."""
        now = time.monotonic()
        cached = self._profile_state_cache
        if cached is not None and now - cached[0] < 1.0:
            return cached[1]
        state = {"schema": SCHEMA, "rank": self.rank,
                 "launch_rank": self.launch_rank, "enabled": self.enabled,
                 "summary": self.summary(), "steps": self.history()}
        self._profile_state_cache = (now, state)
        return state

    # -- dump / ship --------------------------------------------------------
    def snapshot(self) -> dict:
        # memory plane: the reconciliation sampler's trail rides in the
        # profile dump so the merged Perfetto trace gets a per-rank
        # memory counter track (merge_profile_dir)
        memory_samples = []
        try:
            from horovod_tpu import memory

            memory_samples = memory.tracker().samples()
        except Exception:
            pass
        # tracing plane: the request/collective span ring rides the dump
        # so merge_profile_dir can lay out per-rank request lanes and
        # join one trace_id across ranks with flow arrows
        request_spans = []
        try:
            from horovod_tpu import tracing

            request_spans = tracing.spans()
        except Exception:
            pass
        # comms plane: the per-record busbw sample ring rides the dump so
        # the merged trace gets a per-rank bus-bandwidth counter track
        comms_samples = []
        try:
            from horovod_tpu import comms

            comms_samples = comms.tracker().samples()
        except Exception:
            pass
        # goodput plane: the goodput-fraction trail + incident ledger
        # ride the dump so the merged trace gets a per-rank "goodput
        # fraction" counter track and an incident instant lane
        goodput_samples: list = []
        goodput_incidents: list = []
        try:
            from horovod_tpu import goodput

            goodput_samples = goodput.tracker().samples()
            goodput_incidents = goodput.tracker().incidents()
        except Exception:
            pass
        return {
            "schema": SCHEMA,
            "rank": self.rank,
            "launch_rank": self.launch_rank,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "wall_time": time.time(),
            "clock_offset_seconds": flight_recorder.recorder().clock_offset(),
            "flops_per_step": self._flops_per_step,
            "peak_flops_per_chip": self._peak_flops,
            "steps": list(self._steps),
            "trace_events": list(self._trace_events),
            "memory_samples": memory_samples,
            "request_spans": request_spans,
            "comms_samples": comms_samples,
            "goodput_samples": goodput_samples,
            "goodput_incidents": goodput_incidents,
            "flight_events": flight_recorder.recorder().events()
            [-_FLIGHT_TRACE_EVENTS:],
        }

    def dump(self, path: Optional[str] = None, ship: bool = True) -> dict:
        """Write ``profile-rank-N.json`` (to ``path`` or the configured
        dir) and ship a copy to the launcher's rendezvous store. Closes an
        open implicit step first so its breakdown is included. Never
        raises — runs from shutdown paths."""
        with self._dump_lock:
            if self._auto_rec is not None:
                self._finish(self._auto_rec)
                self._auto_rec = None
            self._stop_jax_trace()
            snap = self.snapshot()
            payload = json.dumps(snap)
            target = path or self.dir
            if target:
                try:
                    out = target if target.endswith(".json") else \
                        os.path.join(target,
                                     f"{DUMP_PREFIX}{self.launch_rank}.json")
                    parent = os.path.dirname(out)
                    if parent:
                        os.makedirs(parent, exist_ok=True)
                    with open(out, "w") as f:
                        f.write(payload)
                    log.debug("profiler: wrote %s", out)
                except OSError as exc:
                    log.warning("profiler: dump to %r failed: %s",
                                target, exc)
            if ship:
                try:
                    self._ship(payload)
                except Exception as exc:
                    log.debug("profiler: ship failed: %s", exc)
            return snap

    def _ship(self, payload: str) -> None:
        dest = flight_recorder._rendezvous_addr()
        if dest is None:
            return
        from horovod_tpu.run.rendezvous import KVStoreClient

        client = KVStoreClient(dest[0], dest[1], scope=RENDEZVOUS_SCOPE,
                               timeout=5.0)
        client.set("rank.%d" % self.launch_rank, payload)

    def finalize(self) -> None:
        """Shutdown hook (core/basics.py): dump + ship when enabled."""
        if not self.enabled:
            return
        try:
            self.dump()
        except Exception as exc:
            log.debug("profiler: finalize failed: %s", exc)


_profiler = StepProfiler()


def profiler() -> StepProfiler:
    return _profiler


def configure(rank: Optional[int] = None) -> None:
    _profiler.configure(rank=rank)


def enabled() -> bool:
    return _profiler.enabled


def step(name: Optional[str] = None):
    """``with hvd.profiler.step(): ...`` — bracket one training step."""
    return _profiler.step(name)


def annotate(phase: str):
    """``with hvd.profiler.annotate("host"): ...`` — attribute wall time."""
    return _profiler.annotate(phase)


def auto_step() -> None:
    _profiler.auto_step()


def set_flops_per_step(flops: Optional[float],
                       peak_flops_per_chip: Optional[float] = None) -> None:
    _profiler.set_flops_per_step(flops,
                                 peak_flops_per_chip=peak_flops_per_chip)


def history() -> List[dict]:
    return _profiler.history()


def summary() -> dict:
    return _profiler.summary()


def profile_state() -> dict:
    return _profiler.profile_state()


def dump(path: Optional[str] = None, ship: bool = True) -> dict:
    return _profiler.dump(path=path, ship=ship)


def finalize() -> None:
    _profiler.finalize()


# ---------------------------------------------------------------------------
# Launcher side: harvest, merge, report (tpurun --profile-dir)
# ---------------------------------------------------------------------------

def load_dumps(directory: str) -> List[dict]:
    """Read every ``profile-rank-*.json`` in ``directory`` (unreadable
    files are skipped — a killed worker may have cut one short)."""
    dumps = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return dumps
    for name in names:
        if not (name.startswith(DUMP_PREFIX) and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                dumps.append(json.load(f))
        except (OSError, ValueError) as exc:
            log.warning("profiler: skipping unreadable dump %s: %s",
                        path, exc)
    return dumps


def _flight_trace_events(dump: dict) -> List[dict]:
    """Flight-recorder events as Chrome instants on their own lane (tid 1),
    epoch-us clock — so negotiation/dispatch/membership events interleave
    with step spans in the merged view."""
    out = []
    for ev in dump.get("flight_events", ()):
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        args = {k: v for k, v in ev.items() if k not in ("t", "kind")}
        out.append({"ph": "i", "pid": 0, "tid": 1, "ts": t * 1e6,
                    "name": str(ev.get("kind", "event")), "s": "t",
                    "args": args or None})
    return out


def _memory_trace_events(dump: dict) -> List[dict]:
    """The memory sampler's trail as a Chrome counter ("C") track —
    claimed vs actual device bytes per reconciliation sweep, rendered by
    Perfetto as an area chart on the rank's lane."""
    out = []
    for row in dump.get("memory_samples", ()):
        try:
            t, claimed, actual = row[0], int(row[1]), int(row[2])
        except (TypeError, ValueError, IndexError):
            continue
        if not isinstance(t, (int, float)):
            continue
        out.append({"ph": "C", "pid": 0, "tid": 0, "ts": t * 1e6,
                    "name": "device memory (bytes)",
                    "args": {"claimed": claimed, "actual": actual}})
    return out


def _comms_trace_events(dump: dict) -> List[dict]:
    """The comms tracker's busbw sample ring as a Chrome counter ("C")
    track — per-lane bus bandwidth over time next to the rank's step
    spans, so a bandwidth sag lines up visually with the step that paid
    for it (docs/comms.md)."""
    out = []
    for row in dump.get("comms_samples", ()):
        try:
            t, busbw, lane = row[0], float(row[1]), str(row[2])
        except (TypeError, ValueError, IndexError):
            continue
        if not isinstance(t, (int, float)):
            continue
        out.append({"ph": "C", "pid": 0, "tid": 0, "ts": t * 1e6,
                    "name": "bus bandwidth (GB/s)",
                    "args": {lane: round(busbw, 4)}})
    return out


def _goodput_trace_events(dump: dict) -> List[dict]:
    """The goodput tracker's fraction trail as a Chrome counter ("C")
    track plus its incident ledger as an instant ("i") lane — a goodput
    sag lines up visually with the incident that caused it
    (docs/goodput.md)."""
    out = []
    for row in dump.get("goodput_samples", ()):
        try:
            t, frac = row[0], float(row[1])
        except (TypeError, ValueError, IndexError):
            continue
        if not isinstance(t, (int, float)):
            continue
        out.append({"ph": "C", "pid": 0, "tid": 0, "ts": t * 1e6,
                    "name": "goodput fraction",
                    "args": {"productive": round(frac, 4)}})
    for inc in dump.get("goodput_incidents", ()):
        if not isinstance(inc, dict):
            continue
        t = inc.get("wall_time")
        if not isinstance(t, (int, float)):
            continue
        out.append({"ph": "i", "pid": 0, "tid": 1, "ts": t * 1e6,
                    "s": "t",
                    "name": "incident: %s" % inc.get("cause", "?"),
                    "args": {k: inc.get(k) for k in
                             ("duration_s", "generation", "culprit_rank",
                              "steps_replayed")}})
    return out


def _device_trace_files(directory: str) -> List[str]:
    """jax.profiler output below the profile dir: TensorBoard's profile
    plugin writes ``*.trace.json.gz`` under a nested run directory."""
    hits = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(directory, pat), recursive=True))
    return sorted(set(hits))


def _rank_of_path(path: str) -> Optional[int]:
    base = os.path.basename(path)
    for token in (os.sep.join(path.split(os.sep)[-3:]).split(os.sep)
                  + [base]):
        for prefix in ("timeline-rank-", "jax-rank-"):
            if token.startswith(prefix):
                digits = token[len(prefix):].split(".")[0]
                try:
                    return int(digits)
                except ValueError:
                    continue
    return None


def merge_profile_dir(directory: str,
                      out_path: Optional[str] = None) -> Tuple[str, int]:
    """Build ONE Chrome trace from everything profiling left in
    ``directory``: per-rank step markers + flight events (from the
    profiler dumps), per-rank runtime timelines (``timeline-rank-N.json``,
    written when tpurun launched with ``--profile-dir``), and any
    ``jax.profiler`` device traces below it. Every rank's events are
    shifted by that rank's ``/_time`` clock-offset estimate so two hosts'
    spans line up on the launcher's clock; each source file gets a private
    pid range labeled ``rank N <kind>``. Request spans (tracing.py) get
    their own ``rank N requests`` lane, and one trace_id's spans across
    ALL lanes are joined by Perfetto flow arrows — a request's life is
    one connected line from the frontend's submit through the serving
    replica's prefill/decode to the response. Returns (path, count)."""
    from horovod_tpu import timeline as timeline_mod
    from horovod_tpu import tracing

    dumps = load_dumps(directory)
    offsets: Dict[int, float] = {}
    lanes: List[Tuple[str, List[dict], float]] = []  # (label, events, off_s)
    for d in dumps:
        rank = d.get("launch_rank", d.get("rank", 0))
        offset = d.get("clock_offset_seconds") or 0.0
        offsets[rank] = offset
        events = [e for e in d.get("trace_events", ())
                  if isinstance(e, dict)]
        events += _flight_trace_events(d)
        events += _memory_trace_events(d)
        events += _comms_trace_events(d)
        events += _goodput_trace_events(d)
        if events:
            lanes.append((f"rank {rank} steps", events, offset))
        spans = [s for s in d.get("request_spans", ())
                 if isinstance(s, dict)]
        if spans:
            lanes.append((f"rank {rank} requests",
                          tracing.spans_to_chrome(spans), offset))
    for path in sorted(glob.glob(os.path.join(directory,
                                              "timeline-rank-*.json"))):
        rank = _rank_of_path(path)
        try:
            events = timeline_mod._load_trace_events(path)
        except (OSError, ValueError) as exc:
            log.warning("profiler: skipping unreadable trace %s: %s",
                        path, exc)
            continue
        lanes.append((f"rank {rank} timeline", events,
                      offsets.get(rank, 0.0)))
    for path in _device_trace_files(directory):
        rank = _rank_of_path(path)
        try:
            events = timeline_mod._load_trace_events(path)
        except (OSError, ValueError) as exc:
            log.warning("profiler: skipping unreadable trace %s: %s",
                        path, exc)
            continue
        lanes.append((f"rank {rank} device" if rank is not None
                      else os.path.basename(path), events,
                      offsets.get(rank, 0.0)))

    merged: List[dict] = []
    anchors: List[dict] = []   # corrected-clock request-span coordinates
    pid_base = 0
    for label, events, offset_s in lanes:
        pids = [e.get("pid", 0) for e in events]
        for orig_pid in sorted(set(pids)):
            merged.append({"ph": "M", "pid": orig_pid + pid_base, "ts": 0,
                           "name": "process_labels",
                           "args": {"labels": label}})
        off_us = offset_s * 1e6
        for e in events:
            e = dict(e)
            e["pid"] = e.get("pid", 0) + pid_base
            if isinstance(e.get("ts"), (int, float)) and e.get("ph") != "M":
                e["ts"] = e["ts"] + off_us
            merged.append(e)
            if e.get("ph") == "X" and e.get("cat") == "request":
                trace_id = (e.get("args") or {}).get("trace_id")
                if trace_id:
                    anchors.append({"trace_id": trace_id, "pid": e["pid"],
                                    "tid": e.get("tid", 0), "ts": e["ts"],
                                    "dur": e.get("dur", 0.0)})
        pid_base += max(pids, default=0) + 2
    # flow arrows must be generated AFTER the layout: they bind to their
    # enclosing slices by exact (pid, tid, ts), which only exist once
    # every lane has its final pid range and corrected clock
    merged.extend(tracing.flow_events(anchors))
    merged.sort(key=lambda e: (e.get("ts") or 0))
    out = out_path or os.path.join(directory, MERGED_TRACE)
    with open(out, "w") as f:
        json.dump({"traceEvents": merged}, f)
    return out, len(merged)


def format_step_report(dumps: List[dict]) -> str:
    """Cross-rank step-time report: per-rank mean wall + phase means, and
    a verdict naming the slowest rank and its dominant phase."""
    lines = ["=== step-time report (%d rank%s) ==="
             % (len(dumps), "" if len(dumps) == 1 else "s")]
    slowest: Optional[Tuple[Any, float, dict]] = None
    for d in sorted(dumps, key=lambda d: d.get("launch_rank", 0)):
        rank = d.get("launch_rank", d.get("rank", "?"))
        steps = d.get("steps", ())
        if not steps:
            lines.append(f"rank {rank}: no profiled steps")
            continue
        n = len(steps)
        wall = sum(s["wall_seconds"] for s in steps) / n
        phases = {k: sum(s["phases"].get(k, 0.0) for s in steps) / n
                  for k in PHASES}
        hidden = [s["comm"]["hidden_fraction"] for s in steps
                  if s.get("comm")]
        mfus = [s["mfu"] for s in steps if s.get("mfu") is not None]
        lines.append(
            "rank %s: %d steps, mean %.3f ms/step  "
            "(host %.3f, compute %.3f, exposed_comm %.3f, optimizer %.3f)"
            "  comm_hidden=%.1f%%%s" % (
                rank, n, wall * 1e3, phases["host"] * 1e3,
                phases["compute"] * 1e3, phases["exposed_comm"] * 1e3,
                phases["optimizer"] * 1e3,
                100.0 * (sum(hidden) / len(hidden) if hidden else 0.0),
                ("  mfu=%.3f" % (sum(mfus) / len(mfus))) if mfus else ""))
        if slowest is None or wall > slowest[1]:
            slowest = (rank, wall, phases)
    if slowest is not None:
        rank, wall, phases = slowest
        phase = max(phases, key=lambda k: phases[k])
        lines.append(
            "slowest: rank %s at %.3f ms/step, dominant phase: %s "
            "(%.3f ms, %.1f%% of step)" % (
                rank, wall * 1e3, phase, phases[phase] * 1e3,
                100.0 * phases[phase] / wall if wall else 0.0))
    return "\n".join(lines)
