"""Launcher package (``tpurun``) — reference: horovod/run/ (SURVEY.md §2.6)."""

from horovod_tpu.run.hosts import HostInfo, SlotInfo, allocate, parse_hosts
from horovod_tpu.run.launcher import launch_job
from horovod_tpu.run.rendezvous import KVStoreClient, RendezvousServer
from horovod_tpu.run.run import main, run_commandline

__all__ = [
    "HostInfo", "SlotInfo", "allocate", "parse_hosts",
    "launch_job", "RendezvousServer", "KVStoreClient",
    "run_commandline", "main",
]
