"""``python -m horovod_tpu.run`` — entry point for the tpurun launcher."""

from horovod_tpu.run.run import main

if __name__ == "__main__":
    main()
