"""Pluggable launch backends for tpurun.

The reference launcher selects its fan-out mechanism at runtime — mpirun
when MPI is built, the gloo/ssh path otherwise (reference:
horovod/run/run.py:715-732 `_run`, gloo_run.py vs mpi_run.py). The
mpirun path itself is dead on a TPU stack, but the SEAM matters: this
module is that seam, and provides the TPU-idiomatic second backend — GCE
TPU-VM fan-out via ``gcloud compute tpus tpu-vm ssh --worker=N``, the
way multi-host TPU pods are actually driven.

A backend turns (slot, command, worker_env) into the shell command the
launcher executes on the driver host; `launch_job` runs whatever comes
back through the same supervision machinery (output prefixes, teardown
on failure) regardless of backend.

Selection: ``tpurun --launch-backend {ssh,gcloud-tpu-vm}`` or
``HOROVOD_LAUNCH_BACKEND``; default ssh (local exec for local hosts).
"""

from __future__ import annotations

import os
import shlex
from typing import Dict, Optional

from horovod_tpu.run.hosts import SlotInfo

# env prefixes exported across the remote boundary (ssh/gcloud do not
# forward the environment)
_EXPORT_PREFIXES = ("HOROVOD_", "JAX_", "XLA_", "PATH", "PYTHONPATH",
                    "LD_LIBRARY_PATH", "TPU_")


def _export_prefix(env: Dict[str, str]) -> str:
    return " ".join(
        f"export {k}={shlex.quote(v)};" for k, v in sorted(env.items())
        if k.startswith(_EXPORT_PREFIXES))


def _remote_command(command: str, env: Dict[str, str]) -> str:
    """The shell line run on the far side of any remote transport:
    enter the driver's cwd, export the whitelisted env, run."""
    return (f"cd {shlex.quote(os.getcwd())} > /dev/null 2>&1; "
            f"{_export_prefix(env)} {command}")


class LaunchBackend:
    """One method: the shell command the driver runs for a slot (the
    launcher always passes the worker env to the spawned process too, so
    a backend that runs the command locally may return it unwrapped)."""

    name = "abstract"

    def command_for_slot(self, slot: SlotInfo, command: str,
                         env: Dict[str, str]) -> str:
        raise NotImplementedError


class SSHBackend(LaunchBackend):
    """Default: exec locally for local hosts, ssh otherwise (reference:
    gloo_run.py:211-301 launch loop)."""

    name = "ssh"

    def __init__(self, ssh_port: Optional[int] = None):
        self.ssh_port = ssh_port

    def command_for_slot(self, slot: SlotInfo, command: str,
                         env: Dict[str, str]) -> str:
        from horovod_tpu.run.launcher import is_local_host

        if is_local_host(slot.hostname):
            return command
        port_arg = f"-p {self.ssh_port} " if self.ssh_port else ""
        return (f"ssh -o PasswordAuthentication=no "
                f"-o StrictHostKeyChecking=no "
                f"{port_arg}{slot.hostname} "
                f"{shlex.quote(_remote_command(command, env))}")


class GCloudTPUVMBackend(LaunchBackend):
    """GCE TPU-VM fan-out: every host entry names a TPU VM, and the slot's
    local rank selects the pod worker — `gcloud compute tpus tpu-vm ssh
    <tpu> --worker=<local_rank> --command=...`. The TPU-idiomatic
    equivalent of the reference's second (mpirun) launch path."""

    name = "gcloud-tpu-vm"

    def __init__(self, zone: Optional[str] = None,
                 project: Optional[str] = None):
        self.zone = zone
        self.project = project

    def command_for_slot(self, slot: SlotInfo, command: str,
                         env: Dict[str, str]) -> str:
        zone = f" --zone={shlex.quote(self.zone)}" if self.zone else ""
        project = (f" --project={shlex.quote(self.project)}"
                   if self.project else "")
        return (f"gcloud compute tpus tpu-vm ssh "
                f"{shlex.quote(slot.hostname)}"
                f" --worker={slot.local_rank}{zone}{project}"
                f" --command={shlex.quote(_remote_command(command, env))}")


_BACKENDS = {
    SSHBackend.name: SSHBackend,
    GCloudTPUVMBackend.name: GCloudTPUVMBackend,
}


def make_backend(name: Optional[str] = None,
                 ssh_port: Optional[int] = None,
                 gcloud_zone: Optional[str] = None,
                 gcloud_project: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None) -> LaunchBackend:
    """Resolve the backend like the reference resolves gloo vs mpirun
    (run/run.py:715-732): explicit flag first, then env (``env`` mapping
    if given, else the process environment — HOROVOD_LAUNCH_BACKEND,
    HOROVOD_GCLOUD_ZONE, HOROVOD_GCLOUD_PROJECT), default ssh."""
    lookup = os.environ if env is None else env
    name = name or lookup.get("HOROVOD_LAUNCH_BACKEND", "") or "ssh"
    if name not in _BACKENDS:
        raise ValueError(
            f"unknown launch backend {name!r} (choices: "
            f"{sorted(_BACKENDS)})")
    if name == GCloudTPUVMBackend.name:
        return GCloudTPUVMBackend(
            zone=gcloud_zone or lookup.get("HOROVOD_GCLOUD_ZONE"),
            project=gcloud_project or lookup.get("HOROVOD_GCLOUD_PROJECT"))
    return SSHBackend(ssh_port=ssh_port)
