"""CLI-flag / config-file → HOROVOD_* environment translation.

TPU-native port of the reference's config layer (reference:
horovod/run/common/util/config_parser.py, SURVEY.md §5.6): three layers —
CLI flags, an optional YAML ``--config-file``, and ambient env — all
converge on the environment variables the runtime reads at ``hvd.init()``
(horovod_tpu/utils/env.py). Precedence matches the reference
(run/run.py:422-425,581-585): CLI flags given *after* ``--config-file``
override the file; the file overrides flags given before it; both override
ambient env.
"""

from __future__ import annotations

from typing import Optional

import yaml

# YAML section/key names mirror the reference's config schema
# (reference: config_parser.py constants).
_PARAMS = "params"
_TIMELINE = "timeline"
_AUTOTUNE = "autotune"
_STALL_CHECK = "stall_check"
_LOGGING = "logging"


def parse_config_file(path: str) -> dict:
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f"config file {path} must be a YAML mapping")
    return data


def set_args_from_config_file(args, config: dict) -> None:
    """Apply YAML values onto the parsed-args namespace, honoring
    ``args.seen_args`` — flags the user passed explicitly after the config
    flag keep their CLI value (reference: run.py:581-585)."""
    seen = getattr(args, "seen_args", set())

    def put(attr, value):
        if attr not in seen and value is not None:
            setattr(args, attr, value)

    params = config.get(_PARAMS, {})
    put("fusion_threshold_mb", params.get("fusion_threshold_mb"))
    put("cycle_time_ms", params.get("cycle_time_ms"))
    put("cache_capacity", params.get("cache_capacity"))
    put("hierarchical_allreduce", params.get("hierarchical_allreduce"))
    put("hierarchical_allgather", params.get("hierarchical_allgather"))

    timeline = config.get(_TIMELINE, {})
    put("timeline_filename", timeline.get("filename"))
    put("timeline_mark_cycles", timeline.get("mark_cycles"))

    autotune = config.get(_AUTOTUNE, {})
    put("autotune", autotune.get("enabled"))
    put("autotune_log_file", autotune.get("log_file"))
    put("autotune_warmup_samples", autotune.get("warmup_samples"))
    put("autotune_steps_per_sample", autotune.get("steps_per_sample"))
    put("autotune_bayes_opt_max_samples",
        autotune.get("bayes_opt_max_samples"))
    put("autotune_gaussian_process_noise",
        autotune.get("gaussian_process_noise"))

    stall = config.get(_STALL_CHECK, {})
    put("no_stall_check",
        None if stall.get("enabled") is None else not stall["enabled"])
    put("stall_check_warning_time_seconds",
        stall.get("warning_time_seconds"))
    put("stall_check_shutdown_time_seconds",
        stall.get("shutdown_time_seconds"))

    logging_cfg = config.get(_LOGGING, {})
    put("log_level", logging_cfg.get("level"))
    put("log_hide_timestamp", logging_cfg.get("hide_timestamp"))


def env_from_args(args) -> dict:
    """Translate parsed args into the HOROVOD_* env contract (reference:
    config_parser.set_env_from_args). Returns only the keys to inject."""
    env: dict = {}

    def put(name: str, value, transform=str):
        if value is not None:
            env[name] = transform(value)

    def put_bool(name: str, value):
        if value:
            env[name] = "1"

    put("HOROVOD_FUSION_THRESHOLD", args.fusion_threshold_mb,
        lambda v: str(int(float(v) * 1024 * 1024)))
    put("HOROVOD_CYCLE_TIME", args.cycle_time_ms)
    put("HOROVOD_CACHE_CAPACITY", args.cache_capacity)
    put_bool("HOROVOD_HIERARCHICAL_ALLREDUCE",
             getattr(args, "hierarchical_allreduce", None))
    put_bool("HOROVOD_HIERARCHICAL_ALLGATHER",
             getattr(args, "hierarchical_allgather", None))

    put("HOROVOD_TIMELINE", getattr(args, "timeline_filename", None))
    put_bool("HOROVOD_TIMELINE_MARK_CYCLES",
             getattr(args, "timeline_mark_cycles", None))

    put_bool("HOROVOD_AUTOTUNE", getattr(args, "autotune", None))
    put("HOROVOD_AUTOTUNE_LOG", getattr(args, "autotune_log_file", None))
    put("HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
        getattr(args, "autotune_warmup_samples", None))
    put("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
        getattr(args, "autotune_steps_per_sample", None))
    put("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
        getattr(args, "autotune_bayes_opt_max_samples", None))
    put("HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
        getattr(args, "autotune_gaussian_process_noise", None))

    put_bool("HOROVOD_STALL_CHECK_DISABLE",
             getattr(args, "no_stall_check", None))
    put("HOROVOD_STALL_CHECK_TIME_SECONDS",
        getattr(args, "stall_check_warning_time_seconds", None))
    put("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
        getattr(args, "stall_check_shutdown_time_seconds", None))

    put("HOROVOD_LOG_LEVEL", getattr(args, "log_level", None))
    put_bool("HOROVOD_LOG_HIDE_TIME",
             getattr(args, "log_hide_timestamp", None))

    put("HOROVOD_FLIGHT_RECORDER_DIR",
        getattr(args, "flight_recorder_dir", None))

    put("HOROVOD_MESH_SHAPE", getattr(args, "mesh_shape", None))
    return env


def validate_config_args(args) -> None:
    """Sanity checks mirroring reference validation
    (reference: config_parser.validate_config_args)."""
    fusion = getattr(args, "fusion_threshold_mb", None)
    if fusion is not None and float(fusion) < 0:
        raise ValueError("--fusion-threshold-mb must be >= 0")
    cycle = getattr(args, "cycle_time_ms", None)
    if cycle is not None and float(cycle) <= 0:
        raise ValueError("--cycle-time-ms must be > 0")
    cap: Optional[int] = getattr(args, "cache_capacity", None)
    if cap is not None and int(cap) < 0:
        raise ValueError("--cache-capacity must be >= 0")
