"""NIC / interface discovery for the launcher.

TPU-native port of the reference's ring interface probe (reference:
horovod/run/run.py:195-265 ``_driver_fn`` + horovod/run/task_fn.py:24-50):
before fan-out, a task agent starts on every host, registers its candidate
addresses with the driver, probes the *next* host's candidates in a ring,
and the driver intersects the results. Where the reference intersects
interface *names* (for Gloo's ``iface=`` binding), the TPU launcher needs
proven-routable *addresses*: the rendezvous / jax.distributed coordinator
address handed to workers must be one the workers demonstrably reached —
not whatever ``gethostbyname`` returns on a multi-NIC host.

Products:
* ``driver_addr`` — the driver candidate address every task actually used
  to register (majority vote), fed into ``HOROVOD_GLOO_RENDEZVOUS_ADDR`` /
  ``HOROVOD_COORDINATOR_ADDR``.
* ``host_routable`` — per host index, the addresses its ring predecessor
  reached with an authenticated ping; exported as a diagnostic and usable
  as a bind hint.

Remote agents are spawned over ssh (``python -m horovod_tpu.run.task_agent``)
exactly as the reference spawns ``task_fn`` on every host; local hosts run
the agent in-process.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
import sys
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from horovod_tpu.run import util
from horovod_tpu.run.service import (DriverService, ProbeAddressesRequest,
                                     ServiceClient, ShutdownServiceRequest,
                                     TaskService, local_addresses)


@dataclasses.dataclass
class DiscoveryResult:
    driver_addr: str
    host_routable: Dict[int, List[Tuple[str, int]]]


def _client_for(addresses: List[Tuple[str, int]], key: bytes,
                probe_timeout: float = 3.0) -> ServiceClient:
    """Client bound to the first address that answers an authenticated
    ping (a task registers ALL its candidate addresses; the driver may
    only be able to route to some of them). Each candidate dial is bounded
    by ``probe_timeout``; the VERIFIED client is returned — callers whose
    next request makes the task dial further peers pass a longer
    per-call ``timeout=`` to ``ServiceClient.call`` instead of getting a
    second, unverified client."""
    last_exc: Optional[Exception] = None
    for addr in addresses:
        client = ServiceClient(tuple(addr), key, timeout=probe_timeout)
        try:
            client.call(ProbeAddressesRequest([]))
            return client
        except Exception as exc:  # noqa: BLE001 — try the next candidate
            last_exc = exc
    raise RuntimeError(
        f"no registered task address reachable from the driver: "
        f"{addresses} ({last_exc})")




def _ssh_agent(hostname: str, index: int, num_hosts: int, key: bytes,
               driver_addrs: List[Tuple[str, int]],
               ssh_port: Optional[int], timeout: float) -> subprocess.Popen:
    from horovod_tpu.run.backends import _remote_command

    addrs = ",".join(f"{h}:{p}" for h, p in driver_addrs)
    # the HMAC key travels over the agent's STDIN, never the command line
    # (a command-line key is visible to every local user via `ps` for the
    # agent's whole lifetime); the remote command gets the launcher's
    # whitelisted env (PYTHONPATH etc.) so the agent can import
    # horovod_tpu in PYTHONPATH-based deployments
    inner = (f"{shlex.quote(sys.executable)} "
             f"-m horovod_tpu.run.task_agent {index} {num_hosts} "
             f"{shlex.quote(addrs)} {int(timeout)} --key-stdin")
    # the key must never ride the command line — strip it from the env
    # export too (backends' whitelist would otherwise re-leak it into ps)
    env = {k: v for k, v in os.environ.items() if k != "HOROVOD_TASK_KEY"}
    port_arg = f"-p {ssh_port} " if ssh_port else ""
    cmd = (f"ssh -o PasswordAuthentication=no -o StrictHostKeyChecking=no "
           f"{port_arg}{hostname} "
           f"{shlex.quote(_remote_command(inner, env))}")
    proc = subprocess.Popen(cmd, shell=True, start_new_session=True,
                            stdin=subprocess.PIPE)
    try:
        proc.stdin.write(key.hex().encode() + b"\n")
        proc.stdin.flush()
        proc.stdin.close()
    except (BrokenPipeError, OSError):
        pass  # agent died instantly; registration timeout reports it
    return proc


def _ring_probe(task_addresses: Dict[int, List[Tuple[str, int]]],
                key: bytes, probe_timeout: float
                ) -> Dict[int, List[Tuple[str, int]]]:
    """Ring probe: task i checks the candidates of task (i+1) % n; an
    authenticated pong proves routability host-to-host (not just
    driver-to-host). All n probes run concurrently — each is one
    driver->task-i dial plus one task-i->task-succ probe, independent of
    the others, so wall-clock is ~one probe round, not n of them (the
    reference likewise launches all task probes at once,
    run/run.py:195-265)."""
    n = len(task_addresses)

    def _probe(index: int) -> List[Tuple[str, int]]:
        succ = (index + 1) % n
        # the task dials each successor candidate serially with
        # probe_timeout, so the driver's wait on this one request must
        # cover ALL those dials, not a single one
        call_timeout = probe_timeout * max(1, len(task_addresses[succ])) + 5.0
        client = _client_for(task_addresses[index], key, probe_timeout)
        reachable = client.call(
            ProbeAddressesRequest(task_addresses[succ],
                                  dial_timeout=probe_timeout),
            timeout=call_timeout)
        return [tuple(a) for a in reachable]

    host_routable: Dict[int, List[Tuple[str, int]]] = {}
    with ThreadPoolExecutor(max_workers=min(n, 32)) as pool:
        for index, reachable in enumerate(pool.map(_probe, range(n))):
            host_routable[(index + 1) % n] = reachable
    return host_routable


def discover(hostnames: List[str], key: bytes,
             is_local: Optional[callable] = None,
             ssh_port: Optional[int] = None,
             timeout: float = 120.0,
             probe_timeout: Optional[float] = None) -> DiscoveryResult:
    """Run the ring probe across ``hostnames`` (one agent per host) and
    return the proven driver address plus per-host routable addresses.

    ``is_local`` decides in-process vs ssh agent (default: the launcher's
    ``is_local_host``). ``probe_timeout`` bounds each candidate-address
    dial (default 3 s, ``HOROVOD_PROBE_TIMEOUT``); the per-host probes
    run concurrently — the reference launches all task probes at once
    (run/run.py:195-265), and serial dialing would cost minutes on a
    64-host pod with one stale interface per host."""
    if is_local is None:
        from horovod_tpu.run.launcher import is_local_host as is_local
    if probe_timeout is None:
        probe_timeout = float(os.environ.get("HOROVOD_PROBE_TIMEOUT", "3"))

    n = len(hostnames)
    driver = DriverService(key, n)
    driver_addrs = local_addresses(driver.port)
    local_tasks: List[TaskService] = []
    ssh_procs: List[subprocess.Popen] = []
    try:
        agent_threads = []
        for index, host in enumerate(hostnames):
            if is_local(host):
                task = TaskService(key, index)
                local_tasks.append(task)
                t = threading.Thread(
                    target=task.register_any,
                    args=(driver_addrs, key,
                          util.Timeout(timeout, "task registration")),
                    daemon=True)
                t.start()
                agent_threads.append(t)
            else:
                ssh_procs.append(_ssh_agent(host, index, n, key,
                                            driver_addrs, ssh_port, timeout))
        driver.wait_for_initial_registration(
            util.Timeout(timeout, "task registration (NIC discovery)"))
        for t in agent_threads:
            t.join(timeout=timeout)

        task_addresses = driver.task_addresses()
        host_routable = _ring_probe(task_addresses, key, probe_timeout)
        empty = [i for i in range(n) if not host_routable[i]]
        if empty:
            raise RuntimeError(
                "NIC discovery: no routable address found for host(s) "
                f"{[hostnames[i] for i in empty]}; candidates were "
                f"{ {i: task_addresses[i] for i in empty} } "
                "(reference raises the same way when no common interface "
                "exists, run/run.py:253-262)")

        # the driver address EVERY task proved it can reach — an
        # intersection, like the reference's common_intfs (run/run.py:
        # 253-262); a majority pick would hand minority hosts an address
        # they demonstrably cannot route to
        reachable_sets = [set(addrs) for addrs in
                          driver.task_driver_reachable().values()]
        common = set.intersection(*reachable_sets) if reachable_sets else set()
        if not common:
            raise RuntimeError(
                "NIC discovery: no driver address is reachable from every "
                f"host; per-task reachable sets: "
                f"{driver.task_driver_reachable()}")
        # deterministic preference: candidate order (default-route
        # address first, loopback last — service.local_addresses)
        driver_addr = next(a[0] for a in driver_addrs if tuple(a) in common)
        return DiscoveryResult(driver_addr=driver_addr,
                               host_routable=host_routable)
    finally:
        if ssh_procs:
            # tell remote agents to exit (best-effort, concurrently), then
            # reap
            local_idx = {t.index for t in local_tasks}
            remote = [addrs for index, addrs
                      in driver.task_addresses().items()
                      if index not in local_idx]

            def _shutdown_one(addrs):
                try:
                    _client_for(addrs, key, probe_timeout).call(
                        ShutdownServiceRequest())
                except Exception:
                    pass

            if remote:
                with ThreadPoolExecutor(
                        max_workers=min(len(remote), 32)) as pool:
                    list(pool.map(_shutdown_one, remote))
            for proc in ssh_procs:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        for task in local_tasks:
            task.shutdown()
        driver.shutdown()
