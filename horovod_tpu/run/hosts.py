"""Host-list parsing and slot allocation.

TPU-native port of the reference's allocation semantics (reference:
horovod/run/gloo_run.py:56-114 ``_allocate``): given ``h1:4,h2:2``, assign
every slot a global ``rank``, a ``local_rank`` (index within its host), and
a ``cross_rank`` (index of its host among hosts that have a slot at that
local_rank). ``local_size`` is the host's slot count; ``cross_size`` is the
number of hosts with at least ``local_rank + 1`` slots.

One slot == one worker process == (by the framework's worker model) one TPU
chip (SURVEY.md §7 stage 2).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int

    def to_env(self) -> dict:
        """Launcher→worker env contract (reference: gloo_run.py:211-240
        sets HOROVOD_RANK/SIZE/LOCAL_RANK/...; consumed by
        gloo_context.cc:128-133, here by SocketController.from_env)."""
        return {
            "HOROVOD_HOSTNAME": self.hostname,
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_LOCAL_RANK": str(self.local_rank),
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_RANK": str(self.cross_rank),
            "HOROVOD_CROSS_SIZE": str(self.cross_size),
        }


_HOST_RE = re.compile(r"^(?P<host>[\w.\-\[\]:]+?)(:(?P<slots>\d+))?$")


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse ``host1:2,host2:4``; a missing slot count means 1 (reference:
    run/run.py host parsing)."""
    infos = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        m = _HOST_RE.match(part)
        if not m:
            raise ValueError(f"bad host specification: {part!r}")
        infos.append(HostInfo(m.group("host"),
                              int(m.group("slots") or 1)))
    if not infos:
        raise ValueError(f"no hosts in specification: {hosts_string!r}")
    return infos


def parse_hostfile(path: str) -> List[HostInfo]:
    """Parse an mpirun-style hostfile: ``hostname slots=N`` per line."""
    infos = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            fields = line.split()
            slots = 1
            for field in fields[1:]:
                if field.startswith("slots="):
                    slots = int(field[len("slots="):])
            infos.append(HostInfo(fields[0], slots))
    if not infos:
        raise ValueError(f"no hosts in hostfile {path}")
    return infos


def allocate(hosts: List[HostInfo], np: int) -> List[SlotInfo]:
    """Assign ``np`` ranks to hosts in order, filling each host's slots
    before moving on (reference: gloo_run.py:56-114)."""
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested -np {np} exceeds {total} available slots "
            f"({','.join(f'{h.hostname}:{h.slots}' for h in hosts)})")

    # truncated per-host slot usage for exactly np ranks
    used: List[int] = []
    remaining = np
    for h in hosts:
        take = min(h.slots, remaining)
        used.append(take)
        remaining -= take

    slots: List[SlotInfo] = []
    rank = 0
    for host_idx, (h, n) in enumerate(zip(hosts, used)):
        for local_rank in range(n):
            cross_rank = sum(1 for j in range(host_idx)
                             if used[j] > local_rank)
            cross_size = sum(1 for u in used if u > local_rank)
            slots.append(SlotInfo(
                hostname=h.hostname, rank=rank, size=np,
                local_rank=local_rank, local_size=n,
                cross_rank=cross_rank, cross_size=cross_size))
            rank += 1
    return slots
