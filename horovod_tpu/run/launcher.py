"""Process fan-out: run one worker per slot, locally or over ssh.

TPU-native port of the reference's gloo launcher (reference:
horovod/run/gloo_run.py:211-301): for every allocated slot, build the
worker env (slot contract + rendezvous + knobs), spawn the command —
``exec`` locally, ``ssh`` for remote hosts — stream tag-prefixed output
(optionally also captured to ``<output_dir>/rank.N/``), and terminate the
whole job when any worker exits non-zero (gloo_run.py:256-262) or the
launcher receives SIGINT/SIGTERM.

On top of the reference contract the launcher also wires up
``jax.distributed`` (HOROVOD_COORDINATOR_ADDR / NUM_PROCESSES /
PROCESS_ID) so every process joins one global TPU mesh — the TPU-native
equivalent of NCCL communicator bootstrap.
"""

from __future__ import annotations

import os
import signal
import socket
import sys
import threading
from typing import Dict, List, Optional

from horovod_tpu import flight_recorder
from horovod_tpu.run import util
from horovod_tpu.run.hosts import SlotInfo
from horovod_tpu.run.rendezvous import RendezvousServer

LOCAL_HOSTNAMES = {"localhost", "127.0.0.1", "::1"}


def is_local_host(hostname: str) -> bool:
    if hostname in LOCAL_HOSTNAMES:
        return True
    try:
        return hostname in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("0.0.0.0", 0))
        return s.getsockname()[1]


def _announce_net_chaos() -> None:
    """Log the armed network-fault clauses (if any) once at launch — a
    chaos run whose faults silently fail to parse tests nothing."""
    from horovod_tpu.utils import resilience

    spec = os.environ.get("HOROVOD_FAULT_INJECT", "")
    if not spec:
        return
    try:
        faults = resilience.parse_net_faults(spec)
    except ValueError as exc:
        print(f"tpurun: ignoring malformed HOROVOD_FAULT_INJECT net "
              f"clause: {exc}", file=sys.stderr)
        return
    if faults:
        print("tpurun: network chaos armed: "
              + "; ".join(str(f) for f in faults), file=sys.stderr)


def get_driver_ip(slots: List[SlotInfo]) -> str:
    """Address remote workers use to reach the launcher host."""
    if all(is_local_host(s.hostname) for s in slots):
        return "127.0.0.1"
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return socket.gethostname()


def build_worker_env(slot: SlotInfo, base_env: Dict[str, str],
                     driver_ip: str, socket_port: int, http_port: int,
                     coordinator_port: int, num_processes: int,
                     use_jax_distributed: bool = True) -> Dict[str, str]:
    """Full worker environment: launcher contract (reference:
    gloo_run.py:211-240) + jax.distributed bootstrap.

    Two rendezvous channels: the native socket controller's coordinator
    (rank 0 binds ``socket_port``; others dial it — the analogue of the
    gloo TCP context) and the launcher's HTTP KV store on ``http_port``
    (the analogue of the reference's rendezvous server)."""
    env = dict(base_env)
    env.update(slot.to_env())
    # per-rank file templating (the metrics dump supports the same
    # placeholder): one launcher-side setting fans out to rank-unique
    # paths — used by --profile-dir for timeline-rank-N.json
    if "{rank}" in env.get("HOROVOD_TIMELINE", ""):
        env["HOROVOD_TIMELINE"] = env["HOROVOD_TIMELINE"].format(
            rank=slot.rank)
    env.update({
        "HOROVOD_CONTROLLER": env.get("HOROVOD_CONTROLLER", "socket"),
        "HOROVOD_CPU_OPERATIONS": env.get("HOROVOD_CPU_OPERATIONS", "socket"),
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": driver_ip,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(socket_port),
        "HOROVOD_RENDEZVOUS_HTTP_ADDR": driver_ip,
        "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
    })
    if use_jax_distributed:
        env.update({
            "HOROVOD_COORDINATOR_ADDR": f"{driver_ip}:{coordinator_port}",
            "HOROVOD_NUM_PROCESSES": str(num_processes),
            "HOROVOD_PROCESS_ID": str(slot.rank),
        })
    return env


def launch_job(command: str, slots: List[SlotInfo],
               env: Optional[Dict[str, str]] = None,
               ssh_port: Optional[int] = None,
               output_dir: Optional[str] = None,
               use_jax_distributed: bool = True,
               prefix_output: bool = True,
               start_timeout: float = 300.0,
               backend=None,
               elastic: bool = False,
               min_workers: int = 1,
               max_workers: Optional[int] = None,
               discovery_script: Optional[str] = None,
               flight_recorder_dir: Optional[str] = None,
               profile_dir: Optional[str] = None) -> int:
    """Run ``command`` on every slot; returns the job exit code (first
    non-zero worker code, else 0). Starts the rendezvous KV server for the
    job's lifetime. ``backend`` is a :class:`run.backends.LaunchBackend`
    (default: ssh/local — the seam the reference's gloo-vs-mpirun choice
    occupies, run/run.py:715-732).

    ``elastic`` flips the failure policy (reference: elastic gloo_run vs
    plain gloo_run): a non-zero worker exit no longer tears the job down;
    survivors re-form on their own and the job fails only when fewer than
    ``min_workers`` workers remain. With a ``discovery_script`` an
    :class:`~horovod_tpu.elastic.driver.ElasticDriver` polls it and
    publishes host-change notices + heartbeat evictions through the
    rendezvous store.

    ``flight_recorder_dir`` closes the observability loop: workers write
    (and ship, via the rendezvous store) per-rank flight-recorder dumps;
    the launcher collects the shipped copies for workers whose local
    filesystem died with them and, when the job fails, prints a merged
    cross-rank postmortem naming the suspected culprit rank.

    ``profile_dir`` turns on the step profiler on every worker
    (``HOROVOD_PROFILE_DIR`` — per-rank timelines land in the same
    directory); after the job the launcher harvests shipped profile
    dumps, merges every rank's runtime timeline + step markers (+ any
    jax.profiler device traces) onto one clock-corrected Chrome trace,
    and prints the cross-rank step-time report naming the slowest phase
    and rank."""
    from horovod_tpu.run.backends import make_backend

    base_env = dict(os.environ if env is None else env)
    if flight_recorder_dir:
        base_env["HOROVOD_FLIGHT_RECORDER_DIR"] = flight_recorder_dir
    if profile_dir:
        base_env["HOROVOD_PROFILE_DIR"] = profile_dir
        # each rank's runtime Chrome trace feeds the merged view; an
        # explicit HOROVOD_TIMELINE (single shared path — wrong for
        # multi-rank anyway) is overridden by the per-rank template
        base_env["HOROVOD_TIMELINE"] = os.path.join(
            profile_dir, "timeline-rank-{rank}.json")
        try:
            os.makedirs(profile_dir, exist_ok=True)
        except OSError as exc:
            print(f"tpurun: cannot create profile dir {profile_dir!r}: "
                  f"{exc}", file=sys.stderr)
    if backend is None:
        # resolve from the CALLER's env mapping (like the NIC-discovery
        # knob below), so programmatic callers control the backend the
        # same way tpurun's CLI does
        backend = make_backend(ssh_port=ssh_port, env=base_env)
    driver_ip = get_driver_ip(slots)

    # NIC discovery (reference: run/run.py:195-265): on multi-NIC hosts
    # the heuristic driver_ip may not be the address workers can route
    # to — run the ring probe and use the proven address. Default: on
    # whenever a remote host is involved; HOROVOD_NIC_DISCOVERY=1 forces
    # it for all-local runs (tests), =0 disables. ssh backend only (the
    # agents are ssh-spawned); a non-ssh backend announces the skip so a
    # forced =1 never disappears silently.
    knob = base_env.get("HOROVOD_NIC_DISCOVERY", "").lower()
    any_remote = not all(is_local_host(s.hostname) for s in slots)
    discovery_wanted = knob not in ("0", "false", "off") and (
        any_remote or knob in ("1", "true", "on"))
    if discovery_wanted and getattr(backend, "name", "ssh") != "ssh":
        print(f"tpurun: NIC discovery skipped for launch backend "
              f"{backend.name!r} (agents are ssh-spawned); using "
              f"{driver_ip}", file=sys.stderr)
    elif discovery_wanted:
        from horovod_tpu.run import discovery as discovery_mod

        hostnames = list(dict.fromkeys(s.hostname for s in slots))
        try:
            found = discovery_mod.discover(
                hostnames, util.make_secret_key(), ssh_port=ssh_port)
            driver_ip = found.driver_addr
        except Exception as exc:  # fall back to the heuristic address
            print(f"tpurun: NIC discovery failed ({exc}); using "
                  f"{driver_ip}", file=sys.stderr)

    rendezvous = RendezvousServer()
    http_port = rendezvous.start()
    _announce_net_chaos()
    socket_port = _free_port()
    coordinator_port = _free_port()

    elastic_driver = None
    if elastic and discovery_script:
        from horovod_tpu.elastic.driver import (ElasticDriver,
                                                HostDiscoveryScript)

        elastic_driver = ElasticDriver(
            rendezvous, HostDiscoveryScript(discovery_script),
            min_workers=min_workers, max_workers=max_workers)
        elastic_driver.start()

    exit_codes: List[Optional[int]] = [None] * len(slots)
    failure = threading.Event()
    first_failure: List[Optional[int]] = [None]
    failure_lock = threading.Lock()

    def run_slot(i: int, slot: SlotInfo) -> None:
        worker_env = build_worker_env(
            slot, base_env, driver_ip, socket_port, http_port,
            coordinator_port,
            num_processes=len(slots),
            use_jax_distributed=use_jax_distributed)
        if elastic:
            worker_env["HOROVOD_ELASTIC"] = "1"
            worker_env["HOROVOD_ELASTIC_MIN_WORKERS"] = str(min_workers)
        cmd = backend.command_for_slot(slot, command, worker_env)

        stdout = stderr = None
        files = []
        try:
            if output_dir:
                rank_dir = os.path.join(output_dir, f"rank.{slot.rank}")
                os.makedirs(rank_dir, exist_ok=True)
                stdout = open(os.path.join(rank_dir, "stdout"), "w")
                stderr = open(os.path.join(rank_dir, "stderr"), "w")
                files = [stdout, stderr]
            code = util.execute(
                cmd, env=worker_env,
                stdout=stdout or sys.stdout, stderr=stderr or sys.stderr,
                index=slot.rank, events=[failure],
                prefix_output=prefix_output)
            exit_codes[i] = code
            if code not in (0, None):
                with failure_lock:
                    if elastic:
                        # survivors re-form on their own; only kill the
                        # job once fewer than min_workers could remain
                        failed = sum(1 for c in exit_codes
                                     if c not in (0, None))
                        if len(slots) - failed < min_workers:
                            if not failure.is_set():
                                first_failure[0] = code
                            failure.set()
                    else:
                        # report the code of the worker that failed first,
                        # not of workers we subsequently tore down
                        # (gloo_run.py:256-262)
                        if not failure.is_set():
                            first_failure[0] = code
                        failure.set()
        finally:
            for f in files:
                f.close()

    threads = [threading.Thread(target=run_slot, args=(i, s), daemon=True)
               for i, s in enumerate(slots)]

    prev_handlers = {}

    def on_signal(signum, frame):
        failure.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            prev_handlers[sig] = signal.signal(sig, on_signal)
        except ValueError:  # not main thread (tests)
            pass

    shipped: Dict[str, bytes] = {}
    shipped_profile: Dict[str, bytes] = {}
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)
        if elastic_driver is not None:
            elastic_driver.stop()
        if flight_recorder_dir:
            # harvest dumps workers shipped into the rendezvous store
            # BEFORE stopping it — the in-memory store dies with it
            try:
                scope = flight_recorder.RENDEZVOUS_SCOPE
                for key in rendezvous.live_keys(scope):
                    value = rendezvous.get(scope, key)
                    if value:
                        shipped[key] = value
            except Exception as exc:
                print(f"tpurun: could not collect shipped flight-recorder "
                      f"dumps: {exc}", file=sys.stderr)
        if profile_dir:
            # same store, the profiler's scope: per-rank step profiles
            try:
                from horovod_tpu import profiler

                for key in rendezvous.live_keys(profiler.RENDEZVOUS_SCOPE):
                    value = rendezvous.get(profiler.RENDEZVOUS_SCOPE, key)
                    if value:
                        shipped_profile[key] = value
            except Exception as exc:
                print(f"tpurun: could not collect shipped profiles: {exc}",
                      file=sys.stderr)
        rendezvous.stop()

    def job_exit_code() -> int:
        if elastic:
            # success = enough workers finished cleanly; lost ranks
            # (non-zero exits) were absorbed by the survivors' re-form
            clean = sum(1 for c in exit_codes if c == 0)
            if clean >= min_workers:
                return 0
            if first_failure[0] is not None:
                return first_failure[0]
            for code in exit_codes:
                if code not in (0, None):
                    return code
            return 1
        if first_failure[0] is not None:
            return first_failure[0]
        for code in exit_codes:
            if code not in (0, None):
                return code
        if any(code is None for code in exit_codes):
            return 1
        return 0

    code = job_exit_code()
    if flight_recorder_dir:
        _finalize_flight_dumps(flight_recorder_dir, shipped, code)
    if profile_dir:
        _finalize_profile(profile_dir, shipped_profile)
    return code


RESTART_LINEAGE_FILE = "restart-lineage.json"


def launch_supervised(command: str, slots: List[SlotInfo],
                      restart_budget: int = 3,
                      env: Optional[Dict[str, str]] = None,
                      **kwargs) -> int:
    """``launch_job`` under supervision: a failed job (any non-zero exit
    the elastic layer could not absorb) is relaunched up to
    ``restart_budget`` times — the crash-consistent checkpoint
    (``HOROVOD_CKPT_DIR``) is what makes the relaunch resume instead of
    retrain.

    Every attempt runs with ``HOROVOD_RESTART_ATTEMPT=<n>`` in the
    worker env, and the restart lineage — per attempt: exit code, wall
    times, budget — is appended to ``restart-lineage.json`` in the
    flight-recorder dir, where ``tpurun --postmortem`` folds it into the
    merged report (which restart a dump belongs to is otherwise
    guesswork)."""
    import json
    import time

    base_env = dict(os.environ if env is None else env)
    flight_dir = kwargs.get("flight_recorder_dir")
    lineage: List[dict] = []
    attempt = 0
    while True:
        base_env["HOROVOD_RESTART_ATTEMPT"] = str(attempt)
        t0 = time.time()
        code = launch_job(command, slots, env=dict(base_env), **kwargs)
        lineage.append({"attempt": attempt, "exit_code": code,
                        "started": t0, "ended": time.time(),
                        "restart_budget": restart_budget})
        if flight_dir:
            try:
                os.makedirs(flight_dir, exist_ok=True)
                from horovod_tpu.ckpt import io as ckpt_io

                ckpt_io.atomic_write(
                    os.path.join(flight_dir, RESTART_LINEAGE_FILE),
                    json.dumps({"attempts": lineage}, indent=1).encode(),
                    base="lineage")
            except Exception as exc:
                print(f"tpurun: could not record restart lineage: {exc}",
                      file=sys.stderr)
        if code == 0:
            if attempt:
                print(f"tpurun: job succeeded on supervised restart "
                      f"{attempt}/{restart_budget}", file=sys.stderr)
            return 0
        if attempt >= restart_budget:
            print(f"tpurun: restart budget exhausted "
                  f"({restart_budget} restarts); giving up with exit "
                  f"code {code}", file=sys.stderr)
            return code
        attempt += 1
        print(f"tpurun: job failed (exit {code}); supervised restart "
              f"{attempt}/{restart_budget}", file=sys.stderr)


def _finalize_flight_dumps(directory: str, shipped: Dict[str, bytes],
                           exit_code: int) -> None:
    """Persist rendezvous-shipped dumps (only for ranks that left no local
    file — a worker-written file is at least as fresh) and, when the job
    failed, print the merged cross-rank postmortem."""
    try:
        os.makedirs(directory, exist_ok=True)
    except OSError as exc:
        print(f"tpurun: cannot write flight-recorder dumps to "
              f"{directory!r}: {exc}", file=sys.stderr)
        return
    for key, value in shipped.items():
        if not key.startswith("rank."):
            continue
        path = os.path.join(
            directory, f"{flight_recorder.DUMP_PREFIX}"
            f"{key[len('rank.'):]}.json")
        if os.path.exists(path):
            continue
        try:
            with open(path, "wb") as f:
                f.write(value)
        except OSError as exc:
            print(f"tpurun: could not write {path}: {exc}", file=sys.stderr)
    if exit_code == 0:
        return
    dumps = flight_recorder.load_dumps(directory)
    if dumps:
        print(flight_recorder.format_postmortem(dumps), file=sys.stderr)
    else:
        print(f"tpurun: job failed but no flight-recorder dumps were found "
              f"in {directory!r}", file=sys.stderr)


def _finalize_profile(directory: str, shipped: Dict[str, bytes]) -> None:
    """Persist rendezvous-shipped per-rank profiles (worker-written local
    files win — they are at least as fresh), merge every rank's timeline /
    device trace / step markers onto one corrected clock, and print the
    cross-rank step-time report."""
    from horovod_tpu import profiler

    try:
        os.makedirs(directory, exist_ok=True)
    except OSError as exc:
        print(f"tpurun: cannot write profiles to {directory!r}: {exc}",
              file=sys.stderr)
        return
    for key, value in shipped.items():
        if not key.startswith("rank."):
            continue
        path = os.path.join(
            directory,
            f"{profiler.DUMP_PREFIX}{key[len('rank.'):]}.json")
        if os.path.exists(path):
            continue
        try:
            with open(path, "wb") as f:
                f.write(value)
        except OSError as exc:
            print(f"tpurun: could not write {path}: {exc}", file=sys.stderr)
    try:
        merged_path, n_events = profiler.merge_profile_dir(directory)
    except Exception as exc:
        print(f"tpurun: could not merge profile traces: {exc}",
              file=sys.stderr)
        merged_path, n_events = None, 0
    dumps = profiler.load_dumps(directory)
    if dumps:
        print(profiler.format_step_report(dumps))
    if merged_path and n_events:
        print(f"tpurun: merged trace ({n_events} events) written to "
              f"{merged_path} — load it in Perfetto / chrome://tracing")
