"""Rendezvous HTTP key-value store.

TPU-native port of the reference's launcher rendezvous service (reference:
horovod/run/rendezvous/http_server.py:140-204): a threaded HTTP server
holding scoped KV maps — ``global``, ``local_<cross_rank>``,
``cross_<local_rank>`` — that worker processes use to find each other
before any collective channel exists. PUT stores a value, GET returns 404
until the key appears (clients long-poll), DELETE marks a rank finished so
the launcher can reap the scope.

The socket data plane only needs the coordinator address (rank 0), which
the launcher passes directly in env; this store exists for everything else
— worker liveness, result collection, object exchange before init, and the
driver/task services (service.py).
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.error import HTTPError
from urllib.request import Request, urlopen


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _split(self):
        parts = self.path.strip("/").split("/", 1)
        if len(parts) != 2 or not parts[0] or not parts[1]:
            self.send_error(400, "path must be /scope/key")
            return None
        return parts[0], parts[1]

    def do_PUT(self):
        sk = self._split()
        if sk is None:
            return
        scope, key = sk
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.server.lock:
            self.server.store.setdefault(scope, {})[key] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        sk = self._split()
        if sk is None:
            return
        scope, key = sk
        with self.server.lock:
            value = self.server.store.get(scope, {}).get(key)
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):
        # a rank declaring itself finished with the scope
        # (reference: http_server.py scope_size bookkeeping)
        sk = self._split()
        if sk is None:
            return
        scope, key = sk
        with self.server.lock:
            self.server.store.get(scope, {}).pop(key, None)
            self.server.finished.setdefault(scope, set()).add(key)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer:
    """Launcher-side store. ``start()`` returns the bound port."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 bind_retries: int = 5):
        # An explicitly-requested port can collide with a dying server
        # from a previous launch (or a race between launchers); retry with
        # backoff before giving up. Only EADDRINUSE is plausibly transient
        # — EACCES/EADDRNOTAVAIL etc. fail identically every attempt, so
        # they surface immediately. port=0 (ephemeral) cannot collide.
        import errno

        attempt = 0
        while True:
            try:
                self._httpd = ThreadingHTTPServer((host, port), _Handler)
                break
            except OSError as exc:
                attempt += 1
                if (port == 0 or attempt > bind_retries
                        or exc.errno != errno.EADDRINUSE):
                    raise
                time.sleep(0.2 * attempt)
        self._httpd.store = {}  # type: ignore[attr-defined]
        self._httpd.finished = {}  # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # launcher-side introspection
    def finished_keys(self, scope: str) -> set:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return set(self._httpd.finished.get(scope, set()))  # type: ignore

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return self._httpd.store.get(scope, {}).get(key)  # type: ignore


class KVStoreClient:
    """Worker-side client (reference: the gloo HTTPStore,
    common/gloo/http_store.cc — set/get/wait against the launcher server)."""

    def __init__(self, addr: str, port: int, scope: str = "global",
                 timeout: float = 60.0):
        self._base = f"http://{addr}:{port}"
        self._scope = scope
        self._timeout = timeout

    def _url(self, key: str, scope: Optional[str] = None) -> str:
        return f"{self._base}/{scope or self._scope}/{key}"

    def set(self, key: str, value: bytes, scope: Optional[str] = None) -> None:
        req = Request(self._url(key, scope), data=value, method="PUT")
        urlopen(req, timeout=10).read()

    def get(self, key: str, scope: Optional[str] = None,
            wait: bool = True) -> bytes:
        deadline = time.monotonic() + self._timeout
        while True:
            try:
                return urlopen(self._url(key, scope), timeout=10).read()
            except HTTPError as e:
                if e.code != 404 or not wait:
                    raise KeyError(key) from e
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"rendezvous key {key!r} not published within "
                    f"{self._timeout}s")
            time.sleep(0.05)

    def finish(self, key: str, scope: Optional[str] = None) -> None:
        req = Request(self._url(key, scope), method="DELETE")
        urlopen(req, timeout=10).read()
