"""Rendezvous HTTP key-value store.

TPU-native port of the reference's launcher rendezvous service (reference:
horovod/run/rendezvous/http_server.py:140-204): a threaded HTTP server
holding scoped KV maps — ``global``, ``local_<cross_rank>``,
``cross_<local_rank>`` — that worker processes use to find each other
before any collective channel exists. PUT stores a value, GET returns 404
until the key appears, DELETE marks a rank finished so the launcher can
reap the scope.

GET supports server-side long-polling (``?wait=<seconds>``): the handler
parks on a condition variable until the key is published or the wait
expires, replacing the client's fixed-sleep 404 spin (one request per
``HOROVOD_RENDEZVOUS_LONG_POLL_SECONDS`` instead of twenty per second).

Two scopes get special treatment for the elastic subsystem:

* ``heartbeat`` — every PUT is timestamped; keys older than the server's
  TTL (``HOROVOD_RENDEZVOUS_HEARTBEAT_TTL``) vanish from GET and listing,
  so the elastic driver reads current liveness with no bookkeeping.
* ``/_keys/<scope>`` — lists a scope's keys (newline-joined), which the
  elastic re-form protocol uses to discover who registered for the next
  generation.

Both sides participate in the resilience layer (utils/resilience.py):
the server honors injected ``kv_outage`` windows (answering 503 so chaos
tests drive the real client retry path), and every client HTTP op runs
under a :class:`~horovod_tpu.utils.resilience.RetryPolicy` with a
default socket timeout — a hung or flapping rendezvous server delays a
worker, it can no longer wedge one forever.
"""

from __future__ import annotations

import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.error import HTTPError
from urllib.parse import parse_qs, urlsplit
from urllib.request import Request, urlopen

from horovod_tpu import comms
from horovod_tpu.utils import resilience
from horovod_tpu.utils.env import _get_float

HOROVOD_RENDEZVOUS_LONG_POLL_SECONDS = "HOROVOD_RENDEZVOUS_LONG_POLL_SECONDS"
HOROVOD_RENDEZVOUS_HEARTBEAT_TTL = "HOROVOD_RENDEZVOUS_HEARTBEAT_TTL"

# cap on the server-side park, so a lost client cannot pin a handler
# thread forever
_MAX_WAIT_SECONDS = 60.0
HEARTBEAT_SCOPE = "heartbeat"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _split(self):
        parts = urlsplit(self.path).path.strip("/").split("/", 1)
        if len(parts) != 2 or not parts[0] or not parts[1]:
            self.send_error(400, "path must be /scope/key")
            return None
        return parts[0], parts[1]

    def _query(self, name: str) -> Optional[str]:
        values = parse_qs(urlsplit(self.path).query).get(name)
        return values[0] if values else None

    def _chaos_outage(self, scope: Optional[str]) -> bool:
        """Injected ``kv_outage`` window (HOROVOD_FAULT_INJECT): when
        active, answer 503 and return True. An ``on=reform`` outage arms
        on the first request touching a per-generation elastic scope —
        deterministically covering the re-form window chaos tests target.
        Any request body was already consumed by the caller (keep-alive
        correctness)."""
        srv = self.server
        fault = getattr(srv, "chaos_outage", None)
        if fault is None:
            return False
        now = time.monotonic()
        with srv.lock:
            start = srv.chaos_outage_start
            if (start is None and fault.on == "reform"
                    and scope and scope.startswith("elastic.g")):
                srv.chaos_outage_start = start = now
        if start is None or not (start <= now <= start + fault.seconds):
            return False
        self.send_response(503)
        self.send_header("Content-Length", "0")
        self.end_headers()
        return True

    def do_PUT(self):
        sk = self._split()
        if sk is None:
            return
        scope, key = sk
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if self._chaos_outage(scope):
            return
        with self.server.lock:
            self.server.store.setdefault(scope, {})[key] = value
            self.server.put_times.setdefault(scope, {})[key] = \
                time.monotonic()
            self.server.cond.notify_all()  # wake long-polling GETs
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _lookup(self, scope: str, key: str) -> Optional[bytes]:
        """Caller holds the lock. Heartbeat keys past the TTL read as
        absent — expiry IS the liveness signal."""
        value = self.server.store.get(scope, {}).get(key)
        if value is not None and scope == HEARTBEAT_SCOPE:
            put = self.server.put_times.get(scope, {}).get(key, 0.0)
            if time.monotonic() - put > self.server.heartbeat_ttl:
                return None
        return value

    def do_GET(self):
        path = urlsplit(self.path).path
        if path == "/_time":
            # launcher wall clock: workers sample this (NTP-style) so the
            # flight-recorder postmortem can merge cross-host event times
            body = repr(time.time()).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path.startswith("/_keys/"):
            scope = path[len("/_keys/"):].strip("/")
            if self._chaos_outage(scope):
                return
            return self._do_keys(scope)
        sk = self._split()
        if sk is None:
            return
        scope, key = sk
        if self._chaos_outage(scope):
            return
        try:
            wait = min(float(self._query("wait") or 0.0), _MAX_WAIT_SECONDS)
        except ValueError:
            wait = 0.0
        deadline = time.monotonic() + wait
        with self.server.lock:
            value = self._lookup(scope, key)
            while value is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self.server.cond.wait(remaining)
                value = self._lookup(scope, key)
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def _do_keys(self, scope: str) -> None:
        """GET /_keys/<scope>[?ttl=<s>] — list the scope's (live) keys."""
        ttl = None
        try:
            if self._query("ttl") is not None:
                ttl = float(self._query("ttl"))
        except ValueError:
            ttl = None
        with self.server.lock:
            keys = _live_keys_locked(self.server, scope, ttl)
        body = "\n".join(sorted(keys)).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        # a rank declaring itself finished with the scope
        # (reference: http_server.py scope_size bookkeeping); the
        # special key "*" drops the whole scope (checkpoint commit
        # scopes are per-step — without this they accumulate forever)
        sk = self._split()
        if sk is None:
            return
        scope, key = sk
        if self._chaos_outage(scope):
            return
        with self.server.lock:
            if key == "*":
                self.server.store.pop(scope, None)
                self.server.put_times.pop(scope, None)
                self.server.finished.pop(scope, None)
            else:
                self.server.store.get(scope, {}).pop(key, None)
                self.server.put_times.get(scope, {}).pop(key, None)
                self.server.finished.setdefault(scope, set()).add(key)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


def _live_keys_locked(httpd, scope: str, ttl: Optional[float]) -> List[str]:
    """Keys of ``scope``; with a TTL (explicit, or the server default for
    the heartbeat scope) only keys PUT within the last ``ttl`` seconds."""
    if ttl is None and scope == HEARTBEAT_SCOPE:
        ttl = httpd.heartbeat_ttl
    keys = list(httpd.store.get(scope, {}))
    if ttl is None:
        return keys
    now = time.monotonic()
    times = httpd.put_times.get(scope, {})
    return [k for k in keys if now - times.get(k, 0.0) <= ttl]


class RendezvousServer:
    """Launcher-side store. ``start()`` returns the bound port."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 bind_retries: int = 5,
                 heartbeat_ttl: Optional[float] = None):
        # An explicitly-requested port can collide with a dying server
        # from a previous launch (or a race between launchers); retry with
        # backoff before giving up. Only EADDRINUSE is plausibly transient
        # — EACCES/EADDRNOTAVAIL etc. fail identically every attempt, so
        # they surface immediately. port=0 (ephemeral) cannot collide.
        import errno

        attempt = 0
        while True:
            try:
                self._httpd = ThreadingHTTPServer((host, port), _Handler)
                break
            except OSError as exc:
                attempt += 1
                if (port == 0 or attempt > bind_retries
                        or exc.errno != errno.EADDRINUSE):
                    raise
                time.sleep(0.2 * attempt)
        self._httpd.store = {}  # type: ignore[attr-defined]
        self._httpd.finished = {}  # type: ignore[attr-defined]
        self._httpd.put_times = {}  # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.cond = threading.Condition(  # type: ignore[attr-defined]
            self._httpd.lock)
        self._httpd.heartbeat_ttl = (  # type: ignore[attr-defined]
            heartbeat_ttl if heartbeat_ttl is not None
            else _get_float(HOROVOD_RENDEZVOUS_HEARTBEAT_TTL, 30.0))
        # injected kv_outage (chaos): the window during which every KV
        # request answers 503. Timer-armed outages start counting now;
        # on=reform outages arm on first elastic.g* traffic.
        try:
            faults = resilience.parse_net_faults(
                os.environ.get("HOROVOD_FAULT_INJECT"))
        except ValueError:
            faults = []
        outage = next((f for f in faults if f.kind == "kv_outage"), None)
        self._httpd.chaos_outage = outage  # type: ignore[attr-defined]
        self._httpd.chaos_outage_start = (  # type: ignore[attr-defined]
            None if outage is None or outage.on == "reform"
            else time.monotonic() + outage.after)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        with self._httpd.lock:  # type: ignore[attr-defined]
            # release parked long-polls so their handler threads exit
            self._httpd.cond.notify_all()  # type: ignore[attr-defined]
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # launcher-side introspection
    def finished_keys(self, scope: str) -> set:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return set(self._httpd.finished.get(scope, set()))  # type: ignore

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return self._httpd.store.get(scope, {}).get(key)  # type: ignore

    def put(self, scope: str, key: str, value: bytes) -> None:
        """In-process PUT (the elastic driver lives in the launcher)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.store.setdefault(scope, {})[key] = value
            self._httpd.put_times.setdefault(  # type: ignore[attr-defined]
                scope, {})[key] = time.monotonic()
            self._httpd.cond.notify_all()  # type: ignore[attr-defined]

    def live_keys(self, scope: str, ttl: Optional[float] = None) -> List[str]:
        """Scope keys PUT within ``ttl`` seconds (default: the server's
        heartbeat TTL for the heartbeat scope, else no expiry)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            return _live_keys_locked(self._httpd, scope, ttl)


class KVStoreClient:
    """Worker-side client (reference: the gloo HTTPStore,
    common/gloo/http_store.cc — set/get/wait against the launcher server).

    ``get(wait=True)`` long-polls: each request asks the server to park up
    to ``long_poll`` seconds (``HOROVOD_RENDEZVOUS_LONG_POLL_SECONDS``)
    before 404ing, and the short client-side sleep only paces retries
    against pre-long-poll servers.

    Every HTTP op carries the retry policy's per-attempt socket timeout
    (a hung server can never block a worker forever) and retries
    transient failures — connection resets, 5xx/503 outage windows,
    socket timeouts — with full-jitter backoff. ``get``'s retries are
    bounded by the op's OWN deadline (``timeout``) rather than the
    policy's attempt cap, so a multi-second server outage shorter than
    the deadline is survived no matter how many attempts it takes."""

    def __init__(self, addr: str, port: int, scope: str = "global",
                 timeout: float = 60.0, long_poll: Optional[float] = None,
                 retry: Optional[resilience.RetryPolicy] = None):
        self._base = f"http://{addr}:{port}"
        self._scope = scope
        self._timeout = timeout
        self._long_poll = (long_poll if long_poll is not None
                           else _get_float(
                               HOROVOD_RENDEZVOUS_LONG_POLL_SECONDS, 5.0))
        self._retry = retry or resilience.RetryPolicy.from_env("kv")

    def _url(self, key: str, scope: Optional[str] = None) -> str:
        return f"{self._base}/{scope or self._scope}/{key}"

    def _open(self, url_or_req, timeout: float, phase: str) -> bytes:
        resilience.inject("kv", phase)
        t0 = time.monotonic()
        with urlopen(url_or_req, timeout=timeout) as resp:
            body = resp.read()
        # kv lane: control-plane round trips are tiny but their bandwidth
        # collapse is the earliest symptom of a sick network — account the
        # response payload over the request wall time
        comms.record(phase, "kv", len(body), time.monotonic() - t0)
        return body

    def set(self, key: str, value: bytes, scope: Optional[str] = None) -> None:
        req = Request(self._url(key, scope), data=value, method="PUT")
        self._retry.call(self._open, req, self._retry.attempt_timeout,
                         "set", phase="kv.set")

    def get(self, key: str, scope: Optional[str] = None,
            wait: bool = True) -> bytes:
        deadline = time.monotonic() + self._timeout
        attempt = 0
        while True:
            url = self._url(key, scope)
            poll = 0.0
            if wait:
                poll = max(0.0, min(self._long_poll,
                                    deadline - time.monotonic()))
                if poll > 0:
                    url += f"?wait={poll:g}"
            try:
                return self._open(url, poll + self._retry.attempt_timeout,
                                  "get")
            except HTTPError as e:
                if e.code == 404:
                    if not wait:
                        raise KeyError(key) from e
                    # long-poll miss — the normal not-yet-published signal
                elif self._retry.retryable(e):
                    attempt += 1
                    self._backoff_or_raise(e, "kv.get", attempt, deadline)
                    continue
                else:
                    raise KeyError(key) from e
            except Exception as e:
                if not self._retry.retryable(e):
                    raise
                attempt += 1
                self._backoff_or_raise(e, "kv.get", attempt, deadline)
                continue
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"rendezvous key {key!r} not published within "
                    f"{self._timeout}s")
            time.sleep(0.05)

    def _backoff_or_raise(self, exc: Exception, phase: str, attempt: int,
                          deadline: float) -> None:
        """One full-jitter backoff inside ``get``'s loop; re-raises once
        the op deadline cannot accommodate another attempt."""
        delay = self._retry.delay_for(attempt)
        if time.monotonic() + delay >= deadline:
            resilience.give_up(self._retry.transport, phase, attempt, exc)
            raise exc
        resilience.note_retry(self._retry.transport, phase, attempt, delay,
                              exc)
        time.sleep(delay)

    def keys(self, scope: Optional[str] = None,
             ttl: Optional[float] = None) -> List[str]:
        """List a scope's keys (live ones only, when ``ttl`` given)."""
        url = f"{self._base}/_keys/{scope or self._scope}"
        if ttl is not None:
            url += f"?ttl={ttl:g}"
        body = self._retry.call(
            self._open, url, self._retry.attempt_timeout, "keys",
            phase="kv.keys").decode()
        return [k for k in body.split("\n") if k]

    def finish(self, key: str, scope: Optional[str] = None) -> None:
        req = Request(self._url(key, scope), method="DELETE")
        self._retry.call(self._open, req, self._retry.attempt_timeout,
                         "finish", phase="kv.finish")

    def clear_scope(self, scope: Optional[str] = None) -> None:
        """Drop the whole scope server-side (DELETE of the ``*`` key) —
        used by per-step checkpoint commit scopes once published."""
        self.finish("*", scope)
