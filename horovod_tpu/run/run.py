"""``tpurun`` — the launcher CLI (≡ ``horovodrun``).

TPU-native port of the reference CLI (reference: horovod/run/run.py:374-732
and bin/horovodrun): parse flags / YAML config into the HOROVOD_* env
contract, check host reachability, allocate slots, and fan the training
command out across hosts.

    tpurun -np 4 -H host1:2,host2:2 python train.py
    tpurun -np 8 python train.py           # 8 local workers
    tpurun --check-build
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import textwrap
import time
from typing import List, Optional

from horovod_tpu.run import config_parser, hosts as hosts_mod, launcher
from horovod_tpu.run import util
from horovod_tpu.version import __version__

SSH_CHECK_TIMEOUT_S = 30
# reference caches ssh reachability results for 60 minutes in ~/.horovod
# (run/run.py:49-60)
CACHE_TTL_S = 60 * 60
CACHE_DIR = os.path.expanduser("~/.horovod_tpu")


class _RecordAction(argparse.Action):
    """Records explicitly-passed flags so config-file precedence can be
    applied (reference: run.py:422-425 _add_arg tracking)."""

    def __init__(self, option_strings, dest, nargs=None, const=None, **kw):
        self._const = const
        self._nargs = nargs
        super().__init__(option_strings, dest, nargs=nargs, const=const, **kw)

    def __call__(self, parser, namespace, values, option_string=None):
        if self._const is not None and values in (None, []):
            values = self._const
        setattr(namespace, self.dest, values)
        if not hasattr(namespace, "seen_args"):
            namespace.seen_args = set()
        namespace.seen_args.add(self.dest)


def _add(parser, *flags, **kw):
    if kw.get("action") == "store_true":
        kw.pop("action")
        kw.update(action=_RecordAction, nargs=0, const=True, default=kw.get(
            "default", None))
    else:
        kw.setdefault("action", _RecordAction)
    parser.add_argument(*flags, **kw)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="tpurun",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        description="Launch a distributed horovod_tpu job.",
        epilog=textwrap.dedent("""\
            Example:
                tpurun -np 4 -H host1:2,host2:2 python train.py
            """))
    parser.add_argument("-v", "--version", action="version",
                        version=__version__)
    _add(parser, "-np", "--num-proc", dest="np", type=int,
         help="Total number of worker processes (one per TPU chip).")
    _add(parser, "-H", "--hosts", dest="hosts",
         help="Comma-separated host:slots list, e.g. host1:4,host2:4.")
    _add(parser, "--hostfile", dest="hostfile",
         help="mpirun-style hostfile ('hostname slots=N' per line).")
    _add(parser, "-p", "--ssh-port", dest="ssh_port", type=int,
         help="SSH port on all hosts.")
    _add(parser, "--start-timeout", dest="start_timeout", type=int,
         default=600, help="Seconds to wait for all processes to start.")
    _add(parser, "--output-filename", dest="output_dir",
         help="Capture each rank's output under <dir>/rank.N/std{out,err}.")
    _add(parser, "--verbose", dest="verbose", action="store_true",
         help="Verbose launcher logging.")
    _add(parser, "--disable-cache", dest="disable_cache",
         action="store_true",
         help="Do not cache ssh reachability checks.")
    parser.add_argument("--check-build", action="store_true",
                        help="Print capability report and exit "
                             "(reference: run/run.py:268-303).")
    _add(parser, "--config-file", dest="config_file",
         help="YAML config file; flags given after it take precedence.")
    _add(parser, "--no-jax-distributed", dest="no_jax_distributed",
         action="store_true",
         help="Do not bootstrap jax.distributed (host data plane only).")
    _add(parser, "--launch-backend", dest="launch_backend",
         choices=["ssh", "gcloud-tpu-vm"],
         help="Fan-out mechanism: ssh (default; local exec for local "
              "hosts) or gcloud-tpu-vm (GCE `gcloud compute tpus tpu-vm "
              "ssh --worker=N`; hosts name TPU VMs). Also "
              "HOROVOD_LAUNCH_BACKEND. The seam the reference's "
              "gloo-vs-mpirun choice occupies (run/run.py:715-732).")
    _add(parser, "--gcloud-zone", dest="gcloud_zone",
         help="GCE zone for --launch-backend gcloud-tpu-vm.")
    _add(parser, "--gcloud-project", dest="gcloud_project",
         help="GCP project for --launch-backend gcloud-tpu-vm.")
    _add(parser, "--mesh-shape", dest="mesh_shape",
         help="Global mesh as 'cross,local' (default: hosts x slots).")

    params = parser.add_argument_group("tunable parameters")
    _add(params, "--fusion-threshold-mb", dest="fusion_threshold_mb",
         type=float, help="Tensor fusion buffer threshold in MB.")
    _add(params, "--cycle-time-ms", dest="cycle_time_ms", type=float,
         help="Background cycle time in ms.")
    _add(params, "--cache-capacity", dest="cache_capacity", type=int,
         help="Response cache capacity.")
    _add(params, "--hierarchical-allreduce", dest="hierarchical_allreduce",
         action="store_true",
         help="Force two-level (ICI then DCN) allreduce.")
    _add(params, "--hierarchical-allgather", dest="hierarchical_allgather",
         action="store_true",
         help="Force two-level (ICI then DCN) allgather.")

    timeline = parser.add_argument_group("timeline")
    _add(timeline, "--timeline-filename", dest="timeline_filename",
         help="Chrome-trace timeline output (rank 0).")
    _add(timeline, "--timeline-mark-cycles", dest="timeline_mark_cycles",
         action="store_true", help="Mark cycles in the timeline.")
    _add(timeline, "--merge-trace", dest="merge_trace", metavar="OUT",
         help="Merge Chrome trace files (per-rank timelines, device "
              "traces exported as Chrome JSON / .json.gz) into OUT and "
              "exit; inputs follow as positional arguments.")
    _add(timeline, "--merge-trace-align", dest="merge_trace_align",
         action="store_true",
         help="With --merge-trace: rebase each input's earliest event to "
              "a common origin (for traces not in the epoch clock "
              "domain).")

    metrics_group = parser.add_argument_group("metrics")
    _add(metrics_group, "--metrics-summary", dest="metrics_summary",
         action="store_true",
         help="Aggregate per-rank metrics dumps (written at shutdown when "
              "HOROVOD_METRICS_DUMP is set) into a cross-rank min/median/"
              "max table and exit; dump files (or directories containing "
              "metrics-rank-*.json) follow as positional arguments. Exits "
              "non-zero when no dump files are found.")

    flight = parser.add_argument_group("flight recorder")
    _add(flight, "--flight-recorder-dir", dest="flight_recorder_dir",
         help="Directory for per-rank flight-recorder dumps "
              "(flight-rank-N.json): workers write them on failure/exit, "
              "the launcher collects rendezvous-shipped copies for dead "
              "workers, and on a failed job a merged cross-rank "
              "postmortem is printed. Sets HOROVOD_FLIGHT_RECORDER_DIR.")
    _add(flight, "--postmortem", dest="postmortem", metavar="DIR",
         help="Print the merged cross-rank postmortem from the "
              "flight-recorder dumps in DIR and exit (non-zero when DIR "
              "holds no dumps).")

    profile = parser.add_argument_group("profiler")
    _add(profile, "--profile-dir", dest="profile_dir",
         help="Directory for per-rank step profiles. Sets "
              "HOROVOD_PROFILE_DIR (enabling the step profiler) and a "
              "per-rank HOROVOD_TIMELINE; after the job the launcher "
              "collects every rank's profile + timeline + device trace, "
              "merges them onto one clock-corrected Chrome trace "
              "(merged-trace.json), and prints a cross-rank step-time "
              "report naming the slowest rank and its dominant phase.")
    _add(profile, "--profile-report", dest="profile_report", metavar="DIR",
         help="Print the cross-rank step-time report from the profile "
              "dumps in DIR (re-merging the trace) and exit; non-zero "
              "when DIR holds no dumps.")

    autotune = parser.add_argument_group("autotune")
    _add(autotune, "--autotune", dest="autotune", action="store_true",
         help="Enable Bayesian autotuning of fusion/cycle parameters.")
    _add(autotune, "--autotune-log-file", dest="autotune_log_file",
         help="CSV log of autotune trials.")
    _add(autotune, "--autotune-warmup-samples", dest="autotune_warmup_samples",
         type=int, help="Discarded warmup samples per trial.")
    _add(autotune, "--autotune-steps-per-sample",
         dest="autotune_steps_per_sample", type=int,
         help="Steps per timing sample.")
    _add(autotune, "--autotune-bayes-opt-max-samples",
         dest="autotune_bayes_opt_max_samples", type=int,
         help="Max Bayesian-optimization samples.")
    _add(autotune, "--autotune-gaussian-process-noise",
         dest="autotune_gaussian_process_noise", type=float,
         help="GP noise regularization in [0, 1].")

    elastic_group = parser.add_argument_group("elastic (fault-tolerant)")
    _add(elastic_group, "--elastic", dest="elastic", action="store_true",
         help="Elastic mode: worker failures no longer kill the job; "
              "survivors re-form membership and resume from the last "
              "committed state (requires the training script to use "
              "hvd.elastic). Sets HOROVOD_ELASTIC=1 for workers.")
    _add(elastic_group, "--min-workers", dest="min_workers", type=int,
         help="Minimum workers an elastic job may shrink to (default 1); "
              "below this the job fails. Sets HOROVOD_ELASTIC_MIN_WORKERS.")
    _add(elastic_group, "--max-workers", dest="max_workers", type=int,
         help="Maximum workers an elastic job may grow to (discovered "
              "hosts beyond this are held in reserve).")
    _add(elastic_group, "--host-discovery-script",
         dest="host_discovery_script",
         help="Executable printing the current 'hostname[:slots]' set, one "
              "per line; polled by the elastic driver to add/remove "
              "hosts at runtime.")
    _add(elastic_group, "--supervise", dest="supervise",
         action="store_true",
         help="Supervised restarts: when the whole job fails (beyond "
              "what elastic re-forms absorb), relaunch it — resuming "
              "from the crash-consistent checkpoint directory "
              "(HOROVOD_CKPT_DIR) when the training script uses "
              "hvd.elastic state. Each attempt gets "
              "HOROVOD_RESTART_ATTEMPT=<n>; the restart lineage is "
              "recorded in the flight-recorder dir for --postmortem.")
    _add(elastic_group, "--restart-budget", dest="restart_budget",
         type=int,
         help="Maximum supervised relaunches before giving up "
              "(default 3; only with --supervise).")

    serving = parser.add_argument_group("online serving")
    _add(serving, "--serve", dest="serve", action="store_true",
         help="Launch each slot as a continuous-batching inference "
              "replica instead of a training worker (docs/inference.md). "
              "With no command, runs the built-in demo worker "
              "(python -m horovod_tpu.serve); with a command, the "
              "command is expected to call hvd.serve()/run_kv_replica. "
              "Replicas pull from the rendezvous-KV request queue and "
              "register heartbeats the dispatcher uses to redistribute "
              "work from dead replicas. HOROVOD_SERVE_* env knobs set "
              "the batching policy.")

    stall = parser.add_argument_group("stall check")
    _add(stall, "--no-stall-check", dest="no_stall_check",
         action="store_true", help="Disable the stall inspector.")
    _add(stall, "--stall-check-warning-time-seconds",
         dest="stall_check_warning_time_seconds", type=float,
         help="Seconds before a stall warning is logged.")
    _add(stall, "--stall-check-shutdown-time-seconds",
         dest="stall_check_shutdown_time_seconds", type=float,
         help="Seconds before a stall aborts the job (0 = never).")

    logging_group = parser.add_argument_group("logging")
    _add(logging_group, "--log-level", dest="log_level",
         choices=["trace", "debug", "info", "warning", "error", "fatal"],
         help="Runtime log level.")
    _add(logging_group, "--log-hide-timestamp", dest="log_hide_timestamp",
         action="store_true", help="Hide timestamps in log output.")

    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="Training command to run on every slot.")

    args = parser.parse_args(argv)
    if not hasattr(args, "seen_args"):
        args.seen_args = set()

    if args.config_file:
        config = config_parser.parse_config_file(args.config_file)
        config_parser.set_args_from_config_file(args, config)
    config_parser.validate_config_args(args)
    return args


def check_build(out=sys.stdout) -> None:
    """Capability report (reference: run/run.py:268-303 --check-build)."""
    import horovod_tpu as hvd
    from horovod_tpu.runtime.native import native_built

    def mark(flag: bool) -> str:
        return "[X]" if flag else "[ ]"

    out.write(textwrap.dedent(f"""\
        horovod_tpu v{__version__}:

        Available frameworks:
            {mark(True)} JAX
            {mark(_flax_available())} Flax

        Available controllers:
            {mark(True)} XLA (in-jit SPMD)
            {mark(native_built())} Socket (native TCP)

        Available tensor operations:
            {mark(hvd.xla_built())} XLA collectives (ICI/DCN)
            {mark(native_built())} Native host ring
            {mark(hvd.mpi_built())} MPI
            {mark(hvd.nccl_built())} NCCL
            {mark(hvd.gloo_built())} Gloo
        """))


def _flax_available() -> bool:
    try:
        import flax  # noqa: F401
        return True
    except ImportError:
        return False


# ---------------------------------------------------------------------------
# ssh reachability (reference: run/run.py:60-112, cached per run/run.py:49-60)
# ---------------------------------------------------------------------------

def _cache_path() -> str:
    return os.path.join(CACHE_DIR, "ssh_checks.json")


def check_all_hosts_ssh_successful(hostnames: List[str],
                                   ssh_port: Optional[int] = None,
                                   use_cache: bool = True) -> None:
    import json

    remote = [h for h in hostnames if not launcher.is_local_host(h)]
    if not remote:
        return

    cache = {}
    if use_cache and os.path.exists(_cache_path()):
        try:
            with open(_cache_path()) as f:
                cache = json.load(f)
        except (ValueError, OSError):
            cache = {}

    now = time.time()
    failed = []
    for host in remote:
        entry = cache.get(host)
        if entry and now - entry < CACHE_TTL_S:
            continue
        port_arg = f"-p {ssh_port}" if ssh_port else ""
        result = subprocess.run(
            f"ssh -o PasswordAuthentication=no -o StrictHostKeyChecking=no "
            f"{port_arg} {host} true",
            shell=True, capture_output=True,
            timeout=SSH_CHECK_TIMEOUT_S)
        if result.returncode == 0:
            cache[host] = now
        else:
            failed.append(host)

    if use_cache:
        os.makedirs(CACHE_DIR, exist_ok=True)
        with open(_cache_path(), "w") as f:
            json.dump(cache, f)

    if failed:
        raise RuntimeError(
            "passwordless ssh checked failed for hosts: "
            + ", ".join(failed)
            + ". Set up passwordless ssh or run single-host.")


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)

    if args.check_build:
        check_build()
        return 0

    command = list(args.command or [])
    if command and command[0] == "--":
        command = command[1:]
    if args.merge_trace:
        from horovod_tpu.timeline import merge_traces

        if not command:
            sys.stderr.write("tpurun --merge-trace: no input traces\n")
            return 2
        n = merge_traces(args.merge_trace, command,
                         align=args.merge_trace_align)
        print(f"merged {n} events from {len(command)} trace(s) into "
              f"{args.merge_trace}")
        return 0
    if args.metrics_summary:
        import glob as _glob

        from horovod_tpu.metrics import format_summary, summarize_dumps

        if not command:
            sys.stderr.write("tpurun --metrics-summary: no dump files\n")
            return 2
        # a directory argument stands for its metrics-rank-*.json dumps
        paths: List[str] = []
        for arg in command:
            if os.path.isdir(arg):
                paths.extend(sorted(_glob.glob(
                    os.path.join(arg, "metrics-rank-*.json"))))
            else:
                paths.append(arg)
        if not paths:
            sys.stderr.write("tpurun --metrics-summary: no metrics dump "
                             "files found\n")
            return 1
        try:
            rows = summarize_dumps(paths)
        except (OSError, ValueError, KeyError) as exc:
            sys.stderr.write(f"tpurun --metrics-summary: {exc}\n")
            return 2
        print(format_summary(rows, n_ranks=len(paths)))
        return 0
    if args.profile_report:
        from horovod_tpu import profiler

        dumps = profiler.load_dumps(args.profile_report)
        if not dumps:
            sys.stderr.write(f"tpurun --profile-report: no profile dumps "
                             f"found in {args.profile_report!r}\n")
            return 1
        try:
            merged_path, n_events = profiler.merge_profile_dir(
                args.profile_report)
        except (OSError, ValueError) as exc:
            sys.stderr.write(f"tpurun --profile-report: merge failed: "
                             f"{exc}\n")
            merged_path, n_events = None, 0
        print(profiler.format_step_report(dumps))
        spans = sum(len(d.get("request_spans", ())) for d in dumps)
        if spans:
            traces = {s.get("trace_id") for d in dumps
                      for s in d.get("request_spans", ())
                      if isinstance(s, dict) and s.get("trace_id")}
            print(f"tpurun: {spans} request/collective spans across "
                  f"{len(traces)} trace(s) merged into per-rank request "
                  f"lanes (docs/tracing.md)")
        if merged_path and n_events:
            print(f"tpurun: merged trace ({n_events} events) written to "
                  f"{merged_path}")
        return 0
    if args.postmortem:
        from horovod_tpu import flight_recorder

        dumps = flight_recorder.load_dumps(args.postmortem)
        if not dumps:
            sys.stderr.write(f"tpurun --postmortem: no flight-recorder "
                             f"dumps found in {args.postmortem!r}\n")
            return 1
        lineage = flight_recorder.load_restart_lineage(args.postmortem)
        print(flight_recorder.format_postmortem(dumps, lineage=lineage))
        return 0
    if getattr(args, "serve", False) and not command:
        # the serving plane's default worker: one KV-queue replica per
        # slot, identical random-weight demo model on every rank
        command = [sys.executable, "-m", "horovod_tpu.serve"]
    if not command:
        sys.stderr.write("tpurun: no command given\n")
        return 2

    if args.hostfile:
        host_infos = hosts_mod.parse_hostfile(args.hostfile)
    elif args.hosts:
        host_infos = hosts_mod.parse_hosts(args.hosts)
    else:
        nproc = args.np or 1
        host_infos = [hosts_mod.HostInfo("localhost", nproc)]
    np = args.np or sum(h.slots for h in host_infos)

    from horovod_tpu.run.backends import make_backend

    try:
        backend = make_backend(args.launch_backend, ssh_port=args.ssh_port,
                               gcloud_zone=args.gcloud_zone,
                               gcloud_project=args.gcloud_project)
    except ValueError as exc:  # bad HOROVOD_LAUNCH_BACKEND env value
        sys.stderr.write(f"tpurun: {exc}\n")
        return 2
    if backend.name == "ssh":
        # plain-ssh reachability only makes sense for the ssh backend —
        # gcloud-tpu-vm hosts are TPU VM names reached through gcloud
        check_all_hosts_ssh_successful(
            [h.hostname for h in host_infos], args.ssh_port,
            use_cache=not args.disable_cache)

    slots = hosts_mod.allocate(host_infos, np)
    if args.verbose:
        for s in slots:
            sys.stderr.write(f"tpurun: rank {s.rank} -> {s.hostname} "
                             f"(local {s.local_rank}/{s.local_size}, "
                             f"cross {s.cross_rank}/{s.cross_size})\n")

    env = dict(os.environ)
    env.update(config_parser.env_from_args(args))
    env["HOROVOD_NP"] = str(np)

    import shlex as _shlex

    elastic = bool(args.elastic)
    min_workers = args.min_workers or 1
    if elastic and args.min_workers and args.min_workers > np:
        sys.stderr.write(f"tpurun: --min-workers {args.min_workers} "
                         f"exceeds the launch size {np}\n")
        return 2

    command_str = " ".join(_shlex.quote(c) for c in command)
    launch_kwargs = dict(
        env=env, ssh_port=args.ssh_port,
        output_dir=args.output_dir,
        use_jax_distributed=not args.no_jax_distributed,
        start_timeout=args.start_timeout, backend=backend,
        elastic=elastic, min_workers=min_workers,
        max_workers=args.max_workers,
        discovery_script=args.host_discovery_script,
        flight_recorder_dir=args.flight_recorder_dir,
        profile_dir=args.profile_dir)
    if args.supervise:
        budget = (args.restart_budget if args.restart_budget is not None
                  else 3)
        launch_kwargs.pop("env")
        return launcher.launch_supervised(
            command_str, slots, restart_budget=budget, env=env,
            **launch_kwargs)
    return launcher.launch_job(command_str, slots, **launch_kwargs)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
