"""Generic driver/task TCP services.

TPU-native port of the reference's launcher service pair (reference:
horovod/run/common/service/driver_service.py, task_service.py;
run/common/util/network.py): small request/response servers speaking the
HMAC-authenticated pickle ``Wire`` (util.py). The driver runs next to
``tpurun``; one task service runs on every host to (a) prove the host is
reachable, (b) report its routable addresses (the reference's NIC-discovery
ring, run/run.py:195-265), and (c) execute commands on behalf of the driver
(the Spark-style launch path).
"""

from __future__ import annotations

import dataclasses
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.run import util


# -- request types (reference: driver_service/task_service message classes) --

@dataclasses.dataclass
class RegisterTaskRequest:
    index: int
    addresses: List[Tuple[str, int]]
    host_hash: str
    # which of the driver's candidate addresses this task actually reached
    # (NIC discovery: the proven-routable driver address wins over the
    # gethostbyname guess; reference: run/run.py:195-265)
    driver_addr_used: Optional[Tuple[str, int]] = None
    # the FULL reachable subset — the driver must pick an address common
    # to every host (intersection, like the reference's common_intfs),
    # not a majority winner a minority host provably cannot reach
    driver_addrs_reachable: Optional[List[Tuple[str, int]]] = None


@dataclasses.dataclass
class AllTaskAddressesRequest:
    index: int


@dataclasses.dataclass
class RunCommandRequest:
    command: str
    env: dict


@dataclasses.dataclass
class CommandExitCodeRequest:
    pass


@dataclasses.dataclass
class PingRequest:
    pass


@dataclasses.dataclass
class ProbeAddressesRequest:
    """Ask a task to probe candidate (ip, port) addresses of its ring
    successor and report the reachable subset (reference: task_fn.py:24-50
    — tasks ping each other in a ring to weed out NAT'ed/dead
    interfaces). ``dial_timeout`` bounds each candidate dial on the task
    side (propagated from the driver's probe-timeout knob so one setting
    governs every dial in the probe)."""

    addresses: List[Tuple[str, int]]
    dial_timeout: float = 3.0


@dataclasses.dataclass
class ShutdownServiceRequest:
    pass


@dataclasses.dataclass
class OkResponse:
    payload: object = None


@dataclasses.dataclass
class ErrorResponse:
    message: str = ""


def local_addresses(port: int) -> List[Tuple[str, int]]:
    """All non-loopback addresses this host answers on, plus loopback as a
    fallback — the launcher intersects these across hosts the way the
    reference's ring probe intersects NICs (run/run.py:195-265)."""
    addrs: List[Tuple[str, int]] = []
    try:
        host = socket.gethostname()
        for info in socket.getaddrinfo(host, None, socket.AF_INET):
            ip = info[4][0]
            if (ip, port) not in addrs:
                addrs.append((ip, port))
    except socket.gaierror:
        pass
    # address used for a default route, if any
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
            if (ip, port) not in addrs:
                addrs.insert(0, (ip, port))
    except OSError:
        pass
    if ("127.0.0.1", port) not in addrs:
        addrs.append(("127.0.0.1", port))
    return addrs


class _WireHandler(socketserver.StreamRequestHandler):
    def handle(self):
        wire: util.Wire = self.server.wire  # type: ignore[attr-defined]
        try:
            req = wire.read(self.rfile)
        except (EOFError, IOError):
            return
        try:
            resp = self.server.service._handle(req)  # type: ignore
        except Exception as e:  # noqa: BLE001 — ship the error to the caller
            resp = ErrorResponse(str(e))
        try:
            wire.write(resp, self.wfile)
        except (BrokenPipeError, IOError):
            pass


class BasicService:
    """Threaded TCP service with the HMAC wire protocol."""

    def __init__(self, key: bytes, port: int = 0):
        self._key = key
        self.shutdown_requested = threading.Event()
        self._server = socketserver.ThreadingTCPServer(
            ("0.0.0.0", port), _WireHandler, bind_and_activate=True)
        self._server.daemon_threads = True
        self._server.wire = util.Wire(key)  # type: ignore[attr-defined]
        self._server.service = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def _handle(self, req):
        if isinstance(req, PingRequest):
            return OkResponse()
        if isinstance(req, ShutdownServiceRequest):
            # acknowledge first; the owner (task agent) tears down the
            # server after seeing the event
            self.shutdown_requested.set()
            return OkResponse()
        return ErrorResponse(f"unhandled request {type(req).__name__}")


class DriverService(BasicService):
    """Collects task registrations (reference:
    run/common/service/driver_service.py)."""

    def __init__(self, key: bytes, num_tasks: int, port: int = 0):
        self._lock = threading.Lock()
        self._tasks: Dict[int, RegisterTaskRequest] = {}
        self._all_registered = threading.Event()
        self._num_tasks = num_tasks
        super().__init__(key, port)

    def _handle(self, req):
        if isinstance(req, RegisterTaskRequest):
            with self._lock:
                self._tasks[req.index] = req
                if len(self._tasks) >= self._num_tasks:
                    self._all_registered.set()
            return OkResponse()
        if isinstance(req, AllTaskAddressesRequest):
            with self._lock:
                task = self._tasks.get(req.index)
            if task is None:
                return ErrorResponse(f"task {req.index} not registered")
            return OkResponse(task.addresses)
        return super()._handle(req)

    def wait_for_initial_registration(self, timeout: util.Timeout) -> None:
        while not self._all_registered.wait(timeout=0.1):
            timeout.check()

    def task_addresses(self) -> Dict[int, List[Tuple[str, int]]]:
        with self._lock:
            return {i: t.addresses for i, t in self._tasks.items()}

    def task_host_hashes(self) -> Dict[int, str]:
        with self._lock:
            return {i: t.host_hash for i, t in self._tasks.items()}

    def task_driver_addrs(self) -> Dict[int, Optional[Tuple[str, int]]]:
        """Which driver address each task registered through (NIC
        discovery input)."""
        with self._lock:
            return {i: t.driver_addr_used for i, t in self._tasks.items()}

    def task_driver_reachable(self) -> Dict[int, list]:
        """Each task's full reachable-driver-address subset (falls back
        to the single registration address for agents that did not probe
        the full set)."""
        with self._lock:
            out = {}
            for i, t in self._tasks.items():
                if t.driver_addrs_reachable:
                    out[i] = [tuple(a) for a in t.driver_addrs_reachable]
                elif t.driver_addr_used:
                    out[i] = [tuple(t.driver_addr_used)]
                else:
                    out[i] = []
            return out


class TaskService(BasicService):
    """Per-host agent: registers with the driver, can run commands
    (reference: run/common/service/task_service.py:155)."""

    def __init__(self, key: bytes, index: int, port: int = 0):
        self.index = index
        self._command_proc = None
        self._command_lock = threading.Lock()
        super().__init__(key, port)

    def _handle(self, req):
        if isinstance(req, RunCommandRequest):
            import subprocess

            with self._command_lock:
                if self._command_proc is not None:
                    return ErrorResponse("command already running")
                self._command_proc = subprocess.Popen(
                    req.command, shell=True, env=req.env,
                    start_new_session=True)
            return OkResponse()
        if isinstance(req, CommandExitCodeRequest):
            with self._command_lock:
                proc = self._command_proc
            if proc is None:
                return OkResponse(None)
            return OkResponse(proc.poll())
        if isinstance(req, ProbeAddressesRequest):
            return OkResponse(probe_reachable(req.addresses, self._key,
                                              timeout=req.dial_timeout))
        return super()._handle(req)

    def register(self, driver_addr: Tuple[str, int], key: bytes,
                 timeout: Optional[util.Timeout] = None) -> None:
        req = RegisterTaskRequest(
            self.index, local_addresses(self.port), util.host_hash(),
            driver_addr_used=driver_addr)
        client = ServiceClient(driver_addr, key)
        timeout = timeout or util.Timeout(60, "driver registration")
        while True:
            try:
                client.call(req)
                return
            except (ConnectionError, OSError):
                timeout.check()
                time.sleep(0.2)

    def register_any(self, driver_addrs: List[Tuple[str, int]], key: bytes,
                     timeout: Optional[util.Timeout] = None
                     ) -> Tuple[str, int]:
        """Probe ALL the driver's candidate addresses, then register via
        the first reachable one, reporting the full reachable subset (the
        driver intersects these across hosts to pick the rendezvous
        address every host can actually route to)."""
        timeout = timeout or util.Timeout(60, "driver registration")
        while True:
            reachable = probe_reachable(driver_addrs, key)
            for addr in reachable:
                req = RegisterTaskRequest(
                    self.index, local_addresses(self.port),
                    util.host_hash(), driver_addr_used=tuple(addr),
                    driver_addrs_reachable=[tuple(a) for a in reachable])
                try:
                    ServiceClient(addr, key, timeout=3.0).call(req)
                    return tuple(addr)
                except (ConnectionError, OSError):
                    continue
            timeout.check()
            time.sleep(0.2)


def probe_reachable(addresses: List[Tuple[str, int]],
                    key: bytes, timeout: float = 3.0
                    ) -> List[Tuple[str, int]]:
    """Authenticated-ping each candidate address; return the subset that
    answered. An HMAC-verified pong proves the address routes to a live
    peer service, not a NAT artifact (reference: task_fn.py match_intf)."""
    good: List[Tuple[str, int]] = []
    for addr in addresses:
        try:
            ServiceClient(tuple(addr), key, timeout=timeout).call(
                PingRequest())
            good.append(tuple(addr))
        except Exception:
            continue
    return good


class ServiceClient:
    """One-shot request/response client for BasicService servers."""

    def __init__(self, addr: Tuple[str, int], key: bytes,
                 timeout: float = 10.0):
        self._addr = addr
        self._wire = util.Wire(key)
        self._timeout = timeout

    def call(self, req, timeout: float = None):
        """One request/response round trip. ``timeout`` overrides the
        client default for this call only — a probe-verified client can
        issue a longer follow-up request (e.g. one that makes the task
        dial further peers) without constructing a second, unverified
        client (advisor r3)."""
        if timeout is None:
            timeout = self._timeout
        with socket.create_connection(self._addr, timeout=timeout) as s:
            rfile = s.makefile("rb")
            wfile = s.makefile("wb")
            self._wire.write(req, wfile)
            resp = self._wire.read(rfile)
        if isinstance(resp, ErrorResponse):
            raise RuntimeError(f"service error: {resp.message}")
        return resp.payload if isinstance(resp, OkResponse) else resp
