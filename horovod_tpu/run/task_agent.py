"""Per-host NIC-discovery agent, spawned over ssh by the launcher.

TPU-native analogue of the reference's ``task_fn`` executable (reference:
horovod/run/task_fn.py:24-63, spawned by run.py:143-171): starts a
:class:`TaskService`, registers its candidate addresses with the driver
(reporting which driver address proved reachable), answers ring-probe
requests from the driver, and exits on ``ShutdownServiceRequest``.

Usage (what ``discovery._ssh_agent`` generates)::

    echo <key-hex> | python -m horovod_tpu.run.task_agent \
        <index> <num_hosts> <driver_host:port,...> <timeout_seconds> \
        --key-stdin

With ``--key-stdin`` the HMAC key arrives as one hex line on stdin (the
launcher pipes it through ssh) so it never appears on a command line or
in ``ps`` output; without the flag it falls back to the
``HOROVOD_TASK_KEY`` environment variable (in-process/test use).
"""

from __future__ import annotations

import os
import sys

from horovod_tpu.run import util
from horovod_tpu.run.service import TaskService


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    key_stdin = "--key-stdin" in argv
    argv = [a for a in argv if a != "--key-stdin"]
    if len(argv) != 4:
        print("usage: task_agent <index> <num_hosts> <driver_addrs> "
              "<timeout_s> [--key-stdin]", file=sys.stderr)
        return 2
    index = int(argv[0])
    timeout_s = float(argv[3])
    driver_addrs = []
    for part in argv[2].split(","):
        host, port = part.rsplit(":", 1)
        driver_addrs.append((host, int(port)))
    if key_stdin:
        line = sys.stdin.readline().strip()
        if not line:
            print("task_agent: --key-stdin given but no key arrived on "
                  "stdin (transport dropped before delivering it?)",
                  file=sys.stderr)
            return 2
        key = bytes.fromhex(line)
    else:
        key = bytes.fromhex(os.environ["HOROVOD_TASK_KEY"])

    task = TaskService(key, index)
    try:
        task.register_any(driver_addrs, key,
                          util.Timeout(timeout_s, "driver registration"))
        if not task.shutdown_requested.wait(timeout=timeout_s):
            print(f"task_agent {index}: no shutdown signal within "
                  f"{timeout_s}s, exiting", file=sys.stderr)
            return 1
        return 0
    finally:
        task.shutdown()


if __name__ == "__main__":
    sys.exit(main())
