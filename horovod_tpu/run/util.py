"""Launcher utility belt: secrets, wire codec, process exec, host hashing.

TPU-native equivalents of the reference's ``horovod/run/common/util/``
modules (reference: secret.py, codec.py, network.py, safe_shell_exec.py,
host_hash.py, timeout.py — SURVEY.md §2.6). Same responsibilities, no
cloudpickle dependency (stdlib pickle + HMAC-SHA256).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import pickle
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Optional

# ---------------------------------------------------------------------------
# secret (reference: run/common/util/secret.py:26-36)
# ---------------------------------------------------------------------------

SECRET_LENGTH = 32
SECRET_ENV = "HOROVOD_SECRET_KEY"


def make_secret_key() -> bytes:
    """Per-run random key used to HMAC every launcher wire message."""
    return os.urandom(SECRET_LENGTH)


def encode_secret(key: bytes) -> str:
    return base64.b64encode(key).decode("ascii")


def decode_secret(text: str) -> bytes:
    return base64.b64decode(text.encode("ascii"))


# ---------------------------------------------------------------------------
# codec (reference: run/common/util/codec.py)
# ---------------------------------------------------------------------------

def dumps_base64(obj) -> str:
    return base64.b64encode(pickle.dumps(obj)).decode("ascii")


def loads_base64(text: str):
    return pickle.loads(base64.b64decode(text.encode("ascii")))


# ---------------------------------------------------------------------------
# HMAC'd message framing (reference: run/common/util/network.py:50-84 — the
# ``Wire`` class: every payload is followed by an HMAC-SHA256 digest keyed
# with the per-run secret; receivers verify before unpickling)
# ---------------------------------------------------------------------------

class Wire:
    """Length-prefixed, HMAC-authenticated pickle framing over a socket
    file. Authenticating before unpickling is what makes the launcher's
    TCP services safe to expose on cluster networks."""

    def __init__(self, key: bytes):
        self._key = key

    def write(self, obj, wfile) -> None:
        payload = pickle.dumps(obj)
        digest = hmac.new(self._key, payload, hashlib.sha256).digest()
        wfile.write(len(payload).to_bytes(8, "big"))
        wfile.write(digest)
        wfile.write(payload)
        wfile.flush()

    def read(self, rfile):
        header = _read_exactly(rfile, 8)
        length = int.from_bytes(header, "big")
        if length > (1 << 31):
            raise IOError(f"wire message too large: {length}")
        digest = _read_exactly(rfile, 32)
        payload = _read_exactly(rfile, length)
        expected = hmac.new(self._key, payload, hashlib.sha256).digest()
        if not hmac.compare_digest(digest, expected):
            raise IOError("wire message failed HMAC verification")
        return pickle.loads(payload)


def _read_exactly(rfile, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise EOFError("connection closed mid-message")
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# host hash (reference: run/common/util/host_hash.py:38) — ranks on the same
# node must agree on a node identity for local_rank assignment
# ---------------------------------------------------------------------------

def host_hash(salt: str = "") -> str:
    hostname = socket.gethostname()
    return hashlib.md5((hostname + salt).encode()).hexdigest()


# ---------------------------------------------------------------------------
# timeout helper (reference: run/common/util/timeout.py:32)
# ---------------------------------------------------------------------------

class Timeout:
    def __init__(self, timeout_sec: float, message: str = "operation"):
        self._deadline = time.monotonic() + timeout_sec
        self._message = message

    def remaining(self) -> float:
        return max(0.0, self._deadline - time.monotonic())

    def timed_out(self) -> bool:
        return time.monotonic() >= self._deadline

    def check(self) -> None:
        if self.timed_out():
            raise TimeoutError(
                f"{self._message} timed out. This may indicate that a host "
                f"is unreachable or the job failed to start; check "
                f"connectivity and per-rank logs.")


# ---------------------------------------------------------------------------
# safe shell exec (reference: run/common/util/safe_shell_exec.py:29-57).
# The reference interposes a middleman process that forwards signals and
# kills the whole process tree; on Linux we get the same guarantee with a
# dedicated session (setsid) + killpg.
# ---------------------------------------------------------------------------

GRACEFUL_TERMINATION_TIME_S = 5


def execute(command, env: Optional[dict] = None, stdout=None, stderr=None,
            index: Optional[int] = None, events=None,
            prefix_output: bool = True) -> int:
    """Run ``command`` (shell string) in its own process group, streaming
    output to ``stdout``/``stderr`` (file-like), optionally prefixed with
    ``[index]<tag>`` per line like mpirun --tag-output. ``events`` is a list
    of ``threading.Event``; when any fires, the process tree is terminated
    (SIGTERM, then SIGKILL after a grace period)."""
    proc = subprocess.Popen(
        command, shell=True, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)

    stop = threading.Event()
    watchers = []
    for event in (events or []):
        t = threading.Thread(
            target=_wait_then_kill, args=(event, stop, proc), daemon=True)
        t.start()
        watchers.append(t)

    pumps = []
    for src, dst, tag in ((proc.stdout, stdout or sys.stdout, "stdout"),
                          (proc.stderr, stderr or sys.stderr, "stderr")):
        t = threading.Thread(
            target=_pump, args=(src, dst, index, tag, prefix_output),
            daemon=True)
        t.start()
        pumps.append(t)

    try:
        proc.wait()
    finally:
        stop.set()
    for t in pumps:
        t.join(timeout=5)
    return proc.returncode


def _wait_then_kill(event: threading.Event, stop: threading.Event, proc):
    while not stop.is_set():
        if event.wait(timeout=0.1):
            break
    if stop.is_set() or proc.poll() is not None:
        return
    terminate_tree(proc)


def terminate_tree(proc) -> None:
    """SIGTERM the process group, escalate to SIGKILL after the grace
    period (reference: safe_shell_exec's tree-kill contract)."""
    try:
        pgid = os.getpgid(proc.pid)
    except (ProcessLookupError, PermissionError):
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.1)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def _pump(src, dst, index, tag, prefix: bool) -> None:
    try:
        for raw in iter(src.readline, b""):
            line = raw.decode("utf-8", errors="replace")
            if prefix and index is not None:
                line = f"[{index}]<{tag}>: {line}"
            try:
                dst.write(line)
                dst.flush()
            except ValueError:  # closed file
                return
    except Exception:
        pass
