"""Controller: the coordination plane for dynamic per-tensor negotiation.

TPU-native analogue of the reference's rank-0-coordinator protocol
(reference: horovod/common/controller.cc/.h — the protocol is documented at
controller.h:62-96): every cycle, each worker announces which named tensors
it has enqueued; the coordinator determines which tensors are ready on ALL
workers, validates their metadata matches, fuses them into batched
responses, and broadcasts the final ordered response list that every worker
then executes identically. This is what lets callers enqueue tensors in
different orders on different workers and still execute collectives in one
agreed order.

Transport verbs are abstract (reference: controller.h:34-124 ``Bcast``,
``RecvReadyTensors``, ``CrossRankBitwiseAnd/Or``):

* ``LocalController`` — single-process (all workers are local devices):
  negotiation is trivially satisfied; the cache/fusion machinery still runs
  so that steady-state behavior (fast path, bin-packing) is identical.
* ``SocketController`` (runtime/socket_controller.py) — one process per
  host over TCP, the analogue of the reference's Gloo controller.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from horovod_tpu.runtime import fusion
from horovod_tpu.runtime import message as msg
from horovod_tpu.runtime import types
from horovod_tpu.runtime.response_cache import (CacheCoordinator, CacheState,
                                                ResponseCache)
from horovod_tpu.utils import logging as log


class MessageTable:
    """name -> requests received so far (reference: MessageTable +
    IncrementTensorCount, controller.cc:700-723)."""

    def __init__(self):
        self._table: Dict[str, List[msg.Request]] = {}

    def increment(self, request: msg.Request, world: int) -> bool:
        """Record one worker's announcement; True when all workers have
        announced this tensor."""
        reqs = self._table.setdefault(request.tensor_name, [])
        reqs.append(request)
        return len(reqs) == world

    def pop(self, name: str) -> List[msg.Request]:
        return self._table.pop(name, [])

    def pending(self) -> Dict[str, List[msg.Request]]:
        return self._table

    def __len__(self) -> int:
        return len(self._table)


def construct_response(requests: List[msg.Request]) -> msg.Response:
    """Validate that every worker announced compatible metadata and build
    the verdict (reference: ConstructResponse, controller.cc:320-522 —
    mismatched dtype/shape/root across ranks becomes an ERROR response that
    surfaces as an exception on every worker)."""
    first = requests[0]
    name = first.tensor_name

    for r in requests[1:]:
        if r.request_type != first.request_type:
            return msg.Response(
                types.ERROR, [name],
                f"Mismatched collective operations: one worker requested "
                f"{first.request_type.lower()}, another requested "
                f"{r.request_type.lower()}.")
        if r.dtype != first.dtype:
            return msg.Response(
                types.ERROR, [name],
                f"Mismatched data types: one worker sent {first.dtype}, "
                f"another sent {r.dtype}.")

    if first.request_type == types.ALLREDUCE:
        for r in requests[1:]:
            if r.shape != first.shape:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched allreduce tensor shapes: {first.shape} vs "
                    f"{r.shape}.")
            if r.average != first.average:
                return msg.Response(
                    types.ERROR, [name],
                    "Mismatched allreduce reduction ops across workers.")
        return msg.Response(types.ALLREDUCE, [name])

    if first.request_type == types.ALLGATHER:
        for r in requests[1:]:
            if len(r.shape) != len(first.shape) or r.shape[1:] != first.shape[1:]:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched allgather tensor shapes: all dimensions "
                    f"except the first must match ({first.shape} vs "
                    f"{r.shape}).")
        # per-rank first-dim sizes, in rank order (reference:
        # controller.cc allgather recvcounts)
        by_rank = sorted(requests, key=lambda r: r.rank)
        sizes = [r.shape[0] if r.shape else 1 for r in by_rank]
        return msg.Response(types.ALLGATHER, [name], tensor_sizes=sizes)

    if first.request_type == types.BROADCAST:
        for r in requests[1:]:
            if r.root_rank != first.root_rank:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched broadcast root ranks: {first.root_rank} vs "
                    f"{r.root_rank}.")
            if r.shape != first.shape:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched broadcast tensor shapes: {first.shape} vs "
                    f"{r.shape}.")
        return msg.Response(types.BROADCAST, [name])

    return msg.Response(types.ERROR, [name],
                        f"Unknown request type {first.request_type}.")


class Controller:
    """Base negotiation engine over abstract transport verbs."""

    def __init__(self, rank: int, world: int, cache_capacity: int = 1024):
        self.rank = rank
        self.world = world
        self.cache = ResponseCache(cache_capacity)
        self.message_table = MessageTable()  # coordinator only
        self._should_shut_down = False
        # requests seen this cycle, for fusion byte accounting + cache put
        self._cycle_requests: Dict[str, msg.Request] = {}

    # -- transport verbs (reference: controller.h:98-124) ------------------
    def sync_bitvectors(self, bits: int) -> Tuple[int, int]:
        """Return (AND-reduced, OR-reduced) bitvectors across workers
        (reference: CrossRankBitwiseAnd/Or)."""
        raise NotImplementedError

    def send_ready_tensors(self, requests: List[msg.Request]
                           ) -> Optional[List[List[msg.Request]]]:
        """Workers send their ready lists; on the coordinator this returns
        every worker's list (reference: RecvReadyTensors / SendReadyTensors)."""
        raise NotImplementedError

    def bcast_responses(self, responses: Optional[List[msg.Response]]
                        ) -> List[msg.Response]:
        """Coordinator broadcasts the final list; workers receive it
        (reference: SendFinalTensors / RecvFinalTensors)."""
        raise NotImplementedError

    def barrier(self) -> None:
        raise NotImplementedError

    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    def request_shutdown(self) -> None:
        self._should_shut_down = True

    # -- the cycle (reference: ComputeResponseList, controller.cc:54-298) --
    def compute_response_list(
        self, requests: List[msg.Request], fusion_threshold: int,
        timeline=None, stall_inspector=None,
    ) -> Tuple[List[msg.Response], bool]:
        """Returns (responses_to_execute, should_shut_down)."""
        coordinator = CacheCoordinator()
        hit_bits: List[int] = []
        uncached: List[msg.Request] = []

        for r in requests:
            self._cycle_requests[r.tensor_name] = r
            state = self.cache.cached(r)
            if state == CacheState.HIT:
                bit = self.cache.bit_for_name(r.tensor_name)
                coordinator.record_hit(bit)
                hit_bits.append(bit)
            else:
                if state == CacheState.INVALID:
                    self.cache.invalidate(r.tensor_name)
                    coordinator.set_invalid_in_queue()
                coordinator.set_uncached_in_queue()
                uncached.append(r)

        if self._should_shut_down:
            coordinator.set_should_shut_down()

        anded, ored = self.sync_bitvectors(coordinator.bitvector)
        shut_down, any_uncached, _ = CacheCoordinator.flags(ored)

        responses: List[msg.Response] = []

        common_bits = set(CacheCoordinator.common_hits(anded))
        # Hits not common to all workers stay queued for later cycles:
        # their requests were already recorded; re-enqueue them next cycle.
        deferred = [b for b in hit_bits if b not in common_bits]

        if not any_uncached:
            # FAST PATH (reference: controller.cc:151-179): everything
            # queued everywhere is cached — responses straight from cache.
            for bit in sorted(common_bits):
                resp = self.cache.get_by_bit(bit)
                if resp is not None:
                    responses.append(resp)
            fused = fusion.fuse_responses(responses, self._cycle_requests,
                                          fusion_threshold)
            self._gc_cycle_requests(fused, deferred)
            return fused, shut_down

        # SLOW PATH: full negotiation for uncached tensors; common cache
        # hits still execute this cycle from the cache.
        for bit in sorted(common_bits):
            resp = self.cache.get_by_bit(bit)
            if resp is not None:
                responses.append(resp)

        gathered = self.send_ready_tensors(uncached)
        final: Optional[List[msg.Response]] = None
        if self.is_coordinator:
            assert gathered is not None
            ready_names: List[str] = []
            for worker_requests in gathered:
                for r in worker_requests:
                    if timeline is not None:
                        if r.tensor_name not in self.message_table.pending():
                            timeline.negotiate_start(r.tensor_name,
                                                     r.request_type)
                        timeline.negotiate_rank_ready(r.tensor_name, r.rank)
                    if self.message_table.increment(r, self.world):
                        ready_names.append(r.tensor_name)
            if stall_inspector is not None:
                shut_down = stall_inspector.check(
                    self.message_table, self.cache,
                    world=self.world) or shut_down
            negotiated: List[msg.Response] = []
            for name in ready_names:
                reqs = self.message_table.pop(name)
                if timeline is not None:
                    timeline.negotiate_end(name)
                negotiated.append(construct_response(reqs))
            final = responses + negotiated

        agreed = self.bcast_responses(final)
        # cache puts for newly negotiated single-tensor responses
        for resp in agreed:
            if resp.response_type == types.ERROR:
                continue
            for name in resp.tensor_names:
                req = self._cycle_requests.get(name)
                if req is not None and self.cache.cached(req) != CacheState.HIT:
                    self.cache.put(
                        msg.Response(resp.response_type, [name],
                                     tensor_sizes=resp.tensor_sizes), req)

        fused = fusion.fuse_responses(agreed, self._cycle_requests,
                                      fusion_threshold)
        self._gc_cycle_requests(fused, deferred)
        return fused, shut_down

    def _gc_cycle_requests(self, executed: List[msg.Response],
                           deferred_bits: List[int]) -> None:
        keep = set()
        for bit in deferred_bits:
            resp = self.cache.get_by_bit(bit)
            if resp is not None:
                keep.update(resp.tensor_names)
        executed_names = {n for r in executed for n in r.tensor_names}
        self._cycle_requests = {
            k: v for k, v in self._cycle_requests.items()
            if k in keep and k not in executed_names
        }

    def take_deferred(self) -> List[msg.Request]:
        """Drain tensors announced but not yet agreed (cache hits not yet
        common to all workers) so the cycle loop RE-ANNOUNCES them with the
        new cycle's requests — without this they would hang forever on
        workers that announced early."""
        out = list(self._cycle_requests.values())
        self._cycle_requests = {}
        return out

    def has_deferred(self) -> bool:
        return bool(self._cycle_requests)


class LocalController(Controller):
    """Single-process controller: every enqueued tensor is trivially ready
    on all workers (they share the process); negotiation verbs are
    identities. The cache/fusion path is identical to the distributed
    controllers so tests of fast-path/fusion semantics transfer."""

    def sync_bitvectors(self, bits: int) -> Tuple[int, int]:
        return bits, bits

    def send_ready_tensors(self, requests):
        return [requests]

    def bcast_responses(self, responses):
        assert responses is not None
        return responses

    def barrier(self) -> None:
        pass
