"""Controller: the coordination plane for dynamic per-tensor negotiation.

TPU-native analogue of the reference's rank-0-coordinator protocol
(reference: horovod/common/controller.cc/.h — the protocol is documented at
controller.h:62-96): every cycle, each worker announces which named tensors
it has enqueued; the coordinator determines which tensors are ready on ALL
workers, validates their metadata matches, fuses them into batched
responses, and broadcasts the final ordered response list that every worker
then executes identically. This is what lets callers enqueue tensors in
different orders on different workers and still execute collectives in one
agreed order.

Transport verbs are abstract (reference: controller.h:34-124 ``Bcast``,
``RecvReadyTensors``, ``CrossRankBitwiseAnd/Or``):

* ``LocalController`` — single-process (all workers are local devices):
  negotiation is trivially satisfied; the cache/fusion machinery still runs
  so that steady-state behavior (fast path, bin-packing) is identical.
* ``SocketController`` (runtime/socket_controller.py) — one process per
  host over TCP, the analogue of the reference's Gloo controller.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu import flight_recorder
from horovod_tpu.runtime import fusion
from horovod_tpu.runtime import message as msg
from horovod_tpu.runtime import types
from horovod_tpu.runtime.response_cache import (CacheCoordinator, CacheState,
                                                make_response_cache)
from horovod_tpu.utils import logging as log
from horovod_tpu.utils import resilience


class MessageTable:
    """name -> requests received so far (reference: MessageTable +
    IncrementTensorCount, controller.cc:700-723)."""

    def __init__(self):
        self._table: Dict[str, List[msg.Request]] = {}
        # name -> monotonic time of the FIRST announcement — the stall
        # inspector's age baseline (reference: stall_inspector.cc stamps
        # on IncrementTensorCount, not on its own scan)
        self._first_request_time: Dict[str, float] = {}
        # name -> {rank: monotonic arrival time} — straggler attribution:
        # which rank announced when (resolution = one controller cycle)
        self._arrivals: Dict[str, Dict[int, float]] = {}

    def increment(self, request: msg.Request, world: int) -> bool:
        """Record one worker's announcement; True when all workers have
        announced this tensor."""
        now = time.monotonic()
        reqs = self._table.setdefault(request.tensor_name, [])
        if not reqs:
            self._first_request_time[request.tensor_name] = now
        self._arrivals.setdefault(request.tensor_name, {})[request.rank] = now
        reqs.append(request)
        return len(reqs) == world

    def pop(self, name: str) -> List[msg.Request]:
        self._first_request_time.pop(name, None)
        self._arrivals.pop(name, None)
        return self._table.pop(name, [])

    def first_request_time(self, name: str) -> Optional[float]:
        """Monotonic timestamp of the first announcement for ``name``, or
        None if the tensor is not pending."""
        return self._first_request_time.get(name)

    def arrivals(self, name: str) -> Dict[int, float]:
        """Per-rank monotonic arrival stamps for ``name`` (empty dict when
        the tensor is not pending)."""
        return self._arrivals.get(name, {})

    def pending(self) -> Dict[str, List[msg.Request]]:
        return self._table

    def __len__(self) -> int:
        return len(self._table)


def construct_response(requests: List[msg.Request]) -> msg.Response:
    """Validate that every worker announced compatible metadata and build
    the verdict (reference: ConstructResponse, controller.cc:320-522 —
    mismatched dtype/shape/root across ranks becomes an ERROR response that
    surfaces as an exception on every worker)."""
    first = requests[0]
    name = first.tensor_name

    for r in requests[1:]:
        if r.request_type != first.request_type:
            return msg.Response(
                types.ERROR, [name],
                f"Mismatched collective operations: one worker requested "
                f"{first.request_type.lower()}, another requested "
                f"{r.request_type.lower()}.")
        if r.dtype != first.dtype:
            return msg.Response(
                types.ERROR, [name],
                f"Mismatched data types: one worker sent {first.dtype}, "
                f"another sent {r.dtype}.")

    if first.request_type == types.ALLREDUCE:
        for r in requests[1:]:
            if r.shape != first.shape:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched allreduce tensor shapes: {first.shape} vs "
                    f"{r.shape}.")
            if r.reduce_op != first.reduce_op:
                return msg.Response(
                    types.ERROR, [name],
                    "Mismatched allreduce reduction ops across workers.")
        return msg.Response(types.ALLREDUCE, [name])

    if first.request_type == types.ALLGATHER:
        for r in requests[1:]:
            if len(r.shape) != len(first.shape) or r.shape[1:] != first.shape[1:]:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched allgather tensor shapes: all dimensions "
                    f"except the first must match ({first.shape} vs "
                    f"{r.shape}).")
        # per-rank first-dim sizes, in rank order (reference:
        # controller.cc allgather recvcounts)
        by_rank = sorted(requests, key=lambda r: r.rank)
        sizes = [r.shape[0] if r.shape else 1 for r in by_rank]
        return msg.Response(types.ALLGATHER, [name], tensor_sizes=sizes)

    if first.request_type == types.BROADCAST:
        for r in requests[1:]:
            if r.root_rank != first.root_rank:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched broadcast root ranks: {first.root_rank} vs "
                    f"{r.root_rank}.")
            if r.shape != first.shape:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched broadcast tensor shapes: {first.shape} vs "
                    f"{r.shape}.")
        return msg.Response(types.BROADCAST, [name])

    if first.request_type == types.REDUCESCATTER:
        world = len(requests)
        for r in requests[1:]:
            if r.shape != first.shape:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched reducescatter tensor shapes: "
                    f"{first.shape} vs {r.shape}.")
            if r.reduce_op != first.reduce_op:
                return msg.Response(
                    types.ERROR, [name],
                    "Mismatched reducescatter reduction ops across "
                    "workers.")
        if not first.shape or first.shape[0] % world != 0:
            return msg.Response(
                types.ERROR, [name],
                f"reducescatter dim 0 ({first.shape[0] if first.shape else 0}) "
                f"must divide evenly by the world size ({world}).")
        return msg.Response(types.REDUCESCATTER, [name])

    if first.request_type == types.ALLTOALL:
        world = len(requests)
        for r in requests[1:]:
            if r.shape != first.shape:
                return msg.Response(
                    types.ERROR, [name],
                    f"Mismatched alltoall tensor shapes: {first.shape} vs "
                    f"{r.shape} (equal splits require identical shapes).")
        if not first.shape or first.shape[0] % world != 0:
            return msg.Response(
                types.ERROR, [name],
                f"alltoall dim 0 ({first.shape[0] if first.shape else 0}) "
                f"must divide evenly by the world size ({world}).")
        return msg.Response(types.ALLTOALL, [name])

    return msg.Response(types.ERROR, [name],
                        f"Unknown request type {first.request_type}.")


class Controller:
    """Base negotiation engine over abstract transport verbs."""

    # deferred cache hits older than this are invalidated and renegotiated
    # (reference: stalled cached tensors re-enter negotiation,
    # stall_inspector.cc:112+)
    STALE_HIT_SECONDS = 60.0

    def __init__(self, rank: int, world: int, cache_capacity: int = 1024):
        self.rank = rank
        self.world = world
        self.cache = make_response_cache(cache_capacity)
        # Autotunable (reference: parameter_manager.h:225-228 tunes
        # cache_enabled). Toggled only via the synchronized parameter
        # broadcast so every worker flips at the same cycle boundary.
        self.cache_enabled = cache_capacity > 0
        self.message_table = MessageTable()  # coordinator only
        self._should_shut_down = False
        # typed verdict when the shutdown was provoked by a stall eviction
        # (WorkerStallError from the inspector) — the runtime lifts this
        # so elastic callers get a catchable WorkersDownError while the
        # shutdown bit still propagates to every peer
        self.failure: Optional[Exception] = None
        # name -> Request for every announcement not yet resolved on this
        # worker (needed for fusion byte accounting + cache puts when the
        # agreement arrives in a LATER cycle than the announcement)
        self._pending: Dict[str, msg.Request] = {}
        # uncached names already delivered to the coordinator — must not be
        # re-sent (IncrementTensorCount would double-count this rank)
        self._awaiting: set = set()
        # cache hits not yet common to all workers: re-announced every
        # cycle until the agreement lands; name -> first-announce time
        self._deferred_first_seen: Dict[str, float] = {}
        # synchronized invalidation notices queued for the next slow path
        self._invalidate_queue: List[str] = []
        # coordinator-side straggler attribution, attached by the runtime
        # (stall.StragglerTracker); None on workers / when unwired
        self.straggler = None
        # hard deadline on in-flight negotiate rounds (0 = disabled):
        # unlike the stall inspector's slow warn/shutdown scan, this is
        # the partition-tolerance bound — a rank whose announcements
        # stop arriving trips it within HOROVOD_COLLECTIVE_TIMEOUT
        self.collective_timeout = resilience.collective_timeout()

    # -- transport verbs (reference: controller.h:98-124) ------------------
    def sync_bitvectors(self, bits: int) -> Tuple[int, int]:
        """Return (AND-reduced, OR-reduced) bitvectors across workers
        (reference: CrossRankBitwiseAnd/Or)."""
        raise NotImplementedError

    def send_ready_tensors(self, requests: List[msg.Request]
                           ) -> Optional[List[List[msg.Request]]]:
        """Workers send their ready lists; on the coordinator this returns
        every worker's list (reference: RecvReadyTensors / SendReadyTensors)."""
        raise NotImplementedError

    def bcast_responses(self, responses: Optional[List[msg.Response]]
                        ) -> List[msg.Response]:
        """Coordinator broadcasts the final list; workers receive it
        (reference: SendFinalTensors / RecvFinalTensors)."""
        raise NotImplementedError

    def barrier(self) -> None:
        raise NotImplementedError

    def bcast_blob(self, blob: Optional[bytes]) -> bytes:
        """Coordinator broadcasts an opaque blob; workers receive it. Used
        for the per-cycle autotune parameter sync (reference:
        SynchronizeParameters, controller.cc:32-46)."""
        raise NotImplementedError

    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    def request_shutdown(self) -> None:
        self._should_shut_down = True

    # -- the cycle (reference: ComputeResponseList, controller.cc:54-298) --
    def compute_response_list(
        self, requests: List[msg.Request], fusion_threshold: int,
        timeline=None, stall_inspector=None,
    ) -> Tuple[List[msg.Response], bool]:
        """Returns (responses_to_execute, should_shut_down).

        Cache mutations (puts AND invalidations) happen only through the
        agreed broadcast list, in list order — every worker applies the
        identical sequence, so cache-bit numbering stays aligned across
        workers (the invariant the bitvector fast path depends on;
        reference: response_cache.cc:232+ bit redistribution)."""
        import time as _time

        now = _time.monotonic()
        coordinator = CacheCoordinator()
        uncached_to_send: List[msg.Request] = []

        for r in requests:
            name = r.tensor_name
            self._pending[name] = r
            if name in self._awaiting:
                continue  # already at the coordinator; do not re-send
            state = (self.cache.cached(r) if self.cache_enabled
                     else CacheState.MISS)
            stale = (state == CacheState.HIT and
                     now - self._deferred_first_seen.get(name, now)
                     > self.STALE_HIT_SECONDS)
            if state == CacheState.HIT and not stale:
                coordinator.record_hit(self.cache.bit_for_name(name))
                self._deferred_first_seen.setdefault(name, now)
            else:
                if state in (CacheState.INVALID, CacheState.HIT):
                    # params changed, or the hit went stale waiting for the
                    # other workers: synchronized invalidation + renegotiate
                    self._invalidate_queue.append(name)
                    coordinator.set_invalid_in_queue()
                    self._deferred_first_seen.pop(name, None)
                    flight_recorder.emit("cache_invalidate", name=name,
                                         stale=stale)
                coordinator.set_uncached_in_queue()
                uncached_to_send.append(r)

        if self._should_shut_down:
            coordinator.set_should_shut_down()

        anded, ored = self.sync_bitvectors(coordinator.bitvector)
        shut_down, any_uncached, any_invalid = CacheCoordinator.flags(ored)

        # Stall scan runs on the coordinator EVERY cycle — a stalled tensor
        # sits in the message table while later cycles take the fast path,
        # so a slow-path-only check would never fire (reference: the stall
        # check is part of every ComputeResponseList, controller.cc:98-107).
        if self.is_coordinator and stall_inspector is not None \
                and len(self.message_table):
            try:
                if stall_inspector.check(self.message_table,
                                         world=self.world,
                                         straggler=self.straggler):
                    self.request_shutdown()
            except Exception as stall_exc:
                from horovod_tpu.exceptions import WorkerStallError

                if not isinstance(stall_exc, WorkerStallError):
                    raise
                # keep the typed reason AND still propagate the shutdown
                # bit next cycle so peers exit their loops in lockstep
                if self.failure is None:
                    self.failure = stall_exc
                self.request_shutdown()

        # Generation-fenced collective timeout: negotiate rounds older
        # than HOROVOD_COLLECTIVE_TIMEOUT abort the job with a catchable
        # WorkerStallError naming the ranks that never announced —
        # feeding the elastic reform instead of hanging on a partition.
        if (self.is_coordinator and self.collective_timeout > 0
                and self.failure is None and len(self.message_table)):
            self._check_collective_deadline(now)

        common_bits = sorted(CacheCoordinator.common_hits(anded))
        cached_responses: List[msg.Response] = []
        for bit in common_bits:
            resp = self.cache.get_by_bit(bit)
            if resp is not None:
                cached_responses.append(resp)

        if not any_uncached and not any_invalid:
            # FAST PATH (reference: controller.cc:151-179): everything
            # queued everywhere is cached — responses straight from cache,
            # no gather/bcast round trip.
            agreed = cached_responses
        else:
            # SLOW PATH: ship invalidation notices + uncached requests to
            # the coordinator; receive the agreed ordered list.
            notices = [
                msg.Request(self.rank, types.INVALIDATE, n, "", ())
                for n in dict.fromkeys(self._invalidate_queue)
            ]
            gathered = self.send_ready_tensors(notices + uncached_to_send)
            self._awaiting.update(r.tensor_name for r in uncached_to_send)
            self._invalidate_queue.clear()

            final: Optional[List[msg.Response]] = None
            if self.is_coordinator:
                assert gathered is not None
                invalidate_names: List[str] = []
                ready_names: List[str] = []
                for worker_requests in gathered:
                    for r in worker_requests:
                        if r.request_type == types.INVALIDATE:
                            if r.tensor_name not in invalidate_names:
                                invalidate_names.append(r.tensor_name)
                            continue
                        fresh = (r.tensor_name
                                 not in self.message_table.pending())
                        if timeline is not None:
                            if fresh:
                                timeline.negotiate_start(r.tensor_name,
                                                         r.request_type)
                            timeline.negotiate_rank_ready(r.tensor_name,
                                                          r.rank)
                        if fresh:
                            flight_recorder.emit("negotiate_begin",
                                                 name=r.tensor_name,
                                                 type=r.request_type)
                        flight_recorder.emit("rank_request",
                                             name=r.tensor_name, rank=r.rank)
                        if self.message_table.increment(r, self.world):
                            ready_names.append(r.tensor_name)
                negotiated: List[msg.Response] = []
                for name in ready_names:
                    arrivals = self.message_table.arrivals(name)
                    if self.straggler is not None and arrivals:
                        self.straggler.observe(name, arrivals)
                    skew = (round(max(arrivals.values())
                                  - min(arrivals.values()), 6)
                            if arrivals else 0.0)
                    reqs = self.message_table.pop(name)
                    if timeline is not None:
                        timeline.negotiate_end(name)
                    flight_recorder.emit("negotiate_end", name=name,
                                         skew=skew)
                    negotiated.append(construct_response(reqs))
                final = []
                if invalidate_names:
                    final.append(msg.Response(types.INVALIDATE,
                                              invalidate_names))
                final += cached_responses + negotiated

            agreed = self.bcast_responses(final)

        # Apply the agreed list: invalidations first (identical order on
        # every worker keeps free-bit pools aligned), then cache puts for
        # newly negotiated responses.
        executable: List[msg.Response] = []
        for resp in agreed:
            if resp.response_type == types.INVALIDATE:
                for name in resp.tensor_names:
                    self.cache.invalidate(name)
                continue
            if resp.response_type != types.ERROR and self.cache_enabled:
                for name in resp.tensor_names:
                    req = self._pending.get(name)
                    if req is not None \
                            and self.cache.cached(req) != CacheState.HIT:
                        self.cache.put(
                            msg.Response(resp.response_type, [name],
                                         tensor_sizes=resp.tensor_sizes),
                            req)
            executable.append(resp)

        fused = fusion.fuse_responses(executable, self._pending,
                                      fusion_threshold)

        # Resolve bookkeeping for everything that will now execute.
        for resp in fused:
            for name in resp.tensor_names:
                self._pending.pop(name, None)
                self._awaiting.discard(name)
                self._deferred_first_seen.pop(name, None)
        return fused, shut_down

    def _check_collective_deadline(self, now: float) -> None:
        """Coordinator-side deadline on in-flight negotiate rounds: any
        tensor whose first announcement is older than
        ``HOROVOD_COLLECTIVE_TIMEOUT`` ends the cycle. The verdict is a
        generation-stamped :class:`WorkerStallError` naming the ranks
        that never announced (the partitioned/stalled suspects), stored
        on ``self.failure`` — the runtime lifts it for elastic callers —
        while the shutdown bit still propagates so every peer leaves its
        loop in lockstep rather than waiting out its transport timeout."""
        overdue: List[str] = []
        missing: set = set()
        for name, reqs in self.message_table.pending().items():
            first = self.message_table.first_request_time(name)
            if first is None or now - first < self.collective_timeout:
                continue
            overdue.append(name)
            missing.update(set(range(self.world)) - {r.rank for r in reqs})
        if not overdue:
            return
        from horovod_tpu.exceptions import WorkerStallError

        gen = resilience.current_generation()
        ranks = sorted(missing)
        exc = WorkerStallError(
            f"collective timeout: {len(overdue)} negotiate round(s) "
            f"(first: {overdue[0]!r}) exceeded "
            f"HOROVOD_COLLECTIVE_TIMEOUT={self.collective_timeout:g}s in "
            f"generation {gen}; ranks never announced: {ranks}",
            ranks=ranks)
        log.error("%s", exc)
        flight_recorder.emit("collective_timeout", tensors=len(overdue),
                             missing=ranks, generation=gen)
        flight_recorder.dump_on_failure("collective_timeout")
        self.failure = exc
        self.request_shutdown()

    def take_deferred(self) -> List[msg.Request]:
        """Requests still unresolved on this worker that must be
        RE-ANNOUNCED this cycle: cache hits waiting for the other workers.
        (Uncached announcements already at the coordinator are excluded —
        re-sending would double-count this rank in IncrementTensorCount.)"""
        return [self._pending[n] for n in self._deferred_first_seen
                if n in self._pending]

    def has_deferred(self) -> bool:
        return bool(self._deferred_first_seen)


class LocalController(Controller):
    """Single-process controller: every enqueued tensor is trivially ready
    on all workers (they share the process); negotiation verbs are
    identities. The cache/fusion path is identical to the distributed
    controllers so tests of fast-path/fusion semantics transfer."""

    def sync_bitvectors(self, bits: int) -> Tuple[int, int]:
        return bits, bits

    def send_ready_tensors(self, requests):
        return [requests]

    def bcast_responses(self, responses):
        assert responses is not None
        return responses

    def bcast_blob(self, blob):
        assert blob is not None
        return blob

    def barrier(self) -> None:
        pass
