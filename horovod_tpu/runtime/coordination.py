"""Cross-process coordination primitives over the distributed KV store.

The reference ships objects between processes through its rendezvous KV
store (reference: horovod/run/rendezvous/http_server.py, gloo HTTPStore
horovod/common/gloo/http_store.cc). The TPU-native equivalent rides the
coordination service that ``jax.distributed.initialize`` already
establishes: a key-value store shared by every process in the job.
"""

from __future__ import annotations

import collections
import pickle
from typing import Optional

import jax

_counter = [0]
# per-name sequence numbers: the KV store forbids overwriting a key, so a
# reused broadcast name (e.g. checkpoint's resume-step broadcast every
# restore) gets a fresh key each call — all processes increment in the
# same call order, so the sequenced keys agree job-wide
_name_seq: collections.defaultdict = collections.defaultdict(int)


def _kv_client():
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "cross-process coordination requires jax.distributed to be "
            "initialized (set HOROVOD_COORDINATOR_ADDR or launch with tpurun)"
        )
    return client


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None,
                     timeout_ms: int = 60_000):
    """Broadcast a picklable object from the process owning ``root_rank``
    to every process (analogue of the reference's rendezvous-store KV
    exchange; used by ``hvd.broadcast_object``)."""
    client = _kv_client()
    if name is None:
        _counter[0] += 1
        name = f"_hvd_bcast_{_counter[0]}"
    _name_seq[name] += 1
    key = f"horovod_tpu/{name}.{_name_seq[name]}"
    from horovod_tpu.core import state as state_mod

    st = state_mod.global_state()
    # The process owning the root worker publishes; everyone reads.
    root_process = root_rank // max(st.local_size, 1)
    if jax.process_index() == root_process:
        client.key_value_set(key, pickle.dumps(obj).hex())
    payload = client.blocking_key_value_get(key, timeout_ms)
    return pickle.loads(bytes.fromhex(payload))
