"""Cross-process coordination primitives over the distributed KV store.

The reference ships objects between processes through its rendezvous KV
store (reference: horovod/run/rendezvous/http_server.py, gloo HTTPStore
horovod/common/gloo/http_store.cc). The TPU-native equivalent rides the
coordination service that ``jax.distributed.initialize`` already
establishes: a key-value store shared by every process in the job.
"""

from __future__ import annotations

import collections
import pickle
import time
from typing import Optional

import jax

from horovod_tpu.exceptions import WorkerStallError

_counter = [0]
# per-name sequence numbers: the KV store forbids overwriting a key, so a
# reused broadcast name (e.g. checkpoint's resume-step broadcast every
# restore) gets a fresh key each call — all processes increment in the
# same call order, so the sequenced keys agree job-wide
_name_seq: collections.defaultdict = collections.defaultdict(int)
# GC watermark per name: sequenced keys at or below this are deleted from
# the coordinator's store (long elastic jobs would otherwise grow it
# unboundedly, one dead key per broadcast)
_gc_floor: collections.defaultdict = collections.defaultdict(int)
_GC_INTERVAL = 32


def _kv_client():
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "cross-process coordination requires jax.distributed to be "
            "initialized (set HOROVOD_COORDINATOR_ADDR or launch with tpurun)"
        )
    return client


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None,
                     timeout_ms: int = 60_000):
    """Broadcast a picklable object from the process owning ``root_rank``
    to every process (analogue of the reference's rendezvous-store KV
    exchange; used by ``hvd.broadcast_object``)."""
    client = _kv_client()
    if name is None:
        _counter[0] += 1
        name = f"_hvd_bcast_{_counter[0]}"
    _name_seq[name] += 1
    key = f"horovod_tpu/{name}.{_name_seq[name]}"
    from horovod_tpu.core import state as state_mod

    st = state_mod.global_state()
    # The process owning the root worker publishes; everyone reads.
    root_process = root_rank // max(st.local_size, 1)
    if jax.process_index() == root_process:
        client.key_value_set(key, pickle.dumps(obj).hex())
    budget = timeout_ms / 1000.0
    t0 = time.monotonic()
    try:
        payload = client.blocking_key_value_get(key, timeout_ms)
    except Exception as exc:
        elapsed = time.monotonic() - t0
        text = str(exc).lower()
        if elapsed >= budget - 0.25 or "deadline" in text \
                or "timeout" in text or "timed out" in text:
            raise WorkerStallError(
                f"broadcast_object({name!r}): no value for key {key!r} "
                f"from root process {root_process} within {budget:g}s — "
                f"the publisher is stalled, partitioned, or dead") from exc
        raise
    obj = pickle.loads(bytes.fromhex(payload))
    _maybe_gc(client, name, _name_seq[name], root_process, timeout_ms)
    return obj


def _maybe_gc(client, name: str, seq: int, root_process: int,
              timeout_ms: int) -> None:
    """Delete consumed ``_hvd_bcast_*`` keys. Multi-process: every
    ``_GC_INTERVAL`` broadcasts of a name all processes rendezvous at a
    sequenced barrier (so every reader has observed every key at or below
    ``seq``) and the root deletes the batch; a barrier miss just defers
    GC to the next interval. Single-process: delete immediately. Always
    best-effort — a GC failure never fails the broadcast."""
    try:
        if jax.process_count() == 1:
            client.key_value_delete(f"horovod_tpu/{name}.{seq}")
            _gc_floor[name] = seq
            return
        if seq - _gc_floor[name] < _GC_INTERVAL:
            return
        if not (hasattr(client, "wait_at_barrier")
                and hasattr(client, "key_value_delete")):
            return
        # barrier ids must be fresh per GC round — seq provides that
        client.wait_at_barrier(f"_hvd_bcast_gc.{name}.{seq}", timeout_ms)
        if jax.process_index() == root_process:
            for s in range(_gc_floor[name] + 1, seq + 1):
                try:
                    client.key_value_delete(f"horovod_tpu/{name}.{s}")
                except Exception:
                    pass
        _gc_floor[name] = seq
    except Exception:
        pass
