"""Data-plane execution of negotiated (fused) responses.

TPU-native analogue of the reference's op chain + ``PerformOperation``
(reference: horovod/common/operations.cc:211-279, ops/operation_manager.cc,
ops/collective_operations.cc fused memcpy helpers): a fused ALLREDUCE
response becomes ONE compiled XLA program — flatten each entry, concatenate
into the fusion buffer, reduce across workers, split back — so XLA emits a
single large all-reduce over ICI instead of many small ones. Programs are
cached by (shapes, dtype, op) exactly as the reference reuses its fusion
buffer; in steady state each cycle re-dispatches a cached executable.

Where the reference memcpys into a persistent 64 MB buffer
(MemcpyInFusionBuffer, collective_operations.cc:37-81), here the pack and
unpack are part of the compiled program: XLA fuses them with the collective
and manages the HBM, which is both faster and simpler than hand-managed
staging on TPU.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import timeline as timeline_mod
from horovod_tpu.core import mesh as mesh_mod
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.ops import collectives
from horovod_tpu.runtime import types

_OP_LATENCY = _metrics().histogram(
    "horovod_executor_op_duration_seconds",
    "Wall time executing one (possibly fused) response, per op type.",
    labelnames=("op",))
_OP_BYTES = _metrics().counter(
    "horovod_executor_op_bytes_total",
    "Per-worker payload bytes executed, per op type.", labelnames=("op",))
_OP_ERRORS = _metrics().counter(
    "horovod_executor_op_errors_total",
    "Responses that completed with an error status, per op type.",
    labelnames=("op",))


# reduce_op name -> stacked-axis reducer for the XLA fused programs
_REDUCERS = {
    types.REDUCE_SUM: jnp.sum,
    types.REDUCE_AVERAGE: jnp.mean,
    types.REDUCE_MIN: jnp.min,
    types.REDUCE_MAX: jnp.max,
    types.REDUCE_PRODUCT: jnp.prod,
}

# reduce_op name -> host ring kernel op (average = sum + host divide)
_RING_OP = {
    types.REDUCE_SUM: "sum",
    types.REDUCE_AVERAGE: "sum",
    types.REDUCE_MIN: "min",
    types.REDUCE_MAX: "max",
    types.REDUCE_PRODUCT: "product",
}


def _widen_for_ring(a, copy: bool = False):
    """Map narrow dtypes onto the native ring kernels' four types
    (fp32 accumulation for 16-bit floats matches the reference's fp16
    MPI op behavior, half.cc:43-75). Results are always C-contiguous —
    the ring reduces through ``ravel()``, which must be a view, not a
    stray copy. ``copy=True`` guarantees a NEW array safe to reduce in
    place (callers that reduce the widened buffer itself)."""
    import numpy as np

    if a.dtype in (np.float32, np.float64, np.int32, np.int64):
        if copy:
            return np.array(a, order="C", copy=True)
        return np.ascontiguousarray(a)
    if a.dtype.kind in ("f", "V"):  # f16 / bfloat16(ml_dtypes)
        return a.astype(np.float32, order="C")
    if a.dtype == np.uint32:
        return a.astype(np.int64, order="C")  # exact, no wrap
    if a.dtype.kind in ("i", "b") or a.dtype in (np.uint8, np.uint16):
        return a.astype(np.int32, order="C")
    raise TypeError(f"unsupported host allreduce dtype {a.dtype} "
                    "(uint64 cannot be widened losslessly)")


class Executor:
    """First-match dispatch per response type (reference:
    operation_manager.cc:32-80). Two data planes:

    * XLA programs over the device mesh (default — single-controller, or
      multi-process sharing a global mesh via jax.distributed);
    * the native host ring (``net``) for multi-process mode without a
      shared mesh — each process contributes its local tensor, the TCP
      ring reduces, the analogue of the reference's Gloo CPU ops
      (gloo_operations.cc).
    """

    def __init__(self, mesh, net=None):
        self.mesh = mesh
        self.net = net
        self._programs: Dict[tuple, Any] = {}
        self._lock = threading.Lock()
        # typed workers-down verdict from a data-plane failure (see
        # execute's except clause); lifted by the runtime's cycle body
        self.failure = None
        # Multi-process with a global mesh (jax.distributed): the hot op
        # (allreduce) must ride XLA collectives over ICI/DCN, not the host
        # TCP ring — the ring stays as control plane + fallback. Requires
        # homogeneous device ownership (the reference likewise gates
        # hierarchical paths on homogeneity, mpi_controller.cc:25-81).
        self._spmd_world = jax.process_count() > 1
        self._proc_mesh = None
        if self._spmd_world:
            # One-device-per-process sub-mesh for the fused allreduce: each
            # process transfers its fusion buffer to device exactly once (no
            # k-fold duplication across its local devices) and the reduction
            # is exact for ints (one row per process, no dup correction).
            by_proc: Dict[int, list] = {}
            for d in mesh.devices.flatten():
                by_proc.setdefault(d.process_index, []).append(d)
            firsts = [min(ds, key=lambda d: d.id)
                      for _, ds in sorted(by_proc.items())]
            if len(firsts) == jax.process_count():
                import numpy as _np
                from jax.sharding import Mesh

                self._proc_mesh = Mesh(_np.array(firsts), ("proc",))

    def _replicated(self):
        from horovod_tpu.core import mesh as mesh_mod

        return mesh_mod.replicated_sharding(self.mesh)

    def _fused_allreduce_program(self, shapes, dtype, reduce_op: str,
                                 hierarchical: bool = False):
        key = ("fused_allreduce", shapes, str(dtype), reduce_op,
               hierarchical)
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                return fn

        sizes = []
        for s in shapes:
            n = 1
            for d in s[1:]:
                n *= int(d)
            sizes.append(n)

        if hierarchical:
            # two-level reduction over the fused buffer (shared body with
            # the eager path: collectives.two_level_reduce_block) —
            # sum/average only; callers gate other ops to the flat path
            cross, local = self.mesh.devices.shape
            world = cross * local

            def inner(xblk):
                return collectives.two_level_reduce_block(
                    xblk[0], local, world,
                    reduce_op == types.REDUCE_AVERAGE)

            def reduce_buf(buf):
                return jax.shard_map(
                    inner, mesh=self.mesh,
                    in_specs=P(mesh_mod.GLOBAL_AXES),
                    out_specs=P(), check_vma=False)(buf)
        else:
            reducer = _REDUCERS[reduce_op]

            def reduce_buf(buf):
                return reducer(buf, axis=0)

        def f(*tensors):
            flat = [t.reshape(t.shape[0], -1) for t in tensors]
            buf = jnp.concatenate(flat, axis=1) if len(flat) > 1 else flat[0]
            red = reduce_buf(buf)
            outs = []
            off = 0
            for shape, n in zip(shapes, sizes):
                outs.append(red[off:off + n].reshape(shape[1:]))
                off += n
            return tuple(outs)

        fn = jax.jit(f, out_shardings=self._replicated())
        with self._lock:
            self._programs[key] = fn
        return fn

    def hierarchical_available(self) -> bool:
        """Two-level collectives need both mesh axes populated (reference
        gates hierarchical on topology, nccl_operations.cc:348-355)."""
        cross, local = self.mesh.devices.shape
        return cross > 1 and local > 1

    def execute(self, response, entries: List[types.TensorTableEntry],
                timeline=None) -> None:
        """Run one (fused) response and fire entry callbacks.

        reference: PerformOperation (operations.cc:211-279) — statuses are
        delivered through per-entry callbacks; an ERROR response maps to an
        error status on every entry (ErrorOp,
        collective_operations.cc:202-205).
        """
        name0 = entries[0].name if entries else "?"
        op = response.response_type
        t0 = time.perf_counter()
        try:
            if timeline is not None:
                timeline.start(name0, response.response_type)
            if response.response_type == types.ERROR:
                status = types.Status.PreconditionError(response.error_message)
                _OP_ERRORS.labels(op=op).inc()
                for e in entries:
                    e.complete(status, None)
                return

            if response.response_type == types.ALLREDUCE:
                if (self.net is not None and self._spmd_world
                        and self._proc_mesh is not None):
                    # 64-bit payloads can't ride the XLA sub-mesh under
                    # x32 (device_put would narrow them — 2**40 becomes
                    # garbage); they reduce exactly on the host ring
                    # instead. The split is deterministic across ranks
                    # (dtype is part of the negotiated response). Inspect
                    # dtype via the tensor attribute — np.asarray on a
                    # jax.Array would device_get every gradient just to
                    # look at its dtype.
                    wide, rest = [], []
                    for e in entries:
                        dt = e.tensor.dtype  # np.dtype for numpy AND jax
                        (wide if dt.itemsize == 8 and dt.kind in "iuf"
                         else rest).append(e)
                    if rest:
                        self._execute_allreduce_spmd(rest, timeline)
                    if wide:
                        self._execute_allreduce_host(wide, timeline)
                elif self.net is not None:
                    self._execute_allreduce_host(entries, timeline)
                else:
                    self._execute_allreduce(response, entries, timeline)
            elif response.response_type == types.ALLGATHER:
                if self.net is not None:
                    self._execute_allgather_host(response, entries)
                else:
                    for e in entries:
                        e.output = collectives.allgather(e.tensor)
            elif response.response_type == types.BROADCAST:
                if self.net is not None:
                    self._execute_broadcast_host(entries)
                else:
                    for e in entries:
                        e.output = collectives.broadcast(e.tensor, e.root_rank)
            elif response.response_type == types.REDUCESCATTER:
                if self.net is not None:
                    self._execute_reducescatter_host(entries)
                else:
                    for e in entries:
                        e.output = collectives.reducescatter(
                            e.tensor, op=collectives.OPS_BY_NAME[e.reduce_op])
            elif response.response_type == types.ALLTOALL:
                if self.net is not None:
                    self._execute_alltoall_host(entries)
                else:
                    for e in entries:
                        e.output = collectives.alltoall(e.tensor)
            else:
                raise ValueError(
                    f"unknown response type {response.response_type}")

            ok = types.Status.OK()
            _OP_BYTES.labels(op=op).inc(
                sum(types.entry_nbytes(e) for e in entries))
            for e in entries:
                e.complete(ok, e.output)
        except Exception as exc:  # propagate execution failures as statuses
            status = types.Status.UnknownError(str(exc))
            _OP_ERRORS.labels(op=op).inc()
            from horovod_tpu import exceptions

            if (isinstance(exc, exceptions.WorkersDownError)
                    and self.failure is None):
                # a data-plane transport loss is a workers-down event even
                # though this cycle completes "normally" (entries failed by
                # status): record it so the runtime raises typed errors
                self.failure = exc
            for e in entries:
                e.complete(status, None)
        finally:
            _OP_LATENCY.labels(op=op).observe(time.perf_counter() - t0)
            if timeline is not None:
                timeline.end(name0)

    # -- host (multi-process) data plane -----------------------------------
    def _execute_allreduce_host(self, entries, timeline=None) -> None:
        """Fused host ring allreduce: pack all entries into one flat buffer
        (the literal fusion-buffer memcpy of the reference,
        collective_operations.cc:37-81), one ring pass, unpack."""
        import numpy as np

        world = self.net.world
        arrays = [np.asarray(e.tensor) for e in entries]
        # narrow types have no native host-ring kernels; widen for the wire
        wire = [_widen_for_ring(a) for a in arrays]
        if timeline is not None:
            timeline.activity_start(entries[0].name,
                                    timeline_mod.MEMCPY_IN_FUSION_BUFFER)
        buf = np.concatenate([a.ravel() for a in wire])
        if timeline is not None:
            timeline.activity_end(entries[0].name)
            timeline.activity_start(entries[0].name, "NET_RING_ALLREDUCE")
        reduce_op = entries[0].reduce_op
        self.net.allreduce(buf, _RING_OP[reduce_op])
        if timeline is not None:
            timeline.activity_end(entries[0].name)
        if reduce_op == types.REDUCE_AVERAGE:
            buf = buf / world
        off = 0
        for e, orig, w in zip(entries, arrays, wire):
            n = w.size
            out = buf[off:off + n].reshape(orig.shape).astype(orig.dtype)
            e.output = out
            off += n

    def _fused_spmd_allreduce_program(self, n: int, dtype, reduce_op: str):
        """One compiled XLA program per (flat size, dtype, op): the global
        stacked fusion buffer (P, n) — one row per process, sharded over the
        per-process sub-mesh — is reduced over the process axis, output
        replicated. Integer sums are exact (no duplication)."""
        key = ("spmd_allreduce", n, str(dtype), reduce_op)
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                return fn

        replicated = NamedSharding(self._proc_mesh, P())
        reducer = _REDUCERS[reduce_op]

        def f(buf):
            return reducer(buf, axis=0)

        fn = jax.jit(f, out_shardings=replicated)
        with self._lock:
            self._programs[key] = fn
        return fn

    def _execute_allreduce_spmd(self, entries, timeline=None) -> None:
        """Fused allreduce over a one-device-per-process sub-mesh in
        multi-process mode: pack entries into one flat host buffer, place it
        on this process's row of a (P, n) global array (single host→device
        transfer), reduce with a compiled XLA collective (rides ICI/DCN),
        unpack the replicated result. The analogue of NCCLAllreduce on the
        reference's GPU path (nccl_operations.cc:55-105) with XLA in place
        of NCCL."""
        import numpy as np

        arrays = [np.asarray(e.tensor) for e in entries]
        if timeline is not None:
            timeline.activity_start(entries[0].name,
                                    timeline_mod.MEMCPY_IN_FUSION_BUFFER)
        flat = np.concatenate([a.ravel() for a in arrays])
        mesh = self._proc_mesh
        n_proc = mesh.devices.size
        row_sharding = NamedSharding(mesh, P("proc"))
        local_dev = [d for d in mesh.devices.flatten()
                     if d.process_index == jax.process_index()][0]
        local_row = jax.device_put(flat[None], local_dev)
        global_stack = jax.make_array_from_single_device_arrays(
            (n_proc,) + flat.shape, row_sharding, [local_row])
        if timeline is not None:
            timeline.activity_end(entries[0].name)
            timeline.activity_start(entries[0].name,
                                    timeline_mod.XLA_COLLECTIVE)
        fn = self._fused_spmd_allreduce_program(
            int(flat.size), flat.dtype, entries[0].reduce_op)
        out = np.asarray(fn(global_stack))
        if timeline is not None:
            timeline.activity_end(entries[0].name)
        off = 0
        for e, a in zip(entries, arrays):
            e.output = out[off:off + a.size].reshape(a.shape).astype(
                a.dtype, copy=False)
            off += a.size

    def _execute_allgather_host(self, response, entries) -> None:
        import numpy as np

        for e in entries:
            local = np.ascontiguousarray(np.asarray(e.tensor))
            blobs = self.net.allgatherv(local.tobytes())
            parts = []
            trailing = local.shape[1:]
            for r, blob in enumerate(blobs):
                a = np.frombuffer(blob, dtype=local.dtype)
                first = (response.tensor_sizes[r] if response.tensor_sizes
                         else a.size // max(int(np.prod(trailing)) or 1, 1))
                parts.append(a.reshape((first,) + trailing))
            e.output = np.concatenate(parts, axis=0)

    def _execute_reducescatter_host(self, entries) -> None:
        """Host reduce-scatter on the native half-ring kernel: w-1 ring
        steps moving one chunk each — (w-1)/w of the payload per link,
        the optimal byte count (the round-2 allreduce+slice fallback
        cost 2x; VERDICT r2 ask 6). The negotiation layer validated
        shape[0] %% world == 0, so the kernel's flat near-equal chunks
        coincide exactly with the leading-axis shards."""
        import numpy as np

        world = self.net.world
        for e in entries:
            a = np.asarray(e.tensor)
            wire = _widen_for_ring(a, copy=True)  # consumed as scratch
            chunk = self.net.reducescatter(wire.ravel(),
                                           _RING_OP[e.reduce_op])
            shard = a.shape[0] // world
            out = chunk.reshape((shard,) + a.shape[1:])
            if e.reduce_op == types.REDUCE_AVERAGE:
                out = out / world
            e.output = out.astype(a.dtype, copy=False)

    def _execute_alltoall_host(self, entries) -> None:
        """Host all-to-all on the native pairwise-exchange kernel: w-1
        rounds over the full mesh, every byte crossing exactly one link
        ((w-1)/w of the payload — the round-2 star-allgatherv fallback
        cost Wx; VERDICT r2 ask 6)."""
        import numpy as np

        for e in entries:
            a = np.ascontiguousarray(np.asarray(e.tensor))
            e.output = self.net.alltoall(a)

    def _execute_broadcast_host(self, entries) -> None:
        import numpy as np

        for e in entries:
            local = np.ascontiguousarray(np.asarray(e.tensor))
            blob = self.net.bcast_from(
                local.tobytes() if self.net.rank == e.root_rank else None,
                e.root_rank)
            e.output = np.frombuffer(
                blob, dtype=local.dtype).reshape(local.shape)

    def _execute_allreduce(self, response, entries, timeline=None) -> None:
        stacked, replicated = [], []
        for e in entries:
            (stacked if collectives._is_worker_stacked(e.tensor)
             else replicated).append(e)

        # Replicated inputs need no collective: every worker already holds
        # the same value (single-controller invariant). average/min/max of
        # identical copies is the identity; sum/product scale by world.
        size = collectives.state_mod.global_state().size
        for e in replicated:
            if e.reduce_op == types.REDUCE_SUM:
                e.output = e.tensor * size
            elif e.reduce_op == types.REDUCE_PRODUCT:
                e.output = e.tensor ** size
            else:
                e.output = e.tensor

        if not stacked:
            return
        reduce_op = stacked[0].reduce_op
        shapes = tuple(tuple(e.tensor.shape) for e in stacked)
        dtype = stacked[0].tensor.dtype
        if timeline is not None:
            timeline.activity_start(stacked[0].name,
                                    timeline_mod.MEMCPY_IN_FUSION_BUFFER)
            timeline.activity_end(stacked[0].name)
            timeline.activity_start(stacked[0].name,
                                    timeline_mod.XLA_COLLECTIVE)
        hier = (collectives.state_mod.global_state()
                .config.hierarchical_allreduce
                and self.hierarchical_available()
                and reduce_op in (types.REDUCE_SUM, types.REDUCE_AVERAGE))
        fn = self._fused_allreduce_program(shapes, dtype, reduce_op, hier)
        outs = fn(*[e.tensor for e in stacked])
        for e, out in zip(stacked, outs):
            e.output = out
        if timeline is not None:
            timeline.activity_end(stacked[0].name)
