"""Data-plane execution of negotiated (fused) responses.

TPU-native analogue of the reference's op chain + ``PerformOperation``
(reference: horovod/common/operations.cc:211-279, ops/operation_manager.cc,
ops/collective_operations.cc fused memcpy helpers): a fused ALLREDUCE
response becomes ONE compiled XLA reduction over a fused buffer, so XLA
emits a single large all-reduce over ICI instead of many small ones.

The data plane is **pipelined** (the reference overlaps collective launch
with the next fusion-buffer memcpy the same way): ``dispatch`` packs the
fused payload and *launches* the jitted reduction, returning a pending
token; ``_PendingOp.complete`` later blocks on the device result and
unpacks entry outputs. The cycle body dispatches several responses before
draining, so packing bin k+1 overlaps the device reduction of bin k.
Where the pack happens depends on where the payload lives: the
single-controller path packs **on device** (eager flatten/concatenate/pad
— sharded gradients never visit the host, and outputs stay replicated
``jax.Array`` values), while the SPMD device_put and host-ring paths stage
through a persistent host fusion buffer (fusion_buffer.py, the
reference's MemcpyInFusionBuffer, collective_operations.cc:37-81).
Leases on those host slabs ride on the pending token and are released on
every exit path — success, error status, or cycle abort.

Compiled programs are cached by **size bucket** rather than exact shape:
the fused flat payload is padded with the reduction's identity up to a
bucket boundary (power-of-two above ``HOROVOD_FUSION_BUCKET_QUANTUM``),
so steady-state training compiles O(#buckets) programs total even as
bin-packing regroups the same tensors differently every cycle. The pad is
sliced off before unpack; integer sums stay exact (zero padding).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import comms
from horovod_tpu import flight_recorder
from horovod_tpu import timeline as timeline_mod
from horovod_tpu import tracing
from horovod_tpu.analysis import witness
from horovod_tpu.exceptions import WorkerLostError, WorkerStallError
from horovod_tpu.utils import resilience
from horovod_tpu.core import mesh as mesh_mod
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.ops import collectives
from horovod_tpu.runtime import types
from horovod_tpu.runtime.fusion_buffer import (FusionBufferManager,
                                               reduce_identity)

_OP_LATENCY = _metrics().histogram(
    "horovod_executor_op_duration_seconds",
    "Wall time executing one (possibly fused) response, per op type.",
    labelnames=("op",))
_OP_BYTES = _metrics().counter(
    "horovod_executor_op_bytes_total",
    "Per-worker payload bytes executed, per op type.", labelnames=("op",))
_OP_ERRORS = _metrics().counter(
    "horovod_executor_op_errors_total",
    "Responses that completed with an error status, per op type.",
    labelnames=("op",))
_PROGRAM_COMPILES = _metrics().counter(
    "horovod_executor_program_compiles_total",
    "Fused-collective program cache misses (new XLA compiles). Stops "
    "growing once steady-state traffic maps onto existing size buckets.")
_PROGRAM_CACHE_HITS = _metrics().counter(
    "horovod_executor_program_cache_hits_total",
    "Fused-collective dispatches served by an already-compiled program.")
_PAD_BYTES = _metrics().counter(
    "horovod_executor_pad_bytes_total",
    "Identity-padding bytes appended to fused payloads for size-bucketed "
    "program reuse.")
_COMM_EXPOSED = _metrics().counter(
    "horovod_comm_exposed_seconds_total",
    "Collective wall time NOT hidden behind other in-flight work: dispatch "
    "busy time plus drain (device sync + unpack) time, summed across ops. "
    "Compare against the horovod_executor_op_duration_seconds sum for the "
    "comm-hidden fraction.")


class _CommClock:
    """Cumulative comm-exposure accounting consumed by the step profiler
    (profiler.py diffs these at step boundaries). Per completed op the
    lifetime splits into dispatch-busy (pack + launch), an overlap window
    (token parked in the pipeline deque while later responses dispatch —
    the only part hidden from the caller), and drain-busy (device sync +
    unpack). Plain float adds under the GIL — same hot-path philosophy as
    the metrics registry."""

    __slots__ = ("total_seconds", "exposed_seconds", "total_bytes",
                 "hidden_bytes", "ops")

    def __init__(self) -> None:
        self.total_seconds = 0.0
        self.exposed_seconds = 0.0
        self.total_bytes = 0
        self.hidden_bytes = 0.0
        self.ops = 0

    def record(self, total: float, exposed: float, nbytes: int) -> None:
        self.total_seconds += total
        self.exposed_seconds += exposed
        self.total_bytes += nbytes
        self.ops += 1
        if total > 0.0:
            self.hidden_bytes += nbytes * (1.0 - exposed / total)
        _COMM_EXPOSED.inc(exposed)


_comm_clock = _CommClock()

# every live executor, so the memory tracker's "program_cache" subsystem
# can estimate compiled-program working sets without a push on the hot path
_executors_lock = witness.make_lock("executor._executors_lock")
_executors: "weakref.WeakSet" = weakref.WeakSet()  # guarded-by: _executors_lock


def program_cache_bytes() -> int:
    """Estimated bytes of the fused-program working sets across every
    live executor — the memory tracker's ``program_cache`` pull source."""
    with _executors_lock:
        executors = list(_executors)
    return sum(e.program_cache_bytes() for e in executors)


def comm_totals() -> dict:
    """Snapshot of the cumulative comm-exposure accumulators (the step
    profiler diffs two of these to attribute one step's collectives)."""
    c = _comm_clock
    return {"total_seconds": c.total_seconds,
            "exposed_seconds": c.exposed_seconds,
            "total_bytes": c.total_bytes,
            "hidden_bytes": c.hidden_bytes,
            "ops": c.ops}


# reduce_op name -> stacked-axis reducer for the XLA fused programs
_REDUCERS = {
    types.REDUCE_SUM: jnp.sum,
    types.REDUCE_AVERAGE: jnp.mean,
    types.REDUCE_MIN: jnp.min,
    types.REDUCE_MAX: jnp.max,
    types.REDUCE_PRODUCT: jnp.prod,
}

# reduce_op name -> host ring kernel op (average = sum + host divide)
_RING_OP = {
    types.REDUCE_SUM: "sum",
    types.REDUCE_AVERAGE: "sum",
    types.REDUCE_MIN: "min",
    types.REDUCE_MAX: "max",
    types.REDUCE_PRODUCT: "product",
}


def _widen_for_ring(a, copy: bool = False):
    """Map narrow dtypes onto the native ring kernels' four types
    (fp32 accumulation for 16-bit floats matches the reference's fp16
    MPI op behavior, half.cc:43-75). Results are always C-contiguous —
    the ring reduces through ``ravel()``, which must be a view, not a
    stray copy. ``copy=True`` guarantees a NEW array safe to reduce in
    place (callers that reduce the widened buffer itself)."""
    import numpy as np

    if a.dtype in (np.float32, np.float64, np.int32, np.int64):
        if copy:
            return np.array(a, order="C", copy=True)
        return np.ascontiguousarray(a)
    if a.dtype.kind in ("f", "V"):  # f16 / bfloat16(ml_dtypes)
        return a.astype(np.float32, order="C")
    if a.dtype == np.uint32:
        return a.astype(np.int64, order="C")  # exact, no wrap
    if a.dtype.kind in ("i", "b") or a.dtype in (np.uint8, np.uint16):
        return a.astype(np.int32, order="C")
    raise TypeError(f"unsupported host allreduce dtype {a.dtype} "
                    "(uint64 cannot be widened losslessly)")


class _PendingOp:
    """Completion token for one dispatched response.

    ``dispatch`` fills ``finish`` with the blocking tail (device sync +
    unpack) for async paths, or leaves it None when the work completed
    inline (host ring, eager ops, errors). ``complete`` runs the tail,
    fires entry callbacks exactly once, and closes the metrics/timeline
    span opened at dispatch. A host fusion-buffer lease backing the
    in-flight payload is attached as ``lease`` and released when the span
    closes — success OR failure — so transient faults (WorkersDownError
    mid-ring, an aborted cycle) never strand slabs. Responses must be
    completed in dispatch order (the cycle body's drain preserves it)."""

    __slots__ = ("executor", "op", "entries", "timeline", "name0", "t0",
                 "finish", "done", "lease", "nbytes", "bucket",
                 "t_disp_end", "t_drain_start", "t0_epoch", "lane")

    def __init__(self, executor: "Executor", op: str, entries, timeline):
        self.executor = executor
        self.op = op
        self.entries = entries
        self.timeline = timeline
        self.name0 = entries[0].name if entries else "?"
        self.t0 = time.perf_counter()
        # epoch twin of t0 (the tracing clock domain): the collective
        # span emitted at close must land on the same merged-trace
        # timeline as the request spans (tracing.py)
        self.t0_epoch = time.time()
        self.finish: Optional[Callable[[], None]] = None
        self.done = False
        self.lease = None
        self.nbytes = sum(types.entry_nbytes(e) for e in entries)
        # fused size bucket (elements per row), filled by allreduce
        # dispatch paths that pad to one; None for unbucketed ops
        self.bucket: Optional[int] = None
        # comm-exposure stamps: dispatch() sets t_disp_end when staging
        # returns; complete()/fail() set t_drain_start on entry. The gap
        # between them is the token's pipeline-overlap window — comm time
        # hidden behind later dispatches (profiler.py's hidden fraction).
        self.t_disp_end: Optional[float] = None
        self.t_drain_start: Optional[float] = None
        # transport lane for the comms plane ("device" / "host_ring" /
        # "spmd"), set by the dispatch branch that moved the bytes; None
        # for branches that delegate to eager collectives (those record
        # through ops.collectives._op_event instead — no double count)
        self.lane: Optional[str] = None

    def _close(self) -> None:
        self.done = True
        if self.lease is not None:
            self.executor.fusion_buffers.release(self.lease)
            self.lease = None
        t_end = time.perf_counter()
        total = t_end - self.t0
        _OP_LATENCY.labels(op=self.op).observe(total)
        disp_end = self.t_disp_end if self.t_disp_end is not None else t_end
        drain_start = (self.t_drain_start if self.t_drain_start is not None
                       else t_end)
        hidden = max(0.0, min(drain_start, t_end) - min(disp_end, t_end))
        _comm_clock.record(total, max(0.0, total - hidden), self.nbytes)
        if self.lane is not None:
            # the comms plane's algbw clock: payload bytes over the
            # token's dispatch→drain wall time (docs/comms.md)
            comms.record(self.op, self.lane, self.nbytes, total)
        if tracing.enabled():
            # per-tensor submit→dispatch→overlap→drain lineage: the
            # training-plane analogue of the request spans, so an
            # exposed-comm spike attributes to a named tensor
            tracing.record(
                "collective:" + str(self.name0), self.t0_epoch, total,
                op=self.op, bytes=self.nbytes, bucket=self.bucket,
                dispatch_ms=round((disp_end - self.t0) * 1000.0, 3),
                overlap_ms=round(hidden * 1000.0, 3),
                drain_ms=round(max(t_end - drain_start, 0.0) * 1000.0, 3))
        if self.timeline is not None:
            self.timeline.end(self.name0)

    def fail(self, status: types.Status) -> None:
        """Complete every entry with an error status and close the span
        (reference: ErrorOp, collective_operations.cc:202-205). Idempotent:
        a token already drained (or failed at dispatch) is left alone, so
        the cycle body's abort sweep can fail the whole pending deque."""
        if self.done:
            return
        if self.t_drain_start is None:
            self.t_drain_start = time.perf_counter()
        _OP_ERRORS.labels(op=self.op).inc()
        flight_recorder.emit("op_fail", op=self.op, name=self.name0,
                             bytes=self.nbytes, bucket=self.bucket,
                             error=str(status.reason)[:200])
        for e in self.entries:
            e.complete(status, None)
        self._close()

    def fail_exc(self, exc: Exception) -> None:
        from horovod_tpu import exceptions
        from horovod_tpu import memory

        # HBM exhaustion forensics: one choke point covers dispatch-time
        # and drain-time failures on all three data planes. No-op unless
        # the exception is an allocator OOM; never raises.
        memory.maybe_record_oom(exc, where="executor")
        if (isinstance(exc, exceptions.NumericalError)
                and self.executor.integrity_failure is None):
            # a typed integrity verdict must reach the waiting caller
            # WITHOUT marking the runtime as down: the runtime survives
            # the rollback-and-replay, so this never touches
            # executor.failure (which the cycle body lifts into a
            # runtime shutdown). RuntimeHandle.wait lifts and clears it.
            self.executor.integrity_failure = exc
        elif (isinstance(exc, exceptions.WorkersDownError)
                and self.executor.failure is None):
            # a data-plane transport loss is a workers-down event even
            # though this cycle completes "normally" (entries failed by
            # status): record it so the runtime raises typed errors
            self.executor.failure = exc
        self.fail(types.Status.UnknownError(str(exc)))

    def complete(self) -> None:
        if self.done:
            return
        if self.t_drain_start is None:
            self.t_drain_start = time.perf_counter()
        try:
            if self.finish is not None:
                self.finish()
            ok = types.Status.OK()
            _OP_BYTES.labels(op=self.op).inc(
                sum(types.entry_nbytes(e) for e in self.entries))
            flight_recorder.emit(
                "op_complete", op=self.op, name=self.name0,
                bytes=self.nbytes, bucket=self.bucket,
                seconds=round(time.perf_counter() - self.t0, 6))
            for e in self.entries:
                e.complete(ok, e.output)
            self._close()
        except Exception as exc:  # propagate execution failures as statuses
            self.fail_exc(exc)


class Executor:
    """First-match dispatch per response type (reference:
    operation_manager.cc:32-80). Two data planes:

    * XLA programs over the device mesh (default — single-controller, or
      multi-process sharing a global mesh via jax.distributed);
    * the native host ring (``net``) for multi-process mode without a
      shared mesh — each process contributes its local tensor, the TCP
      ring reduces, the analogue of the reference's Gloo CPU ops
      (gloo_operations.cc).
    """

    def __init__(self, mesh, net=None):
        self.mesh = mesh
        self.net = net
        self._programs: Dict[tuple, Any] = {}  # guarded-by: _lock
        self._lock = witness.make_lock("Executor._lock")
        # typed workers-down verdict from a data-plane failure (see
        # _PendingOp.fail_exc); lifted by the runtime's cycle body
        self.failure = None
        # typed integrity verdict (NumericalError family) from a digest
        # check; lifted AND CLEARED by RuntimeHandle.wait so the runtime
        # itself survives the rollback-and-replay
        self.integrity_failure = None  # guarded-by: <cycle-thread>
        # eligible fused-allreduce dispatches seen, for the digest
        # cadence; deterministic across ranks (dispatch order is
        # negotiated)
        self._integrity_dispatches = 0  # guarded-by: <cycle-thread>
        # persistent host staging (reference: FusionBufferManager) + the
        # size-bucket policy keying the program caches
        quantum = None
        try:
            from horovod_tpu.core import state as state_mod

            quantum = state_mod.global_state().config.fusion_bucket_quantum
        except Exception:
            pass  # direct construction in tests / tools: use the default
        self.fusion_buffers = (FusionBufferManager(quantum)
                               if quantum is not None
                               else FusionBufferManager())
        self._ag_staging = bytearray()  # allgather wire staging (reused)
        # two-level host-collective group plan, memoized per (net,
        # world, rank, knob) — elastic re-forms swap the NetComm, which
        # invalidates the key so groups are recomputed for the new world
        self._hier_plan = None       # guarded-by: <cycle-thread>
        self._hier_plan_key = None   # guarded-by: <cycle-thread>
        with _executors_lock:
            _executors.add(self)
        # Multi-process with a global mesh (jax.distributed): the hot op
        # (allreduce) must ride XLA collectives over ICI/DCN, not the host
        # TCP ring — the ring stays as control plane + fallback. Requires
        # homogeneous device ownership (the reference likewise gates
        # hierarchical paths on homogeneity, mpi_controller.cc:25-81).
        self._spmd_world = jax.process_count() > 1
        self._proc_mesh = None
        if self._spmd_world:
            # One-device-per-process sub-mesh for the fused allreduce: each
            # process transfers its fusion buffer to device exactly once (no
            # k-fold duplication across its local devices) and the reduction
            # is exact for ints (one row per process, no dup correction).
            by_proc: Dict[int, list] = {}
            for d in mesh.devices.flatten():
                by_proc.setdefault(d.process_index, []).append(d)
            firsts = [min(ds, key=lambda d: d.id)
                      for _, ds in sorted(by_proc.items())]
            if len(firsts) == jax.process_count():
                import numpy as _np
                from jax.sharding import Mesh

                self._proc_mesh = Mesh(_np.array(firsts), ("proc",))

    def _replicated(self):
        from horovod_tpu.core import mesh as mesh_mod

        return mesh_mod.replicated_sharding(self.mesh)

    def _fused_allreduce_program(self, rows: int, n: int, dtype,
                                 reduce_op: str,
                                 hierarchical: bool = False):
        """One compiled reduction per (rows, bucket, dtype, op[, hier]):
        input is the packed fusion buffer (rows, n) — one row per worker —
        reduced over the worker axis, output replicated. Keyed by the
        size bucket, not the member shapes, so regrouped bins reuse it."""
        key = ("fused_allreduce", rows, n, str(dtype), reduce_op,
               hierarchical)
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                _PROGRAM_CACHE_HITS.inc()
                return fn
        _PROGRAM_COMPILES.inc()

        if hierarchical:
            # two-level reduction over the fused buffer (shared body with
            # the eager path: collectives.two_level_reduce_block) —
            # sum/average only; callers gate other ops to the flat path
            cross, local = self.mesh.devices.shape
            world = cross * local

            def inner(xblk):
                return collectives.two_level_reduce_block(
                    xblk[0], local, world,
                    reduce_op == types.REDUCE_AVERAGE)

            def reduce_buf(buf):
                return jax.shard_map(
                    inner, mesh=self.mesh,
                    in_specs=P(mesh_mod.GLOBAL_AXES),
                    out_specs=P(), check_vma=False)(buf)
        else:
            reducer = _REDUCERS[reduce_op]

            def reduce_buf(buf):
                return reducer(buf, axis=0)

        fn = jax.jit(reduce_buf, out_shardings=self._replicated())
        with self._lock:
            self._programs[key] = fn
        return fn

    def _integrity_due(self) -> bool:
        """Advance the digest cadence by one eligible dispatch; True on
        the first and every HOROVOD_INTEGRITY_INTERVAL-th. Called at the
        same negotiated dispatch on every rank, so the decision (and the
        in-band exchange it triggers) stays lockstep."""
        from horovod_tpu import integrity

        if not integrity.enabled():
            return False
        iv = integrity.interval()
        if iv <= 0:
            return False
        n = self._integrity_dispatches
        self._integrity_dispatches = n + 1
        return n % iv == 0

    def _digest_nonfinite_program(self, rows: int, capacity: int, dtype):
        """Per-row non-finite count over the packed fusion buffer, in
        band with the fused reduction. ``total`` is a traced scalar so
        one program per (rows, bucket, dtype) serves every payload size
        in the bucket; the mask keeps the reduction-identity padding
        (±inf for min/max) from counting as corruption."""
        key = ("digest_nf", rows, capacity, str(dtype))
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                _PROGRAM_CACHE_HITS.inc()
                return fn
        _PROGRAM_COMPILES.inc()

        def count_nonfinite(buf, total):
            mask = jnp.arange(capacity)[None, :] < total
            bad = jnp.logical_and(mask, ~jnp.isfinite(buf))
            return jnp.sum(bad, axis=1, dtype=jnp.int32)

        fn = jax.jit(count_nonfinite, out_shardings=self._replicated())
        with self._lock:
            self._programs[key] = fn
        return fn

    def program_cache_bytes(self) -> int:
        """Estimated working-set bytes of the compiled-program cache,
        derived from the size-bucketed cache keys (the fused input buffer
        each program was specialized for — the persistent device-side
        footprint the cache pins)."""
        import numpy as np

        with self._lock:
            keys = list(self._programs)
        total = 0
        for key in keys:
            try:
                kind = key[0]
                if kind in ("fused_allreduce", "digest_nf"):
                    rows, n, dtype = int(key[1]), int(key[2]), key[3]
                elif kind == "spmd_allreduce":
                    rows, n, dtype = jax.process_count(), int(key[1]), key[2]
                else:
                    continue
                total += rows * n * np.dtype(dtype).itemsize
            except Exception:
                continue  # an unparseable key must not break accounting
        return total

    def hierarchical_available(self) -> bool:
        """Two-level collectives need both topology axes populated
        (reference gates hierarchical on topology,
        nccl_operations.cc:348-355). On the multiprocess host-ring data
        plane the topology is the rank grouping, NOT the stacked device
        mesh — the old mesh-only check meant a two-host host-ring job
        never saw its hierarchical knobs join the autotune sweep. This
        is a static predicate (no wire traffic): an explicit group size
        must tile the world into >= 2 groups of >= 2; with auto (host-
        derived) grouping any world >= 4 COULD split, so the knob is
        sweepable and a flat-resolving plan simply makes it a no-op."""
        if self.net is not None and not self._spmd_world:
            w = self.net.world
            if w < 4:
                return False
            g = self._hier_group_size()
            return g == 0 or (g >= 2 and w % g == 0 and w // g >= 2)
        cross, local = self.mesh.devices.shape
        return cross > 1 and local > 1

    def _hier_group_size(self) -> int:
        """The HOROVOD_HIERARCHY_GROUP_SIZE knob (0 = host-derived),
        autotuner-writable through the synced config."""
        try:
            from horovod_tpu.core import state as state_mod

            return int(state_mod.global_state()
                       .config.hierarchy_group_size or 0)
        except Exception:
            return 0

    def _hierarchy_plan(self):
        """Memoized group plan for the host-ring data plane; None when
        hierarchy is off (knob disabled) or the plan resolves flat.
        Host-derived formation runs one roster allgatherv — safe here
        because dispatch order is negotiated, so every rank builds the
        plan at the same point in its wire-op sequence."""
        net = self.net
        if net is None:
            return None
        from horovod_tpu.core import state as state_mod

        cfg = state_mod.global_state().config
        if not cfg.hierarchical_allreduce:
            return None
        gsize = int(cfg.hierarchy_group_size or 0)
        key = (id(net), net.world, net.rank, gsize)
        if self._hier_plan_key != key:
            from horovod_tpu.runtime import hierarchy

            plan = hierarchy.build_plan(net, gsize)
            self._hier_plan = plan
            self._hier_plan_key = key
            if plan.enabled:
                flight_recorder.emit(
                    "hierarchy_plan", groups=plan.num_groups,
                    group_size=plan.group_size, source=plan.source,
                    world=plan.world)
        plan = self._hier_plan
        return plan if (plan is not None and plan.enabled) else None

    def _hier_wire_dtype(self):
        """Numpy wire dtype for the compressed cross-group hop (None =
        full precision), from HOROVOD_HIERARCHY_COMPRESSION."""
        from horovod_tpu.core import state as state_mod
        from horovod_tpu.runtime import hierarchy

        try:
            name = state_mod.global_state().config.hierarchy_compression
        except Exception:
            return None
        try:
            return hierarchy.wire_dtype_from_name(name)
        except ValueError:
            return None

    def execute(self, response, entries: List[types.TensorTableEntry],
                timeline=None) -> None:
        """Run one (fused) response synchronously: dispatch + complete.
        Kept for callers that don't pipeline (and as the un-overlapped
        baseline — semantics identical to dispatch().complete())."""
        self.dispatch(response, entries, timeline=timeline).complete()

    def dispatch(self, response, entries: List[types.TensorTableEntry],
                 timeline=None) -> _PendingOp:
        """Stage one (fused) response onto the data plane and return a
        pending token; ``token.complete()`` blocks on the result and fires
        entry callbacks (reference: PerformOperation, operations.cc:211-279
        — statuses are delivered through per-entry callbacks; an ERROR
        response maps to an error status on every entry).

        Asynchronous paths (the XLA fused allreduces) launch here and
        fetch in complete(); host-ring and eager paths run to completion
        here and complete() only fires callbacks — the drain order is the
        same either way.
        """
        pend = _PendingOp(self, response.response_type, entries, timeline)
        flight_recorder.emit("op_dispatch", op=pend.op, name=pend.name0,
                             tensors=len(entries), bytes=pend.nbytes)
        t0 = time.monotonic()
        try:
            if timeline is not None:
                timeline.start(pend.name0, response.response_type)
            if response.response_type == types.ERROR:
                pend.fail(
                    types.Status.PreconditionError(response.error_message))
                return pend

            if response.response_type == types.ALLREDUCE:
                if (self.net is not None and self._spmd_world
                        and self._proc_mesh is not None):
                    # 64-bit payloads can't ride the XLA sub-mesh under
                    # x32 (device_put would narrow them — 2**40 becomes
                    # garbage); they reduce exactly on the host ring
                    # instead. The split is deterministic across ranks
                    # (dtype is part of the negotiated response). Inspect
                    # dtype via the tensor attribute — np.asarray on a
                    # jax.Array would device_get every gradient just to
                    # look at its dtype.
                    wide, rest = [], []
                    for e in entries:
                        dt = e.tensor.dtype  # np.dtype for numpy AND jax
                        (wide if dt.itemsize == 8 and dt.kind in "iuf"
                         else rest).append(e)
                    if wide:
                        # the ring ran to completion right here — fire
                        # these callbacks now rather than when the token
                        # drains (under pipeline depth N the drain waits
                        # behind up to N-1 later device collectives)
                        wide_bytes = sum(
                            types.entry_nbytes(e) for e in wide)
                        t_ring = time.perf_counter()
                        self._execute_allreduce_host(wide, timeline)
                        comms.record("allreduce", "host_ring", wide_bytes,
                                     time.perf_counter() - t_ring)
                        ok = types.Status.OK()
                        _OP_BYTES.labels(op=pend.op).inc(wide_bytes)
                        for e in wide:
                            e.complete(ok, e.output)
                        pend.entries = rest
                        # the token's remaining bytes ride the SPMD lane
                        pend.nbytes -= wide_bytes
                    if rest:
                        pend.lane = "spmd"
                        pend.finish = self._dispatch_allreduce_spmd(
                            rest, timeline, pend)
                elif self.net is not None:
                    pend.lane = "host_ring"
                    self._execute_allreduce_host(entries, timeline)
                else:
                    pend.lane = "device"
                    pend.finish = self._dispatch_allreduce(
                        response, entries, timeline, pend)
            elif response.response_type == types.ALLGATHER:
                if self.net is not None:
                    pend.lane = "host_ring"
                    self._execute_allgather_host(response, entries)
                else:
                    for e in entries:
                        e.output = collectives.allgather(e.tensor)
            elif response.response_type == types.BROADCAST:
                if self.net is not None:
                    pend.lane = "host_ring"
                    self._execute_broadcast_host(entries)
                else:
                    for e in entries:
                        e.output = collectives.broadcast(e.tensor, e.root_rank)
            elif response.response_type == types.REDUCESCATTER:
                if self.net is not None:
                    pend.lane = "host_ring"
                    self._execute_reducescatter_host(entries)
                else:
                    for e in entries:
                        e.output = collectives.reducescatter(
                            e.tensor, op=collectives.OPS_BY_NAME[e.reduce_op])
            elif response.response_type == types.ALLTOALL:
                if self.net is not None:
                    pend.lane = "host_ring"
                    self._execute_alltoall_host(entries)
                else:
                    for e in entries:
                        e.output = collectives.alltoall(e.tensor)
            else:
                raise ValueError(
                    f"unknown response type {response.response_type}")
        except Exception as exc:
            pend.fail_exc(self._maybe_stall(exc, time.monotonic() - t0))
        if pend.t_disp_end is None:
            pend.t_disp_end = time.perf_counter()
        return pend

    def _maybe_stall(self, exc: Exception, elapsed: float) -> Exception:
        """Classify a data-plane transport loss that consumed the whole
        HOROVOD_COLLECTIVE_TIMEOUT budget as a generation-stamped
        ``WorkerStallError``: a peer that sat silent for the entire
        deadline is partitioned/stalled, not cleanly dead, and the
        elastic reform should treat the cycle abort as a stall (the
        error still flows through the same ``_PendingOp.fail`` path)."""
        ct = resilience.collective_timeout()
        if (ct > 0 and elapsed >= ct - 0.05
                and isinstance(exc, WorkerLostError)
                and not isinstance(exc, WorkerStallError)):
            gen = resilience.current_generation()
            flight_recorder.emit("collective_timeout", phase="dispatch",
                                 generation=gen, elapsed=round(elapsed, 3))
            return WorkerStallError(
                f"data-plane dispatch blocked {elapsed:.1f}s — "
                f"HOROVOD_COLLECTIVE_TIMEOUT={ct:g}s exceeded in "
                f"generation {gen}; aborting the cycle for elastic "
                f"recovery ({exc})", ranks=exc.ranks)
        return exc

    # -- fused pack/pad helpers --------------------------------------------
    def _pack_fused(self, arrays, rows: int, dtype, reduce_op: str):
        """Copy flattened entry payloads into a leased persistent fusion
        buffer of shape (rows, bucket) and pad the tail columns with the
        reduction identity. Returns (lease, total_elems_per_row)."""
        import numpy as np

        sizes = [a.size // rows for a in arrays]
        total = sum(sizes)
        lease = self.fusion_buffers.acquire(rows, total, dtype)
        try:
            buf = lease.array
            off = 0
            for a, n in zip(arrays, sizes):
                np.copyto(buf[:, off:off + n], a.reshape(rows, n))
                off += n
            if lease.capacity > total:
                buf[:, total:] = reduce_identity(dtype, reduce_op)
                _PAD_BYTES.inc(
                    (lease.capacity - total) * rows * buf.dtype.itemsize)
        except Exception:
            self.fusion_buffers.release(lease)
            raise
        return lease, total

    # -- single-controller XLA data plane ----------------------------------
    def _dispatch_allreduce(self, response, entries, timeline=None,
                            pend=None):
        """Fused allreduce over the global mesh, entirely on device: the
        worker-stacked entries are flattened, concatenated and
        identity-padded to the size bucket with eager XLA ops (the
        device-side MemcpyInFusionBuffer — sharded gradients never visit
        the host), the bucket-keyed compiled reduction is launched, and
        the returned completion tail blocks on the device result and
        unpacks replicated ``jax.Array`` slices. The host
        FusionBufferManager still owns the bucket policy but stages
        nothing here — it serves the host-ring and SPMD device_put paths.
        Replicated inputs need no collective and complete inline."""
        import numpy as np

        stacked, replicated = [], []
        for e in entries:
            (stacked if collectives._is_worker_stacked(e.tensor)
             else replicated).append(e)

        # Replicated inputs need no collective: every worker already holds
        # the same value (single-controller invariant). average/min/max of
        # identical copies is the identity; sum/product scale by world.
        size = collectives.state_mod.global_state().size
        for e in replicated:
            if e.reduce_op == types.REDUCE_SUM:
                e.output = e.tensor * size
            elif e.reduce_op == types.REDUCE_PRODUCT:
                e.output = e.tensor ** size
            else:
                e.output = e.tensor

        if not stacked:
            if pend is not None:
                pend.lane = None  # nothing crossed a wire
            return None
        reduce_op = stacked[0].reduce_op
        name0 = stacked[0].name
        rows = int(stacked[0].tensor.shape[0])  # worker-stacked == world
        dtype = np.dtype(stacked[0].tensor.dtype)
        sizes = [int(e.tensor.size) // rows for e in stacked]
        shapes = [tuple(e.tensor.shape[1:]) for e in stacked]
        total = sum(sizes)
        capacity = self.fusion_buffers.bucket_elems(total, dtype.itemsize)
        if pend is not None:
            pend.bucket = capacity
        if timeline is not None:
            timeline.activity_start(name0,
                                    timeline_mod.MEMCPY_IN_FUSION_BUFFER)
        # Device-side pack: eager reshape/concat/pad are tiny XLA ops
        # cached by shape in jax's own executable cache, and in steady
        # state the bounded set of bin groupings is fully warm. The
        # expensive program (the one holding the collective) stays keyed
        # by the size bucket below.
        parts = [jnp.reshape(e.tensor, (rows, n))
                 for e, n in zip(stacked, sizes)]
        if capacity > total:
            parts.append(jnp.full((rows, capacity - total),
                                  reduce_identity(dtype, reduce_op), dtype))
            _PAD_BYTES.inc((capacity - total) * rows * dtype.itemsize)
        buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        from horovod_tpu.integrity import digest as integ_digest
        from horovod_tpu.integrity import inject as integ_inject

        is_float = dtype.kind in ("f", "V")  # V: ml_dtypes bf16
        plan = integ_inject.plan_dispatch_any()
        if plan is not None and plan[0] == "nan" and is_float:
            # one process owns every worker's row here, so the clause
            # rank selects the ROW to poison (bitflip is a no-op on this
            # path: a single replicated result has no copy to diverge)
            row = min(max(plan[1], 0), rows - 1)
            buf = buf.at[row, 0].set(jnp.nan)
        nf_dev = None
        if is_float and self._integrity_due():
            digest_fn = self._digest_nonfinite_program(rows, capacity,
                                                       dtype)
            nf_dev = digest_fn(buf, np.int32(total))
        if timeline is not None:
            timeline.activity_end(name0)
            timeline.activity_start(name0, timeline_mod.XLA_COLLECTIVE)
        hier = (collectives.state_mod.global_state()
                .config.hierarchical_allreduce
                and self.hierarchical_available()
                and reduce_op in (types.REDUCE_SUM, types.REDUCE_AVERAGE))
        fn = self._fused_allreduce_program(rows, capacity, dtype,
                                           reduce_op, hier)
        out_dev = fn(buf)  # async launch; completion syncs in finish()

        def finish():
            # pipeline barrier without D2H: bound in-flight device work
            # at the drain, but keep results resident as replicated
            # jax.Arrays (callers rely on device residency/sharding)
            jax.block_until_ready(out_dev)
            if nf_dev is not None:
                counts = np.asarray(nf_dev)
                bad = np.nonzero(counts)[0]
                integ_digest.verify_local(
                    int(counts.sum()), bucket=f"fused[{capacity}]",
                    tensor=name0,
                    suspect_rank=int(bad[0]) if bad.size else None)
            if timeline is not None:
                timeline.activity_end(name0)
                timeline.activity_start(
                    name0, timeline_mod.MEMCPY_OUT_FUSION_BUFFER)
            off = 0
            for e, shape, n in zip(stacked, shapes, sizes):
                e.output = out_dev[off:off + n].reshape(shape)
                off += n
            if timeline is not None:
                timeline.activity_end(name0)

        return finish

    # -- host (multi-process) data plane -----------------------------------
    def _execute_allreduce_host(self, entries, timeline=None) -> None:
        """Fused host ring allreduce: pack all entries into one flat
        persistent buffer (the literal fusion-buffer memcpy of the
        reference, collective_operations.cc:37-81), one ring pass, unpack.
        No bucket padding on the wire — the ring isn't compiled, so extra
        bytes would cost bandwidth for nothing; the persistent slab is
        bucket-sized and sliced to the exact payload."""
        import numpy as np

        world = self.net.world
        hier_plan = self._hierarchy_plan()
        if hier_plan is None:
            # chaos seam on the DATA plane (the ctrl/kv seams cover only
            # the control plane): HOROVOD_FAULT_INJECT=netdelay:... slows
            # the ring pass itself, so the comms plane's host_ring busbw
            # visibly degrades (docs/comms.md, docs/robustness.md). A
            # flat ring's 2(w-1) exchange steps each cross the slow
            # group boundary, so a hop=cross netdelay taxes all of them.
            resilience.inject("ring", "allreduce",
                              crossings=2 * (world - 1))
        arrays = [np.asarray(e.tensor) for e in entries]
        # narrow types have no native host-ring kernels; widen for the wire
        wire = [_widen_for_ring(a) for a in arrays]
        if timeline is not None:
            timeline.activity_start(entries[0].name,
                                    timeline_mod.MEMCPY_IN_FUSION_BUFFER)
        total = sum(w.size for w in wire)
        lease = self.fusion_buffers.acquire(1, total, wire[0].dtype)
        try:  # the ring raising (WorkersDownError is routine in elastic
            # mode) must not strand the slab — release on every path
            buf = lease.array.ravel()[:total]
            off = 0
            for w in wire:
                np.copyto(buf[off:off + w.size], w.ravel())
                off += w.size
            from horovod_tpu.integrity import digest as integ_digest
            from horovod_tpu.integrity import inject as integ_inject

            plan = integ_inject.plan_dispatch()
            if plan == "nan" and buf.dtype.kind == "f":
                # poison this rank's INPUT before the ring pass — the
                # NaN spreads to every replica through the reduction
                integ_inject.corrupt_nan(buf)
            check = self._integrity_due()
            nf_in = integ_digest.nonfinite_count(buf) if check else 0
            if timeline is not None:
                timeline.activity_end(entries[0].name)
                timeline.activity_start(entries[0].name,
                                        "NET_RING_ALLREDUCE")
            reduce_op = entries[0].reduce_op
            if hier_plan is not None:
                # two-level path: intra reduce-scatter -> cross exchange
                # over 1/g of the bytes (optionally 16-bit on the wire)
                # -> intra allgather. nf_in above was computed on the
                # uncompressed input and checksum below on the
                # decompressed result, so integrity verdicts are
                # independent of the wire precision (pre-compression
                # digests, the PR 10 contract).
                from horovod_tpu.runtime import hierarchy

                hierarchy.hier_allreduce(
                    self.net, hier_plan, buf, _RING_OP[reduce_op],
                    wire_dtype=self._hier_wire_dtype())
            else:
                self.net.allreduce(buf, _RING_OP[reduce_op])
            if timeline is not None:
                timeline.activity_end(entries[0].name)
            if reduce_op == types.REDUCE_AVERAGE:
                buf = buf / world  # new array; slab is released unscaled
            if plan == "bitflip":
                # SDC on this rank's LOCAL copy of the reduced result:
                # the other ranks hold the correct bytes, so only the
                # cross-rank checksum vote can convict
                if reduce_op != types.REDUCE_AVERAGE:
                    buf = buf.copy()  # don't poison the reusable slab
                integ_inject.corrupt_bitflip(buf)
            if check:
                # in-band agreement: one 12-byte record per rank over
                # the same wire, same thread, same negotiated order as
                # the payload — raises BEFORE any output is unpacked
                records = integ_digest.exchange(
                    self.net, nf_in, integ_digest.checksum(buf))
                integ_digest.verify(records, bucket=f"ring[{total}]",
                                    tensor=entries[0].name)
            off = 0
            for e, orig, w in zip(entries, arrays, wire):
                n = w.size
                # astype(copy=True is the default) detaches the output
                # from the reusable slab even when dtypes already match
                out = buf[off:off + n].reshape(orig.shape).astype(
                    orig.dtype)
                e.output = out
                off += n
        finally:
            self.fusion_buffers.release(lease)

    def _fused_spmd_allreduce_program(self, n: int, dtype, reduce_op: str):
        """One compiled XLA program per (size bucket, dtype, op): the
        global stacked fusion buffer (P, n) — one row per process, sharded
        over the per-process sub-mesh — is reduced over the process axis,
        output replicated. Integer sums are exact (no duplication, and
        bucket padding is zeros for sum/average)."""
        key = ("spmd_allreduce", n, str(dtype), reduce_op)
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                _PROGRAM_CACHE_HITS.inc()
                return fn
        _PROGRAM_COMPILES.inc()

        replicated = NamedSharding(self._proc_mesh, P())
        reducer = _REDUCERS[reduce_op]

        def f(buf):
            return reducer(buf, axis=0)

        fn = jax.jit(f, out_shardings=replicated)
        with self._lock:
            self._programs[key] = fn
        return fn

    def _dispatch_allreduce_spmd(self, entries, timeline=None, pend=None):
        """Fused allreduce over a one-device-per-process sub-mesh in
        multi-process mode: pack entries into the flat persistent fusion
        buffer (padded to its size bucket — deterministic across ranks,
        the sizes are negotiated), place it on this process's row of a
        (P, bucket) global array (single host→device transfer), launch
        the compiled XLA collective (rides ICI/DCN), and return the
        completion tail that fetches + unpacks the replicated result. The
        slab lease rides on ``pend`` so the token releases it whether the
        response completes, fails, or the cycle aborts. The analogue of
        NCCLAllreduce on the reference's GPU path
        (nccl_operations.cc:55-105) with XLA in place of NCCL."""
        import numpy as np

        reduce_op = entries[0].reduce_op
        name0 = entries[0].name
        arrays = [np.asarray(e.tensor) for e in entries]
        if timeline is not None:
            timeline.activity_start(name0,
                                    timeline_mod.MEMCPY_IN_FUSION_BUFFER)
        lease, total = self._pack_fused(arrays, 1, arrays[0].dtype,
                                        reduce_op)
        if pend is not None:
            pend.lease = lease
            pend.bucket = lease.capacity
        flat = lease.array  # (1, bucket) — already the row layout
        from horovod_tpu.integrity import digest as integ_digest
        from horovod_tpu.integrity import inject as integ_inject

        plan = integ_inject.plan_dispatch()
        if plan == "nan" and flat.dtype.kind in ("f", "V"):
            integ_inject.corrupt_nan(flat)  # pre-reduce input poisoning
        check = self._integrity_due()
        # input digest over the exact payload — the [total:] tail is
        # reduction-identity padding (±inf for min/max), not corruption
        nf_in = (integ_digest.nonfinite_count(flat.ravel()[:total])
                 if check else 0)
        mesh = self._proc_mesh
        n_proc = mesh.devices.size
        row_sharding = NamedSharding(mesh, P("proc"))
        local_dev = [d for d in mesh.devices.flatten()
                     if d.process_index == jax.process_index()][0]
        local_row = jax.device_put(flat, local_dev)
        global_stack = jax.make_array_from_single_device_arrays(
            (n_proc, lease.capacity), row_sharding, [local_row])
        if timeline is not None:
            timeline.activity_end(name0)
            timeline.activity_start(name0, timeline_mod.XLA_COLLECTIVE)
        fn = self._fused_spmd_allreduce_program(
            lease.capacity, flat.dtype, reduce_op)
        out_dev = fn(global_stack)  # async launch; fetch in finish()

        def finish():
            out = np.asarray(out_dev)  # D2H, blocks on the collective
            if plan == "bitflip":
                out = out.copy()  # np.asarray of a jax.Array is read-only
                integ_inject.corrupt_bitflip(out)
            if check:
                # the drain runs on the cycle thread in dispatch order,
                # so the agreement exchange is in band with (never racing)
                # the ring's payload traffic; raises before unpack, and
                # complete() routes it to executor.integrity_failure
                records = integ_digest.exchange(
                    self.net, nf_in, integ_digest.checksum(out[:total]))
                integ_digest.verify(records,
                                    bucket=f"spmd[{lease.capacity}]",
                                    tensor=name0)
            if timeline is not None:
                timeline.activity_end(name0)
                timeline.activity_start(
                    name0, timeline_mod.MEMCPY_OUT_FUSION_BUFFER)
            off = 0
            for e, a in zip(entries, arrays):
                e.output = out[off:off + a.size].reshape(a.shape).astype(
                    a.dtype, copy=False)
                off += a.size
            if timeline is not None:
                timeline.activity_end(name0)

        return finish

    def _execute_allgather_host(self, response, entries) -> None:
        """Per-entry variable-size gather on the host wire. The wire wants
        one contiguous byte blob per entry; instead of a fresh
        ``tobytes()`` copy each time, contiguous arrays go out zero-copy
        (a ctypes view of their memory) and non-contiguous ones stage
        through one persistent bytearray reused across entries/cycles."""
        import ctypes

        import numpy as np

        resilience.inject("ring", "allgather")
        for e in entries:
            local = np.asarray(e.tensor)
            nb = local.nbytes
            if local.flags.c_contiguous and nb:
                blob = (ctypes.c_char * nb).from_address(
                    local.ctypes.data) if local.flags.writeable else \
                    ctypes.cast(local.ctypes.data,
                                ctypes.POINTER(ctypes.c_char * nb)).contents
            else:
                if len(self._ag_staging) < nb:
                    self._ag_staging = bytearray(nb)
                view = np.frombuffer(self._ag_staging, dtype=local.dtype,
                                     count=local.size)
                np.copyto(view.reshape(local.shape), local)
                blob = (ctypes.c_char * nb).from_buffer(self._ag_staging)
            blobs = self.net.allgatherv(blob)
            parts = []
            trailing = local.shape[1:]
            for r, blob_r in enumerate(blobs):
                a = np.frombuffer(blob_r, dtype=local.dtype)
                first = (response.tensor_sizes[r] if response.tensor_sizes
                         else a.size // max(int(np.prod(trailing)) or 1, 1))
                parts.append(a.reshape((first,) + trailing))
            e.output = np.concatenate(parts, axis=0)

    def _execute_reducescatter_host(self, entries) -> None:
        """Host reduce-scatter on the native half-ring kernel: w-1 ring
        steps moving one chunk each — (w-1)/w of the payload per link,
        the optimal byte count (the round-2 allreduce+slice fallback
        cost 2x; VERDICT r2 ask 6). The negotiation layer validated
        shape[0] %% world == 0, so the kernel's flat near-equal chunks
        coincide exactly with the leading-axis shards."""
        import numpy as np

        world = self.net.world
        hier_plan = self._hierarchy_plan()
        if hier_plan is None:
            # flat half-ring: (w-1) steps, each crossing the slow group
            # boundary (see _execute_allreduce_host on the seam)
            resilience.inject("ring", "reducescatter",
                              crossings=world - 1)
        from horovod_tpu.integrity import digest as integ_digest

        if self._integrity_due():
            # pre-reduce input digest (the ZeRO sharded-gradient lane):
            # each rank ends up holding a DIFFERENT shard, so there is
            # no replicated result to checksum — the agreement exchange
            # carries the non-finite counts only (constant CRC)
            nf_in = sum(integ_digest.nonfinite_count(np.asarray(e.tensor))
                        for e in entries)
            records = integ_digest.exchange(self.net, nf_in, 0)
            integ_digest.verify(records, bucket=f"rs[{len(entries)}]",
                                tensor=entries[0].name)
        for e in entries:
            a = np.asarray(e.tensor)
            wire = _widen_for_ring(a, copy=True)  # consumed as scratch
            if hier_plan is not None and wire.size % world == 0:
                # two-level reduce-scatter: j-major permutation + intra
                # RS + cross RS over 1/g of the bytes, same flat-chunk
                # output convention as the native kernel (ZeRO's shard
                # streams keep size % world == 0; ragged payloads fall
                # back to the flat ring per entry)
                from horovod_tpu.runtime import hierarchy

                chunk = hierarchy.hier_reducescatter(
                    self.net, hier_plan, wire.ravel(),
                    _RING_OP[e.reduce_op],
                    wire_dtype=self._hier_wire_dtype())
            else:
                chunk = self.net.reducescatter(wire.ravel(),
                                               _RING_OP[e.reduce_op])
            shard = a.shape[0] // world
            out = chunk.reshape((shard,) + a.shape[1:])
            if e.reduce_op == types.REDUCE_AVERAGE:
                out = out / world
            e.output = out.astype(a.dtype, copy=False)

    def _execute_alltoall_host(self, entries) -> None:
        """Host all-to-all on the native pairwise-exchange kernel: w-1
        rounds over the full mesh, every byte crossing exactly one link
        ((w-1)/w of the payload — the round-2 star-allgatherv fallback
        cost Wx; VERDICT r2 ask 6)."""
        import numpy as np

        resilience.inject("ring", "alltoall")
        for e in entries:
            a = np.ascontiguousarray(np.asarray(e.tensor))
            e.output = self.net.alltoall(a)

    def _execute_broadcast_host(self, entries) -> None:
        import numpy as np

        resilience.inject("ring", "broadcast")
        for e in entries:
            local = np.ascontiguousarray(np.asarray(e.tensor))
            blob = self.net.bcast_from(
                local.tobytes() if self.net.rank == e.root_rank else None,
                e.root_rank)
            e.output = np.frombuffer(
                blob, dtype=local.dtype).reshape(local.shape)
