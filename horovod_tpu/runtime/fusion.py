"""Tensor fusion: bin-packing responses under the fusion threshold.

TPU-native analogue of the reference's ``FuseResponses`` (reference:
horovod/common/controller.cc:551-672) and the fusion-buffer design
(reference: fusion_buffer_manager.cc, docs/tensor-fusion.rst:9-17): many
small tensors become one collective over a single fused buffer, trading a
little packing work for far fewer collective launches.

This module owns the *batching decision*: which responses fuse, bounded
by ``HOROVOD_FUSION_THRESHOLD`` bytes, with look-ahead past dtype
mismatches (reference: controller.cc:595-650). The buffer itself lives in
``fusion_buffer.py`` — a persistent host staging slab the executor packs
with ``np.copyto`` (the reference's FusionBufferManager) before launching
one bucket-keyed XLA reduction over it.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.metrics import RATIO_BUCKETS, registry as _metrics
from horovod_tpu.runtime import message as msg
from horovod_tpu.runtime import types

_FUSED_BATCHES = _metrics().counter(
    "horovod_fusion_batches_total",
    "Fused allreduce responses carrying more than one tensor.")
_FUSED_TENSORS = _metrics().counter(
    "horovod_fusion_tensors_total",
    "Tensors that left fusion inside a multi-tensor batch.")
_FUSED_BYTES = _metrics().counter(
    "horovod_fusion_bytes_total",
    "Payload bytes across all allreduce responses after bin-packing.")
_BUFFER_UTILIZATION = _metrics().histogram(
    "horovod_fusion_buffer_utilization_ratio",
    "Per-bin fill ratio: fused bytes / HOROVOD_FUSION_THRESHOLD.",
    buckets=RATIO_BUCKETS)


def _dtype_size(dtype: str) -> int:
    return np.dtype(dtype if dtype != "bfloat16" else "uint16").itemsize


def response_bytes(response: msg.Response,
                   request_by_name: Dict[str, msg.Request]) -> int:
    total = 0
    for name in response.tensor_names:
        req = request_by_name[name]
        total += int(np.prod(req.shape, dtype=np.int64)) * _dtype_size(req.dtype)
    return total


def _fusable(a: msg.Response, b: msg.Response,
             request_by_name: Dict[str, msg.Request]) -> bool:
    """Same response type + same dtype + same reduction params
    (reference: controller.cc:560-585 join conditions)."""
    if a.response_type != b.response_type:
        return False
    if a.response_type not in (types.ALLREDUCE,):
        # allgather fusion requires offset bookkeeping the eager TPU path
        # does not benefit from (one XLA program per gather already);
        # broadcast responses never fuse in the reference either.
        return False
    ra = request_by_name[a.tensor_names[0]]
    rb = request_by_name[b.tensor_names[0]]
    return (ra.dtype == rb.dtype and ra.reduce_op == rb.reduce_op)


def fuse_responses_py(responses: List[msg.Response],
                      request_by_name: Dict[str, msg.Request],
                      threshold_bytes: int) -> List[msg.Response]:
    """Greedy bin-packing with look-ahead (reference: controller.cc:551-672).

    Walk the response list; accumulate joinable responses into the current
    fused response while the byte total stays under ``threshold_bytes``.
    Non-joinable responses are *skipped over* (look-ahead) rather than
    flushing the bin, so a stray fp32 tensor between bf16 gradients does
    not break the bf16 bin — then form later bins from the skipped ones.
    """
    # deque walk: popleft is O(1), each response is examined once per bin
    # it fails to join (O(n·bins) total) — a list with pop(0) re-shifts
    # the whole tail for every bin head, going O(n²) on large backlogs
    remaining = collections.deque(responses)
    fused: List[msg.Response] = []
    while remaining:
        head = remaining.popleft()
        if head.response_type != types.ALLREDUCE:
            fused.append(head)
            continue
        acc_names = list(head.tensor_names)
        acc_bytes = response_bytes(head, request_by_name)
        skipped: "collections.deque" = collections.deque()
        while remaining:
            cand = remaining.popleft()
            if _fusable(head, cand, request_by_name):
                nbytes = response_bytes(cand, request_by_name)
                if acc_bytes + nbytes <= threshold_bytes:
                    acc_names.extend(cand.tensor_names)
                    acc_bytes += nbytes
                    continue
            skipped.append(cand)
        remaining = skipped
        fused.append(msg.Response(types.ALLREDUCE, acc_names))
    return fused


def fuse_responses_native(responses: List[msg.Response],
                          request_by_name: Dict[str, msg.Request],
                          threshold_bytes: int
                          ) -> Optional[List[msg.Response]]:
    """Same bin-packing executed by the C++ engine (cpp/cycle.cc hvc_fuse;
    the reference keeps FuseResponses native). Returns None if the native
    library is unavailable. Python precomputes per-response join keys
    (dtype + reduction params) and byte counts; C++ returns index groups.
    """
    import ctypes

    from horovod_tpu.runtime import native

    try:
        lib = native.load_library()
    except native.NativeUnavailableError:
        return None
    n = len(responses)
    is_ar = (ctypes.c_uint8 * n)()
    key_id = (ctypes.c_int64 * n)()
    nbytes = (ctypes.c_int64 * n)()
    key_ids: Dict[tuple, int] = {}
    for i, r in enumerate(responses):
        if r.response_type == types.ALLREDUCE:
            is_ar[i] = 1
            req = request_by_name[r.tensor_names[0]]
            key = (req.dtype, req.reduce_op)
            key_id[i] = key_ids.setdefault(key, len(key_ids))
            nbytes[i] = response_bytes(r, request_by_name)
    cap = 2 * n
    out = (ctypes.c_int32 * cap)()
    w = lib.hvc_fuse(n, is_ar, key_id, nbytes, threshold_bytes, out, cap)
    if w < 0:
        return None
    fused: List[msg.Response] = []
    pos = 0
    while pos < w:
        count = out[pos]
        idxs = [out[pos + 1 + j] for j in range(count)]
        pos += 1 + count
        if is_ar[idxs[0]]:
            names: List[str] = []
            for i in idxs:
                names.extend(responses[i].tensor_names)
            fused.append(msg.Response(types.ALLREDUCE, names))
        else:
            fused.append(responses[idxs[0]])
    return fused


def _record_fusion_metrics(fused: List[msg.Response],
                           request_by_name: Dict[str, msg.Request],
                           threshold_bytes: int) -> None:
    for resp in fused:
        if resp.response_type != types.ALLREDUCE:
            continue
        nbytes = response_bytes(resp, request_by_name)
        _FUSED_BYTES.inc(nbytes)
        if threshold_bytes > 0:
            _BUFFER_UTILIZATION.observe(nbytes / threshold_bytes)
        if len(resp.tensor_names) > 1:
            _FUSED_BATCHES.inc()
            _FUSED_TENSORS.inc(len(resp.tensor_names))


def fuse_responses(responses: List[msg.Response],
                   request_by_name: Dict[str, msg.Request],
                   threshold_bytes: int) -> List[msg.Response]:
    """Native bin-packing when available, Python otherwise (semantics are
    identical — tests/test_native_cycle.py asserts it differentially)."""
    from horovod_tpu.runtime.response_cache import native_cycle_enabled

    fused = None
    if responses and native_cycle_enabled():
        fused = fuse_responses_native(responses, request_by_name,
                                      threshold_bytes)
    if fused is None:
        fused = fuse_responses_py(responses, request_by_name,
                                  threshold_bytes)
    _record_fusion_metrics(fused, request_by_name, threshold_bytes)
    return fused
