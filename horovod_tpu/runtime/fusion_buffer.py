"""Persistent host fusion buffers + size-bucket policy for the data plane.

TPU-native analogue of the reference's ``FusionBufferManager``
(reference: horovod/common/fusion_buffer_manager.cc): instead of
``np.concatenate`` allocating a fresh host staging array every cycle, the
executor packs entry slices into a reusable per-(rows, bucket, dtype)
buffer with ``np.copyto``. Buffers are leased for the lifetime of one
dispatched response — the pipelined cycle body can have several responses
in flight, so a key may hold up to ``HOROVOD_CYCLE_PIPELINE_DEPTH``
buffers (each is reused as soon as its response completes).

The same module owns the **size-bucket policy** that keys the executor's
compiled-program caches: a fused flat payload is padded up to the next
power-of-two boundary above ``HOROVOD_FUSION_BUCKET_QUANTUM`` bytes
(identity below it), so steady-state training compiles O(#buckets) XLA
programs no matter how bin-packing regroups the same tensors from cycle
to cycle. Padding must not perturb the reduction — ``reduce_identity``
supplies the dtype-appropriate neutral element per reduce op (zeros for
sum/avg keep integer sums exact; ±inf / integer extremes for min/max;
ones for product) and the executor slices the pad off before unpacking.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Tuple

import numpy as np

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.runtime import types
from horovod_tpu.utils import env as env_mod

_BUF_ALLOCS = _metrics().counter(
    "horovod_fusion_buffer_allocs_total",
    "Persistent fusion-buffer allocations (new (rows, bucket, dtype) "
    "slabs; steady state stops growing).")
_BUF_REUSES = _metrics().counter(
    "horovod_fusion_buffer_reuses_total",
    "Fusion-buffer leases served from an existing slab (no allocation).")
_BUF_BYTES = _metrics().gauge(
    "horovod_fusion_buffer_bytes",
    "Total bytes held in persistent fusion buffers (resident slabs, "
    "leased or free), per purpose.", labelnames=("purpose",))
_BUF_LIVE_BYTES = _metrics().gauge(
    "horovod_fusion_buffer_live_bytes",
    "Bytes in fusion slabs currently checked out on a lease, per purpose. "
    "Returns to 0 between cycles; a leaked lease is visible here.",
    labelnames=("purpose",))
_BUF_LEASES_OUT = _metrics().gauge(
    "horovod_fusion_buffer_leases_outstanding",
    "Fusion-buffer leases acquired and not yet released, per purpose.",
    labelnames=("purpose",))

# every live manager, so the memory tracker can pull a per-purpose ledger
# (weak: an executor teardown drops its manager without unregistering)
_managers_lock = witness.make_lock("fusion_buffer._managers_lock")
_managers: "weakref.WeakSet" = weakref.WeakSet()  # guarded-by: _managers_lock


def bytes_by_purpose() -> Dict[str, Dict[str, int]]:
    """Aggregate slab accounting across every live manager, keyed by
    purpose label ("fusion" for data-plane staging, "ckpt_staging" for
    checkpoint slabs). The memory tracker's pull source."""
    with _managers_lock:
        managers = list(_managers)
    out: Dict[str, Dict[str, int]] = {}
    for mgr in managers:
        rec = out.setdefault(mgr.purpose, {
            "allocated_bytes": 0, "live_bytes": 0, "leases_outstanding": 0})
        rec["allocated_bytes"] += mgr.allocated_bytes()
        rec["live_bytes"] += mgr.live_bytes()
        rec["leases_outstanding"] += mgr.leases_outstanding()
    return out


def _refresh_gauges(purpose: str) -> None:
    rec = bytes_by_purpose().get(purpose)
    if rec is None:  # last manager of this purpose died
        rec = {"allocated_bytes": 0, "live_bytes": 0,
               "leases_outstanding": 0}
    _BUF_BYTES.labels(purpose=purpose).set(rec["allocated_bytes"])
    _BUF_LIVE_BYTES.labels(purpose=purpose).set(rec["live_bytes"])
    _BUF_LEASES_OUT.labels(purpose=purpose).set(rec["leases_outstanding"])

DEFAULT_BUCKET_QUANTUM_BYTES = env_mod.DEFAULT_FUSION_BUCKET_QUANTUM_BYTES


def bucket_elems(nelems: int, itemsize: int, quantum_bytes: int) -> int:
    """Element count of the size bucket holding ``nelems`` items.

    Payloads at or under the quantum keep their exact size (identity —
    tiny tensors don't pay padding for cache keys they'd rarely share);
    larger payloads round up to the next power-of-two multiple of the
    quantum, so arbitrary bin-packing totals collapse onto O(log) keys.
    """
    nbytes = nelems * itemsize
    if quantum_bytes <= 0 or nbytes <= quantum_bytes:
        return nelems
    bucket = quantum_bytes
    while bucket < nbytes:
        bucket <<= 1
    return -(-bucket // itemsize)  # ceil: quantum need not divide itemsize


def reduce_identity(dtype, reduce_op: str):
    """Neutral element of ``reduce_op`` for ``dtype`` — the only value the
    pad region may hold (reduced pad columns are sliced off before unpack,
    but the reduction itself must not overflow or NaN on them)."""
    dt = np.dtype(dtype)
    if reduce_op in (types.REDUCE_SUM, types.REDUCE_AVERAGE):
        return np.zeros((), dt)[()]
    if reduce_op == types.REDUCE_PRODUCT:
        return np.ones((), dt)[()]
    if reduce_op in (types.REDUCE_MIN, types.REDUCE_MAX):
        want_max = reduce_op == types.REDUCE_MIN
        if dt.kind in ("i", "u"):
            info = np.iinfo(dt)
            return dt.type(info.max if want_max else info.min)
        if dt.kind == "b":
            return dt.type(want_max)
        # floats incl. float16/bfloat16 (ml_dtypes registers kind "V"
        # types that still carry infinities)
        return dt.type(np.inf if want_max else -np.inf)
    raise ValueError(f"unknown reduce_op {reduce_op!r}")


class BufferLease:
    """One checked-out fusion buffer: ``array`` is (rows, capacity) in the
    requested dtype; ``capacity`` is the bucket element count per row."""

    __slots__ = ("array", "capacity", "_key", "_released")

    def __init__(self, array: np.ndarray, capacity: int, key: tuple):
        self.array = array
        self.capacity = capacity
        self._key = key
        self._released = False


class FusionBufferManager:
    """Reusable host staging arrays keyed by (rows, bucket, dtype).

    ``acquire`` hands out a free slab (allocating only on first sight of a
    key, or while more leases are outstanding than slabs exist — bounded
    by the cycle pipeline depth); ``release`` returns it for the next
    cycle. Only the background cycle thread packs, so contention is nil;
    the lock merely keeps the free lists consistent if an elastic restart
    tears the runtime down mid-flight.
    """

    def __init__(self,
                 quantum_bytes: int = DEFAULT_BUCKET_QUANTUM_BYTES,
                 purpose: str = "fusion") -> None:
        self.quantum_bytes = int(quantum_bytes)
        self.purpose = str(purpose)
        self._free: Dict[Tuple[int, int, str], List[np.ndarray]] = {}  # guarded-by: _lock
        self._lock = witness.make_lock("FusionBufferManager._lock")
        self._total_bytes = 0  # guarded-by: _lock
        self._live_bytes = 0   # guarded-by: _lock
        self._leases_out = 0   # guarded-by: _lock
        with _managers_lock:
            _managers.add(self)

    def bucket_elems(self, nelems: int, itemsize: int) -> int:
        return bucket_elems(nelems, itemsize, self.quantum_bytes)

    def acquire(self, rows: int, nelems: int, dtype) -> BufferLease:
        """Lease a (rows, bucket(nelems)) staging array. The caller packs
        real payload into ``array[:, :nelems]`` and pads the rest."""
        dt = np.dtype(dtype)
        capacity = self.bucket_elems(int(nelems), dt.itemsize)
        key = (int(rows), capacity, dt.str)
        with self._lock:
            free = self._free.get(key)
            if free:
                _BUF_REUSES.inc()
                array = free.pop()
                self._live_bytes += array.nbytes
                self._leases_out += 1
                reused = True
            else:
                reused = False
        if reused:
            # gauge refresh re-takes _managers_lock then per-manager
            # locks — must run outside our own _lock (lock order)
            _refresh_gauges(self.purpose)
            return BufferLease(array, capacity, key)
        _BUF_ALLOCS.inc()
        array = np.empty((int(rows), capacity), dt)
        with self._lock:
            self._total_bytes += array.nbytes
            self._live_bytes += array.nbytes
            self._leases_out += 1
        _refresh_gauges(self.purpose)
        return BufferLease(array, capacity, key)

    def release(self, lease: BufferLease) -> None:
        """Return a lease's slab to the free list. Idempotent: failure
        paths may release the same lease from more than one unwind."""
        with self._lock:
            if lease._released:
                return
            lease._released = True
            self._free.setdefault(lease._key, []).append(lease.array)
            self._live_bytes -= lease.array.nbytes
            self._leases_out -= 1
        _refresh_gauges(self.purpose)

    def allocated_bytes(self) -> int:
        """Resident slab bytes (leased or free) — the slab pool's size."""
        with self._lock:
            return self._total_bytes

    def live_bytes(self) -> int:
        """Bytes currently checked out on a lease. Returns to 0 when all
        leases are released — a leaked lease keeps this high forever."""
        with self._lock:
            return self._live_bytes

    def leases_outstanding(self) -> int:
        with self._lock:
            return self._leases_out
