"""Topology-aware two-level host collectives (hierarchical ring).

Reference Horovod's cross-node scaling trick is hierarchical allreduce
(NCCLHierarchicalAllreduce, nccl_operations.cc): reduce-scatter inside
the fast intra-node domain, run the only cross-node exchange over 1/g of
the bytes, then allgather the result back inside the node. This module
is the host-ring port of that decomposition: ranks are grouped into
slices — an explicit ``HOROVOD_HIERARCHY_GROUP_SIZE`` of contiguous
ranks, or host-derived from the rendezvous roster's hostnames — and the
three phases are composed from the native mesh's point-to-point
``sendrecv`` verb (every rank pair already holds a socket), so the slow
cross-group hop can be independently

  * compressed: the seed's ``compression.py`` wire dtypes (bf16 / IEEE
    f16) applied to JUST the cross hop — 1/g of the bytes at half
    precision on the slow link, full precision on the fast one
    (reference: fp16 compression halves MPI bytes, half.cc), and
  * fault-injected: ``HOROVOD_FAULT_INJECT=netdelay:<ms>:hop=cross``
    taxes only seams that declare slow-link crossings, so a simulated
    DCN penalizes each path by the traffic it actually puts there.

Numerical contract: with compression OFF the two-level sum is exact
whenever the flat ring's is (integer payloads; floats whose partial sums
are exactly representable) — fp addition is non-associative, so on
general float data the two paths agree only to rounding error, and the
parity tests pin bit-equality on exactly-representable values only.
With compression ON, every rank still ends bit-identical to its peers
(the cross hop's allgather phase distributes one set of wire bytes per
chunk), so the PR 10 cross-rank checksum agreement stays meaningful;
the error vs the uncompressed result is bounded by the wire dtype's
rounding (asserted in tests/test_hierarchy_plan.py).
"""

from __future__ import annotations

import dataclasses
import os
import socket
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from horovod_tpu import comms
from horovod_tpu.utils import logging as log
from horovod_tpu.utils import resilience

# ring-kernel op name -> in-place numpy combiner (matches RedOp in
# cpp/net.cc; "average" never reaches here — the executor divides after
# assembly, exactly as on the flat ring)
_COMBINE = {
    "sum": np.add,
    "min": np.minimum,
    "max": np.maximum,
    "product": np.multiply,
}


@dataclasses.dataclass(frozen=True)
class HierarchyPlan:
    """One rank's view of the two-level grouping.

    ``members`` is this rank's slice in ring order; ``cross_members``
    holds the one rank per slice sharing this rank's ``local_index`` —
    the slow-hop ring. A degenerate plan (``group_size`` or
    ``num_groups`` of 1) means the topology offers no hierarchy and the
    flat ring should be used; ``enabled`` gates that."""

    world: int
    rank: int
    group_size: int          # g: ranks per slice
    num_groups: int          # G: slices
    members: Tuple[int, ...]         # my slice, ring order
    cross_members: Tuple[int, ...]   # same-local-index ranks, ring order
    group_index: int         # which slice I'm in = my cross-ring position
    local_index: int         # my position within the slice
    source: str              # "env" | "hosts" | "flat"

    @property
    def enabled(self) -> bool:
        return self.group_size > 1 and self.num_groups > 1

    def describe(self) -> str:
        return (f"{self.num_groups}x{self.group_size} ({self.source}); "
                f"rank {self.rank} = group {self.group_index} "
                f"slot {self.local_index}")


def _flat(world: int, rank: int) -> HierarchyPlan:
    return HierarchyPlan(world, rank, 1, world, (rank,),
                         tuple(range(world)), rank, 0, "flat")


def build_plan(net, group_size: int = 0) -> HierarchyPlan:
    """Form groups for ``net``'s world. An explicit ``group_size`` takes
    contiguous rank blocks with no wire traffic; ``group_size == 0``
    derives groups from the rendezvous roster's hostnames (one
    allgatherv — the launcher exports ``HOROVOD_HOSTNAME`` to every
    rank, run/hosts.py). Uneven or degenerate topologies fall back to a
    flat plan with a warning: the decomposition needs equal-size groups
    (the cross ring pairs one member per slice at each slot)."""
    w, r = net.world, net.rank
    if w < 4:
        return _flat(w, r)  # two levels need >= 2 groups of >= 2
    if group_size:
        g = int(group_size)
        if g < 2 or w % g or w // g < 2:
            log.warning(
                "hierarchy: HOROVOD_HIERARCHY_GROUP_SIZE=%d does not "
                "tile world %d into >=2 equal groups of >=2 — flat ring",
                g, w)
            return _flat(w, r)
        gi, j = divmod(r, g)
        return HierarchyPlan(
            w, r, g, w // g, tuple(range(gi * g, (gi + 1) * g)),
            tuple(k * g + j for k in range(w // g)), gi, j, "env")
    # host-derived: group ranks sharing a hostname (the real slow-link
    # boundary). One collective, memoized by the executor per (net,
    # world) so elastic re-forms recompute it for the new roster.
    host = os.environ.get("HOROVOD_HOSTNAME") or socket.gethostname()
    hosts = [b.decode("utf-8", "replace") for b in
             net.allgatherv(host.encode())]
    by_host = {}
    for rr, h in enumerate(hosts):
        by_host.setdefault(h, []).append(rr)
    groups = sorted(by_host.values(), key=lambda m: m[0])
    sizes = {len(m) for m in groups}
    if len(groups) < 2 or len(sizes) != 1 or next(iter(sizes)) < 2:
        return _flat(w, r)
    g = len(groups[0])
    gi = next(i for i, m in enumerate(groups) if r in m)
    j = groups[gi].index(r)
    return HierarchyPlan(
        w, r, g, len(groups), tuple(groups[gi]),
        tuple(m[j] for m in groups), gi, j, "hosts")


# ---------------------------------------------------------------------------
# ring primitives over sendrecv (subgroup analogues of the cpp/net.cc
# full-world kernels; same chunk conventions)
# ---------------------------------------------------------------------------

def _cb(n: int, k: int, i: int) -> int:
    """Chunk boundary i of n elements over k near-equal chunks — the
    same split as the native ring kernels, so empty chunks (n < k)
    no-op consistently on both ends of every exchange."""
    return n * i // k


def _ring_reduce_scatter(net, ring: Sequence[int], pos: int,
                         buf: np.ndarray, op: str) -> Tuple[int, int]:
    """In-place ring reduce-scatter over ``ring``; afterwards chunk
    ``pos`` of ``buf`` holds the fully ring-reduced values (the native
    kernel's shifted-by-one convention). Returns the owned chunk's
    [begin, end). Non-owned chunks are left holding partial sums."""
    k = len(ring)
    n = buf.size
    if k == 1:
        return 0, n
    comb = _COMBINE[op]
    nxt, prv = ring[(pos + 1) % k], ring[(pos - 1) % k]
    max_chunk = max(_cb(n, k, i + 1) - _cb(n, k, i) for i in range(k))
    recv = np.empty(max_chunk, buf.dtype)
    for step in range(k - 1):
        sc = (pos - step - 1) % k
        rc = (pos - step - 2) % k
        sb, se = _cb(n, k, sc), _cb(n, k, sc + 1)
        rb, re = _cb(n, k, rc), _cb(n, k, rc + 1)
        net.sendrecv(nxt, buf[sb:se], prv, recv[:re - rb])
        if re > rb:
            comb(buf[rb:re], recv[:re - rb], out=buf[rb:re])
    return _cb(n, k, pos), _cb(n, k, pos + 1)


def _ring_allgather(net, ring: Sequence[int], pos: int,
                    buf: np.ndarray) -> None:
    """In-place ring allgather over ``ring``: chunk ``pos`` (this rank's,
    per the reduce-scatter convention) is distributed until every member
    holds all k chunks. Receives land directly in ``buf``."""
    k = len(ring)
    if k == 1:
        return
    n = buf.size
    nxt, prv = ring[(pos + 1) % k], ring[(pos - 1) % k]
    for step in range(k - 1):
        sc = (pos - step) % k
        rc = (pos - step - 1) % k
        sb, se = _cb(n, k, sc), _cb(n, k, sc + 1)
        rb, re = _cb(n, k, rc), _cb(n, k, rc + 1)
        net.sendrecv(nxt, buf[sb:se], prv, buf[rb:re])


def _ring_allreduce(net, ring: Sequence[int], pos: int,
                    buf: np.ndarray, op: str) -> None:
    """In-place ring allreduce (reduce-scatter + allgather) over
    ``ring`` — 2(k-1) exchange steps, the cross hop's kernel."""
    _ring_reduce_scatter(net, ring, pos, buf, op)
    _ring_allgather(net, ring, pos, buf)


# ---------------------------------------------------------------------------
# two-level collectives
# ---------------------------------------------------------------------------

def hier_allreduce(net, plan: HierarchyPlan, buf: np.ndarray, op: str,
                   wire_dtype=None) -> np.ndarray:
    """Two-level in-place allreduce on a contiguous 1-D host array:
    intra-group reduce-scatter -> cross-group ring allreduce over only
    this rank's 1/g chunk (cast to ``wire_dtype`` for the slow hop when
    given and the payload is floating) -> intra-group allgather.
    Averaging stays with the caller — the executor divides after
    assembly, exactly as on the flat ring."""
    g, big_g = plan.group_size, plan.num_groups
    t0 = time.perf_counter()
    resilience.inject("hier_intra", "reducescatter", crossings=0)
    b, e = _ring_reduce_scatter(net, plan.members, plan.local_index,
                                buf, op)
    t1 = time.perf_counter()
    comms.record("reducescatter", "hier_intra", buf.nbytes, t1 - t0,
                 world=g)
    chunk = buf[b:e]
    # every step of the cross ring crosses the slow group boundary:
    # 2(G-1) exchanges for the allreduce
    resilience.inject("hier_cross", "allreduce",
                      crossings=2 * (big_g - 1))
    if wire_dtype is not None and chunk.dtype.kind == "f" \
            and chunk.size and np.dtype(wire_dtype) != chunk.dtype:
        # the compression hop: wire bytes halve; accumulation happens in
        # the wire dtype (the reference's fp16-MPI semantics, half.cc) —
        # all cross peers end with identical wire bytes, so cross-rank
        # digests still agree after decompression
        wire = np.ascontiguousarray(chunk.astype(wire_dtype))
        _ring_allreduce(net, plan.cross_members, plan.group_index,
                        wire, op)
        chunk[...] = wire.astype(chunk.dtype)
        cross_bytes = wire.nbytes
    else:
        _ring_allreduce(net, plan.cross_members, plan.group_index,
                        chunk, op)
        cross_bytes = chunk.nbytes
    t2 = time.perf_counter()
    if cross_bytes:
        comms.record("allreduce", "hier_cross", cross_bytes, t2 - t1,
                     world=big_g)
    resilience.inject("hier_intra", "allgather", crossings=0)
    _ring_allgather(net, plan.members, plan.local_index, buf)
    comms.record("allgather", "hier_intra", buf.nbytes,
                 time.perf_counter() - t2, world=g)
    return buf


def hier_reducescatter(net, plan: HierarchyPlan, arr: np.ndarray,
                       op: str, wire_dtype=None) -> np.ndarray:
    """Two-level reduce-scatter with the flat ring's output convention:
    rank r receives flat chunk r. Requires ``arr.size % world == 0``
    (ZeRO's shard streams guarantee it; the executor falls back to the
    flat ring otherwise).

    Layout: flat chunk i belongs to rank i = (i // g, i % g). The
    j-major permutation ``reshape(G, g, c).transpose(1, 0, 2)`` makes
    the G chunks destined for slice slot j contiguous, so the intra
    reduce-scatter hands slot j its superchunk and the cross
    reduce-scatter (G-1 slow-link steps over 1/g of the bytes) carves
    out exactly flat chunk ``group_index * g + local_index``."""
    w, g, big_g = plan.world, plan.group_size, plan.num_groups
    n = arr.size
    if n % w:
        raise ValueError(
            f"hier_reducescatter needs size % world == 0, got {n} % {w}")
    c = n // w
    work = np.ascontiguousarray(
        arr.reshape(big_g, g, c).transpose(1, 0, 2)).reshape(-1)
    t0 = time.perf_counter()
    resilience.inject("hier_intra", "reducescatter", crossings=0)
    b, e = _ring_reduce_scatter(net, plan.members, plan.local_index,
                                work, op)
    t1 = time.perf_counter()
    comms.record("reducescatter", "hier_intra", work.nbytes, t1 - t0,
                 world=g)
    sup = work[b:e]
    resilience.inject("hier_cross", "reducescatter",
                      crossings=big_g - 1)
    if wire_dtype is not None and sup.dtype.kind == "f" \
            and sup.size and np.dtype(wire_dtype) != sup.dtype:
        wire = np.ascontiguousarray(sup.astype(wire_dtype))
        b2, e2 = _ring_reduce_scatter(net, plan.cross_members,
                                      plan.group_index, wire, op)
        out = wire[b2:e2].astype(sup.dtype)
        cross_bytes = wire.nbytes
    else:
        b2, e2 = _ring_reduce_scatter(net, plan.cross_members,
                                      plan.group_index, sup, op)
        out = sup[b2:e2].copy()
        cross_bytes = sup.nbytes
    if cross_bytes:
        comms.record("reducescatter", "hier_cross", cross_bytes,
                     time.perf_counter() - t1, world=big_g)
    return out


def wire_dtype_from_name(name: str) -> Optional[np.dtype]:
    """Map the ``HOROVOD_HIERARCHY_COMPRESSION`` knob to the numpy wire
    dtype for the slow hop (the host-side counterparts of
    ``compression.Compression``'s jnp wire dtypes). ``none``/empty
    disables compression; unknown names raise."""
    name = (name or "none").strip().lower()
    if name in ("", "none", "off", "0", "false"):
        return None
    if name in ("fp16", "bf16", "bfloat16"):
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if name in ("ieee_fp16", "float16", "f16"):
        return np.dtype(np.float16)
    raise ValueError(
        f"unknown HOROVOD_HIERARCHY_COMPRESSION {name!r} "
        "(expected none | fp16 | ieee_fp16)")
