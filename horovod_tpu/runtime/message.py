"""Wire messages for controller negotiation.

TPU-native analogue of the reference's flatbuffers-defined coordination
messages (reference: horovod/common/message.h:45-210,
horovod/common/wire/message.fbs:41-100): a ``Request`` announces one named
tensor ready on one worker; a ``Response`` carries the coordinator's verdict
for one (possibly fused) set of tensors.

Serialization is a compact length-prefixed binary format (struct-packed —
no schema compiler needed; the format is versioned with a magic byte so the
C++ runtime can speak it too).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Tuple

from horovod_tpu.runtime import types

_MAGIC = 0x48  # 'H'
# v2: the request op byte carries a reduce-op code (0=sum, 1=average,
# 2=min, 3=max, 4=product) where v1 carried a boolean average byte — a
# version-skewed peer must reject the frame, not misread min as average.
_VERSION = 2

_REQUEST_TYPES = {types.ALLREDUCE: 0, types.ALLGATHER: 1, types.BROADCAST: 2,
                  types.INVALIDATE: 4, types.REDUCESCATTER: 5,
                  types.ALLTOALL: 6}
_REQUEST_TYPES_INV = {v: k for k, v in _REQUEST_TYPES.items()}
_RESPONSE_TYPES = {types.ALLREDUCE: 0, types.ALLGATHER: 1,
                   types.BROADCAST: 2, types.ERROR: 3, types.INVALIDATE: 4,
                   types.REDUCESCATTER: 5, types.ALLTOALL: 6}
_RESPONSE_TYPES_INV = {v: k for k, v in _RESPONSE_TYPES.items()}

# Reduce-op wire codes. Codes 0/1 preserve the *meaning* of the old v1
# boolean ``average`` byte (0=sum, 1=average) so the assignment stays
# self-documenting; version-skewed frames are still rejected outright by
# the _VERSION check above, never interpreted.
_REDUCE_OPS = {types.REDUCE_SUM: 0, types.REDUCE_AVERAGE: 1,
               types.REDUCE_MIN: 2, types.REDUCE_MAX: 3,
               types.REDUCE_PRODUCT: 4}
_REDUCE_OPS_INV = {v: k for k, v in _REDUCE_OPS.items()}


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack("<I", len(b)) + b


def _unpack_str(buf: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return buf[off:off + n].decode("utf-8"), off + n


@dataclasses.dataclass(frozen=True)
class Request:
    """reference: message.h Request (rank, type, dtype, name, root_rank,
    device, shape)."""

    rank: int
    request_type: str
    tensor_name: str
    dtype: str
    shape: Tuple[int, ...]
    root_rank: int = 0
    reduce_op: str = types.REDUCE_AVERAGE

    def pack(self) -> bytes:
        head = struct.pack(
            "<BBiBiB", _MAGIC, _VERSION, self.rank,
            _REQUEST_TYPES[self.request_type], self.root_rank,
            _REDUCE_OPS[self.reduce_op])
        body = _pack_str(self.tensor_name) + _pack_str(self.dtype)
        body += struct.pack("<I", len(self.shape))
        body += struct.pack(f"<{len(self.shape)}q", *self.shape)
        return head + body

    @staticmethod
    def unpack(buf: bytes, off: int = 0) -> Tuple["Request", int]:
        magic, ver, rank, rtype, root, rop = struct.unpack_from("<BBiBiB",
                                                                buf, off)
        if magic != _MAGIC or ver != _VERSION:
            raise ValueError("bad request header")
        off += struct.calcsize("<BBiBiB")
        name, off = _unpack_str(buf, off)
        dtype, off = _unpack_str(buf, off)
        (ndim,) = struct.unpack_from("<I", buf, off)
        off += 4
        shape = struct.unpack_from(f"<{ndim}q", buf, off)
        off += 8 * ndim
        return Request(rank, _REQUEST_TYPES_INV[rtype], name, dtype,
                       tuple(shape), root, _REDUCE_OPS_INV[rop]), off


@dataclasses.dataclass
class Response:
    """reference: message.h Response (type, names, error message, devices,
    sizes). A fused response lists several tensor names executed as one
    collective."""

    response_type: str
    tensor_names: List[str] = dataclasses.field(default_factory=list)
    error_message: str = ""
    # per-rank first-dim sizes for allgather (reference: fused allgather
    # add_allgather_response)
    tensor_sizes: List[int] = dataclasses.field(default_factory=list)

    def pack(self) -> bytes:
        out = struct.pack("<BBB", _MAGIC, _VERSION,
                          _RESPONSE_TYPES[self.response_type])
        out += struct.pack("<I", len(self.tensor_names))
        for n in self.tensor_names:
            out += _pack_str(n)
        out += _pack_str(self.error_message)
        out += struct.pack("<I", len(self.tensor_sizes))
        if self.tensor_sizes:
            out += struct.pack(f"<{len(self.tensor_sizes)}q",
                               *self.tensor_sizes)
        return out

    @staticmethod
    def unpack(buf: bytes, off: int = 0) -> Tuple["Response", int]:
        magic, ver, rtype = struct.unpack_from("<BBB", buf, off)
        if magic != _MAGIC or ver != _VERSION:
            raise ValueError("bad response header")
        off += 3
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        names = []
        for _ in range(n):
            s, off = _unpack_str(buf, off)
            names.append(s)
        err, off = _unpack_str(buf, off)
        (ns,) = struct.unpack_from("<I", buf, off)
        off += 4
        sizes = list(struct.unpack_from(f"<{ns}q", buf, off))
        off += 8 * ns
        return Response(_RESPONSE_TYPES_INV[rtype], names, err, sizes), off


def pack_request_list(requests: List[Request]) -> bytes:
    out = struct.pack("<I", len(requests))
    for r in requests:
        out += r.pack()
    return out


def unpack_request_list(buf: bytes) -> List[Request]:
    (n,) = struct.unpack_from("<I", buf, 0)
    off = 4
    out = []
    for _ in range(n):
        r, off = Request.unpack(buf, off)
        out.append(r)
    return out


def pack_response_list(responses: List[Response]) -> bytes:
    out = struct.pack("<I", len(responses))
    for r in responses:
        out += r.pack()
    return out


def unpack_response_list(buf: bytes) -> List[Response]:
    (n,) = struct.unpack_from("<I", buf, 0)
    off = 4
    out = []
    for _ in range(n):
        r, off = Response.unpack(buf, off)
        out.append(r)
    return out
