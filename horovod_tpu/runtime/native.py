"""ctypes bindings for the native transport library.

Loads ``libhvdtpu_net.so`` (built from ``horovod_tpu/cpp/net.cc`` — the
Gloo-layer analogue, see that file's header) and exposes the controller
verbs + host collectives as a ``NetComm`` object. The library is built on
demand with ``make`` if missing (the reference similarly builds vendored
gloo during setup, reference: setup.py:49); binding is ctypes because the
image has no pybind11 (reference used pybind11, torch/mpi_ops_v2.cc).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

from horovod_tpu.exceptions import WorkerLostError

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "libhvdtpu_net.so")

_lib = None
_lib_lock = threading.Lock()


class NativeUnavailableError(RuntimeError):
    pass


_lib_error: Optional[str] = None


def load_library(build_if_missing: bool = True, retry_failed: bool = False):
    """Load (building if needed) the native library; raises
    NativeUnavailableError if no toolchain is available. Failure is cached
    so callers on the hot cycle path (fusion/cache) fall back to Python
    without re-running make/dlopen every cycle — but callers for whom the
    library is REQUIRED (the transport) pass ``retry_failed=True`` so a
    transient build failure (e.g. flock contention exceeding the make
    timeout when many workers launch at once) does not permanently poison
    the process."""
    global _lib, _lib_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_error is not None:
            if not retry_failed:
                raise NativeUnavailableError(_lib_error)
            _lib_error = None
        try:
            _lib = _load_locked(build_if_missing)
        except NativeUnavailableError as exc:
            _lib_error = str(exc)
            raise
        return _lib


def _load_locked(build_if_missing: bool):
    if build_if_missing:
        # Always invoke make — a fresh build is a no-op, and a stale
        # .so from before a source file was added would otherwise load
        # with missing symbols. Simultaneously-launched workers race
        # here; an fcntl lock serializes them (and the Makefile writes
        # the .so atomically via tmp+rename) so nobody dlopens a
        # half-written library.
        try:
            import fcntl

            lock_path = os.path.join(_CPP_DIR, ".build_lock")
            with open(lock_path, "w") as lock_file:
                fcntl.flock(lock_file, fcntl.LOCK_EX)
                try:
                    subprocess.run(["make", "-C", _CPP_DIR],
                                   check=True, capture_output=True,
                                   timeout=120)
                finally:
                    fcntl.flock(lock_file, fcntl.LOCK_UN)
        except Exception as exc:
            if not os.path.exists(_LIB_PATH):
                raise NativeUnavailableError(
                    f"could not build native transport: {exc}") from exc
            # toolchain gone but a previously-built library exists —
            # fall through and try to load it
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as exc:
        raise NativeUnavailableError(str(exc)) from exc

    try:
        _bind_symbols(lib)
    except AttributeError as exc:
        # stale library missing newer symbols and no toolchain to
        # rebuild it
        raise NativeUnavailableError(
            f"stale native library {_LIB_PATH}: {exc}") from exc
    return lib


_ABI_VERSION = 5  # must match hvdnet_abi_version() in cpp/net.cc


def _bind_symbols(lib) -> None:
    # A stale prebuilt library can resolve every symbol yet have an
    # incompatible signature (ctypes argtypes are Python-side only) —
    # verify the compiled-in ABI version before trusting it.
    lib.hvdnet_abi_version.restype = ctypes.c_int
    lib.hvdnet_abi_version.argtypes = []
    got = lib.hvdnet_abi_version()
    if got != _ABI_VERSION:
        raise AttributeError(
            f"native ABI version {got} != expected {_ABI_VERSION}")
    lib.hvdnet_init.restype = ctypes.c_void_p
    lib.hvdnet_init.argtypes = [ctypes.c_int, ctypes.c_int,
                                ctypes.c_char_p, ctypes.c_int,
                                ctypes.c_int]
    lib.hvdnet_finalize.argtypes = [ctypes.c_void_p]
    lib.hvdnet_abort.argtypes = [ctypes.c_void_p]
    lib.hvdnet_rank.argtypes = [ctypes.c_void_p]
    lib.hvdnet_world.argtypes = [ctypes.c_void_p]
    lib.hvdnet_barrier.argtypes = [ctypes.c_void_p]
    lib.hvdnet_bit_and_or.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.hvdnet_gatherv.restype = ctypes.c_int64
    lib.hvdnet_gatherv.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.hvdnet_bcast.restype = ctypes.c_int64
    lib.hvdnet_bcast.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64]
    for name in ("hvdnet_allreduce_f32", "hvdnet_allreduce_f64",
                 "hvdnet_allreduce_i32", "hvdnet_allreduce_i64"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                       ctypes.c_int]
    for name in ("hvdnet_reducescatter_f32", "hvdnet_reducescatter_f64",
                 "hvdnet_reducescatter_i32", "hvdnet_reducescatter_i64"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                       ctypes.c_int, ctypes.c_void_p]
    lib.hvdnet_alltoall.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_void_p, ctypes.c_uint64]
    lib.hvdnet_sendrecv.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64]
    lib.hvdnet_data_bytes_sent.restype = ctypes.c_uint64
    lib.hvdnet_data_bytes_sent.argtypes = [ctypes.c_void_p]
    lib.hvdnet_exchange_calls.restype = ctypes.c_uint64
    lib.hvdnet_exchange_calls.argtypes = [ctypes.c_void_p]
    lib.hvdnet_ctrl_bytes_sent.restype = ctypes.c_uint64
    lib.hvdnet_ctrl_bytes_sent.argtypes = [ctypes.c_void_p]
    lib.hvdnet_allgatherv.restype = ctypes.c_int64
    lib.hvdnet_allgatherv.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64)]
    # timeline writer (timeline.cc)
    lib.hvd_tl_open.restype = ctypes.c_void_p
    lib.hvd_tl_open.argtypes = [ctypes.c_char_p]
    lib.hvd_tl_emit.restype = ctypes.c_int
    lib.hvd_tl_emit.argtypes = [
        ctypes.c_void_p, ctypes.c_char, ctypes.c_int, ctypes.c_double,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    lib.hvd_tl_close.argtypes = [ctypes.c_void_p]
    # cycle engine: response cache + fusion (cycle.cc)
    lib.hvc_cache_new.restype = ctypes.c_void_p
    lib.hvc_cache_new.argtypes = [ctypes.c_int64]
    lib.hvc_cache_free.argtypes = [ctypes.c_void_p]
    lib.hvc_cache_cached.restype = ctypes.c_int
    lib.hvc_cache_cached.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p, ctypes.c_int64]
    lib.hvc_cache_put.restype = ctypes.c_int64
    lib.hvc_cache_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_char_p, ctypes.c_int64]
    lib.hvc_cache_bit_for_name.restype = ctypes.c_int64
    lib.hvc_cache_bit_for_name.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.hvc_cache_get_len.restype = ctypes.c_int64
    lib.hvc_cache_get_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.hvc_cache_get.restype = ctypes.c_int64
    lib.hvc_cache_get.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_char_p, ctypes.c_int64]
    lib.hvc_cache_invalidate.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.hvc_cache_size.restype = ctypes.c_int64
    lib.hvc_cache_size.argtypes = [ctypes.c_void_p]
    lib.hvc_fuse.restype = ctypes.c_int64
    lib.hvc_fuse.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]


def native_built() -> bool:
    """Capability probe for the native transport (analogue of
    ``horovod_gloo_built``)."""
    try:
        load_library(build_if_missing=True)
        return True
    except NativeUnavailableError:
        return False


_ALLREDUCE_FN = {
    np.dtype(np.float32): "hvdnet_allreduce_f32",
    np.dtype(np.float64): "hvdnet_allreduce_f64",
    np.dtype(np.int32): "hvdnet_allreduce_i32",
    np.dtype(np.int64): "hvdnet_allreduce_i64",
}

_REDUCESCATTER_FN = {
    np.dtype(np.float32): "hvdnet_reducescatter_f32",
    np.dtype(np.float64): "hvdnet_reducescatter_f64",
    np.dtype(np.int32): "hvdnet_reducescatter_i32",
    np.dtype(np.int64): "hvdnet_reducescatter_i64",
}

# op codes shared with cpp/net.cc RedOp ("average" is sum + host divide)
_RING_OPS = {"sum": 0, "min": 1, "max": 2, "product": 3}



class NetComm:
    """One process's membership in the TCP communicator (star + ring).

    ``bit_words``: fixed uint64-word width of the coordination bitvector.
    The width is statically bounded by the response-cache capacity plus the
    status bits, so it is agreed once at construction instead of per cycle
    (the per-cycle sync is the steady-state fast path's only collective —
    reference: response_cache.cc:308 syncs fixed-width chunks the same way).
    """

    def __init__(self, rank: int, world: int, coord_host: str = "127.0.0.1",
                 coord_port: int = 29500, timeout_ms: int = 30_000,
                 bit_words: int = 17):
        self._lib = load_library(retry_failed=True)
        self._h = self._lib.hvdnet_init(
            rank, world, coord_host.encode(), coord_port, timeout_ms)
        if not self._h:
            raise RuntimeError(
                f"native transport init failed (rank {rank}/{world} via "
                f"{coord_host}:{coord_port})")
        self.rank = rank
        self.world = world
        self.bit_words = bit_words
        self._lock = threading.Lock()

    def close(self) -> None:
        with self._lock:
            if self._h:
                self._lib.hvdnet_finalize(self._h)
                self._h = None

    def abort(self) -> None:
        """Wake any verb blocked on this communicator (collective-timeout
        watchdog). Deliberately does NOT take ``self._lock`` — the blocked
        verb is holding it, and that is exactly the thread being woken.
        Safe against ``close()``: the handle can only be finalized under
        the lock, which the blocked verb owns until abort() unblocks it."""
        h = self._h
        if h:
            self._lib.hvdnet_abort(h)

    def barrier(self) -> None:
        with self._lock:
            if self._lib.hvdnet_barrier(self._h) != 0:
                raise WorkerLostError(
                    "barrier failed (peer closed or "
                    "transport lost)")

    def bit_and_or(self, bits: int) -> Tuple[int, int]:
        """Cross-worker bitwise AND/OR of the coordination bitvector
        (fixed ``bit_words`` uint64 words — one round trip, no width
        agreement)."""
        nwords = self.bit_words
        if bits.bit_length() > nwords * 64:
            raise ValueError(
                f"bitvector needs {bits.bit_length()} bits but transport "
                f"width is {nwords * 64} (raise bit_words / cache capacity "
                "mismatch)")
        words = np.frombuffer(
            bits.to_bytes(nwords * 8, "little"), dtype=np.uint64).copy()
        out_and = np.zeros(nwords, dtype=np.uint64)
        out_or = np.zeros(nwords, dtype=np.uint64)
        with self._lock:
            rc = self._lib.hvdnet_bit_and_or(
                self._h,
                words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                nwords,
                out_and.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                out_or.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        if rc != 0:
            raise WorkerLostError(
                "bit_and_or failed (peer closed or "
                "transport lost)")
        return (int.from_bytes(out_and.tobytes(), "little"),
                int.from_bytes(out_or.tobytes(), "little"))

    def _gatherv_raw(self, blob: bytes, cap: int) -> Optional[List[bytes]]:
        lens = (ctypes.c_uint64 * self.world)()
        out = ctypes.create_string_buffer(cap) if self.rank == 0 else None
        with self._lock:
            total = self._lib.hvdnet_gatherv(
                self._h, blob, len(blob), out,
                cap if self.rank == 0 else 0, lens)
        if total < 0:
            raise WorkerLostError(
                "gatherv failed (peer closed or "
                "transport lost)")
        if self.rank != 0:
            return None
        blobs, off = [], 0
        raw = out.raw
        for r in range(self.world):
            n = int(lens[r])
            blobs.append(raw[off:off + n])
            off += n
        return blobs

    def gatherv(self, blob: bytes) -> Optional[List[bytes]]:
        """Workers send to rank 0; rank 0 returns all blobs (rank order),
        workers return None. Two-phase (sizes first) — no payload cap."""
        sizes = self._gatherv_raw(
            np.uint64(len(blob)).tobytes(), 16 * self.world)
        cap = 0
        if self.rank == 0:
            cap = int(sum(np.frombuffer(b, dtype=np.uint64)[0]
                          for b in sizes)) or 1
        return self._gatherv_raw(blob, cap)

    def _bcast_raw(self, blob: Optional[bytes], cap: int) -> bytes:
        if self.rank == 0:
            assert blob is not None
            buf = ctypes.create_string_buffer(blob, len(blob))
            with self._lock:
                rc = self._lib.hvdnet_bcast(self._h, buf, len(blob))
            if rc < 0:
                raise WorkerLostError(
                    "bcast failed (peer closed or "
                    "transport lost)")
            return blob
        buf = ctypes.create_string_buffer(max(cap, 1))
        with self._lock:
            n = self._lib.hvdnet_bcast(self._h, buf, cap)
        if n < 0:
            raise WorkerLostError(
                "bcast failed (peer closed or "
                "transport lost)")
        return buf.raw[:n]

    def bcast(self, blob: Optional[bytes]) -> bytes:
        """Rank 0 passes the blob; workers pass None and receive it.
        Two-phase (size first) — no payload cap."""
        size_blob = self._bcast_raw(
            np.uint64(len(blob)).tobytes() if self.rank == 0 else None, 8)
        size = int(np.frombuffer(size_blob, dtype=np.uint64)[0])
        return self._bcast_raw(blob, size)

    def bcast_from(self, blob: Optional[bytes], root: int) -> bytes:
        """Broadcast from an arbitrary root: root relays through rank 0,
        then the star bcast fans out (payload moves once per link, unlike
        an allgather)."""
        if root == 0:
            return self.bcast(blob if self.rank == 0 else None)
        relayed = self.gatherv(blob if self.rank == root else b"")
        if self.rank == 0:
            return self.bcast(relayed[root])
        return self.bcast(None)

    def allreduce(self, arr: np.ndarray, op: str = "sum") -> np.ndarray:
        """In-place ring allreduce on a contiguous host array.

        ``op`` is one of sum/min/max/product (reference generalizes its op
        dispatch the same way, horovod/torch/mpi_ops_v2.cc:52-76; the ring
        reduction body only differs in the combine step)."""
        if arr.dtype not in _ALLREDUCE_FN:
            raise TypeError(f"unsupported dtype {arr.dtype} for host "
                            "allreduce (use float32/float64/int32/int64)")
        if op not in _RING_OPS:
            raise ValueError(f"unsupported ring allreduce op {op!r}")
        arr = np.ascontiguousarray(arr)
        fn = getattr(self._lib, _ALLREDUCE_FN[arr.dtype])
        with self._lock:
            rc = fn(self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.size,
                    _RING_OPS[op])
        if rc != 0:
            raise WorkerLostError(
                "ring allreduce failed (peer closed or "
                "transport lost)")
        return arr

    def allreduce_sum(self, arr: np.ndarray) -> np.ndarray:
        """In-place ring allreduce (sum) on a contiguous host array."""
        return self.allreduce(arr, "sum")

    def data_bytes_sent(self) -> int:
        """Cumulative data-plane bytes this process sent through the
        collective kernels — lets tests assert the kernels' byte
        optimality instead of trusting comments."""
        with self._lock:
            return int(self._lib.hvdnet_data_bytes_sent(self._h))

    def exchange_calls(self) -> int:
        """Cumulative ring/mesh kernel steps — fusion's dispatch-count
        win is this counter's delta (deterministic, box-independent)."""
        with self._lock:
            return int(self._lib.hvdnet_exchange_calls(self._h))

    def ctrl_bytes_sent(self) -> int:
        """Cumulative control-plane (star) bytes sent — negotiation
        gathers/bcasts + cache-bit syncs; the response cache's byte
        amortization is this counter's per-op delta."""
        with self._lock:
            return int(self._lib.hvdnet_ctrl_bytes_sent(self._h))

    def reducescatter(self, arr: np.ndarray, op: str = "sum") -> np.ndarray:
        """Half-ring reduce-scatter: returns this rank's fully-reduced
        chunk of the flattened array ((w-1)/w of the payload per link —
        optimal; VERDICT r2 ask 6 replacing the allreduce+slice
        fallback). ``arr`` is consumed as scratch. The flat chunk split
        matches the ring allreduce's near-equal boundaries; callers
        wanting a leading-axis split pass count divisible by world."""
        if arr.dtype not in _REDUCESCATTER_FN:
            raise TypeError(f"unsupported dtype {arr.dtype} for host "
                            "reducescatter (use float32/float64/int32/"
                            "int64)")
        if op not in _RING_OPS:
            raise ValueError(f"unsupported reducescatter op {op!r}")
        arr = np.ascontiguousarray(arr).ravel()
        w, r = self.world, self.rank
        begin = arr.size * r // w
        end = arr.size * (r + 1) // w
        out = np.empty(end - begin, dtype=arr.dtype)
        fn = getattr(self._lib, _REDUCESCATTER_FN[arr.dtype])
        with self._lock:
            rc = fn(self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.size,
                    _RING_OPS[op], out.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise WorkerLostError(
                "reducescatter failed (peer closed or "
                "transport lost)")
        return out

    def alltoall(self, arr: np.ndarray) -> np.ndarray:
        """Pairwise all-to-all: ``arr``'s leading axis is split into
        ``world`` equal chunks (chunk j to rank j); returns the received
        chunks concatenated in source-rank order. Every byte crosses
        exactly one mesh link ((w-1)/w of the payload — optimal; VERDICT
        r2 ask 6 replacing the star-allgatherv fallback)."""
        arr = np.ascontiguousarray(arr)
        if arr.shape[0] % self.world != 0:
            raise ValueError(
                f"alltoall dim0 {arr.shape[0]} not divisible by world "
                f"{self.world}")
        out = np.empty_like(arr)
        chunk_bytes = arr.nbytes // self.world
        with self._lock:
            rc = self._lib.hvdnet_alltoall(
                self._h, arr.ctypes.data_as(ctypes.c_void_p),
                out.ctypes.data_as(ctypes.c_void_p), chunk_bytes)
        if rc != 0:
            raise WorkerLostError(
                "alltoall failed (peer closed or "
                "transport lost)")
        return out

    def sendrecv(self, send_peer: int, send_buf: Optional[np.ndarray],
                 recv_peer: int, recv_buf: Optional[np.ndarray]) -> None:
        """Full-duplex point-to-point exchange over the data mesh: send
        ``send_buf``'s bytes to ``send_peer`` while filling ``recv_buf``
        from ``recv_peer``. Either side may be ``None``/empty (pure send
        or pure recv). Both ends of a transfer must agree on the byte
        count — framing is the caller's contract, as in the ring kernels.
        The hierarchical host collectives (runtime/hierarchy.py) compose
        subgroup rings from this verb."""
        sn = 0 if send_buf is None else send_buf.nbytes
        rn = 0 if recv_buf is None else recv_buf.nbytes
        if sn:
            send_buf = np.ascontiguousarray(send_buf)
        sp = (send_buf.ctypes.data_as(ctypes.c_void_p) if sn else None)
        if rn and not recv_buf.flags["C_CONTIGUOUS"]:
            raise ValueError("sendrecv recv_buf must be contiguous "
                             "(received bytes land in place)")
        rp = (recv_buf.ctypes.data_as(ctypes.c_void_p) if rn else None)
        with self._lock:
            rc = self._lib.hvdnet_sendrecv(
                self._h, send_peer, sp, sn, recv_peer, rp, rn)
        if rc != 0:
            raise WorkerLostError(
                "sendrecv failed (peer closed or "
                "transport lost)")

    def _allgatherv_raw(self, blob: bytes, cap: int) -> List[bytes]:
        lens = (ctypes.c_uint64 * self.world)()
        out = ctypes.create_string_buffer(max(cap, 1))
        with self._lock:
            total = self._lib.hvdnet_allgatherv(
                self._h, blob, len(blob), out, cap, lens)
        if total < 0:
            raise WorkerLostError(
                "allgatherv failed (peer closed or "
                "transport lost)")
        blobs, off = [], 0
        raw = out.raw
        for r in range(self.world):
            n = int(lens[r])
            blobs.append(raw[off:off + n])
            off += n
        return blobs

    def allgatherv(self, blob: bytes) -> List[bytes]:
        """Every rank contributes a blob; every rank receives all blobs in
        rank order. Two-phase (sizes first) — no payload cap."""
        size_blobs = self._allgatherv_raw(
            np.uint64(len(blob)).tobytes(), 16 * self.world)
        total = int(sum(np.frombuffer(b, dtype=np.uint64)[0]
                        for b in size_blobs))
        return self._allgatherv_raw(blob, total)
