"""LRU response cache with stable cache bits — the negotiation fast path.

TPU-native analogue of the reference's ``ResponseCache``/``CacheCoordinator``
(reference: horovod/common/response_cache.cc/.h): once a named tensor has
been negotiated, its ``Response`` is cached under a stable *cache bit*; on
later cycles each worker only contributes a bitvector of hit bits, the
controller ANDs the bitvectors across workers (2 small collectives instead
of a full gather/bcast of requests), and if every queued tensor is a
universal hit the fused responses come straight from the cache
(reference: controller.cc:151-179 fast path).

In steady-state training — same named gradients every step — every cycle
takes the fast path, exactly like jit tracing caches a step program.
"""

from __future__ import annotations

import ctypes
import enum
import heapq
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.runtime import message as msg

_CACHE_HITS = _metrics().counter(
    "horovod_response_cache_hits_total",
    "Negotiation cache lookups that found a matching cached response.")
_CACHE_MISSES = _metrics().counter(
    "horovod_response_cache_misses_total",
    "Negotiation cache lookups that found no entry for the tensor name.")
_CACHE_INVALIDATIONS = _metrics().counter(
    "horovod_response_cache_invalidations_total",
    "Cached responses dropped (params changed or stale deferred hits).")


def _record_lookup(state: "CacheState") -> "CacheState":
    """Shared hit/miss accounting for the Python and native caches.
    INVALID lookups count as misses (they re-enter full negotiation);
    the explicit invalidation is counted separately in invalidate()."""
    if state == CacheState.HIT:
        _CACHE_HITS.inc()
    else:
        _CACHE_MISSES.inc()
    return state


class CacheState(enum.Enum):
    # reference: response_cache.h:44-56
    MISS = 0
    HIT = 1
    INVALID = 2


class ResponseCache:
    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        # The cache is deliberately lock-free: every mutation happens on
        # the background cycle thread (hvd-analyze checks the confinement
        # annotations below — external writes are flagged).
        # bit -> (response, params_key); OrderedDict gives LRU order
        self._entries: "OrderedDict[int, Tuple[msg.Response, tuple]]" = OrderedDict()  # guarded-by: <cycle-thread>
        self._name_to_bit: Dict[str, int] = {}  # guarded-by: <cycle-thread>
        self._next_bit = 0  # guarded-by: <cycle-thread>
        # bits freed by eviction/invalidation, reused lowest-first so the
        # bitvector stays bounded by capacity (the reference keeps bits
        # < capacity and redistributes, response_cache.cc:232+)
        self._free_bits: list[int] = []  # guarded-by: <cycle-thread>

    def _alloc_bit(self) -> int:
        if self._free_bits:
            return heapq.heappop(self._free_bits)
        bit = self._next_bit
        self._next_bit += 1
        return bit

    def _release_bit(self, bit: int) -> None:
        heapq.heappush(self._free_bits, bit)

    @staticmethod
    def _params_key(request: msg.Request) -> tuple:
        return (request.request_type, request.dtype, request.shape,
                request.root_rank, request.reduce_op)

    def cached(self, request: msg.Request) -> CacheState:
        """reference: response_cache.cc:50-76 — a name hit with changed
        shape/dtype/params is INVALID, not HIT.

        Deliberately does NOT touch LRU order: announcement timing differs
        across workers, so a touch here would diverge the eviction order and
        eventually remap the same cache bit to different tensors on
        different workers. Order mutations happen only on the synchronized
        paths — ``get_by_bit`` with agreed common bits, ``put`` /
        ``invalidate`` with agreed responses — which every worker executes
        in the identical sequence (the invariant the reference maintains as
        well: response_cache.cc cached() is const)."""
        bit = self._name_to_bit.get(request.tensor_name)
        if bit is None or bit not in self._entries:
            return _record_lookup(CacheState.MISS)
        _, key = self._entries[bit]
        if key == self._params_key(request):
            return _record_lookup(CacheState.HIT)
        return _record_lookup(CacheState.INVALID)

    def put(self, response: msg.Response, request: msg.Request) -> int:
        """Insert (or refresh) a single-tensor response; evicts LRU at
        capacity (reference: response_cache.cc:144-230). No-op at
        capacity 0 (cache disabled via HOROVOD_CACHE_CAPACITY=0).

        Single-tensor responses only (fusion happens after cache replay,
        never before) — enforced so the native engine, whose eviction
        unmaps exactly one name per entry, stays in lockstep."""
        if len(response.tensor_names) != 1:
            raise ValueError(
                "response cache stores single-tensor responses only")
        if self.capacity <= 0:
            return -1
        name = request.tensor_name
        bit = self._name_to_bit.get(name)
        if bit is not None and bit in self._entries:
            self._entries.move_to_end(bit)
            self._entries[bit] = (response, self._params_key(request))
            return bit
        if len(self._entries) >= self.capacity:
            old_bit, (old_resp, _) = self._entries.popitem(last=False)
            for n in old_resp.tensor_names:
                self._name_to_bit.pop(n, None)
            self._release_bit(old_bit)
        bit = self._alloc_bit()
        self._entries[bit] = (response, self._params_key(request))
        self._name_to_bit[name] = bit
        return bit

    def get_by_bit(self, bit: int) -> Optional[msg.Response]:
        entry = self._entries.get(bit)
        if entry is None:
            return None
        self._entries.move_to_end(bit)  # touch for LRU
        return entry[0]

    def bit_for_name(self, name: str) -> Optional[int]:
        return self._name_to_bit.get(name)

    def invalidate(self, name: str) -> None:
        """Drop a cached entry (stalled or params-changed tensors re-enter
        full negotiation; reference: stall_inspector.cc:112+)."""
        bit = self._name_to_bit.pop(name, None)
        if bit is not None and self._entries.pop(bit, None) is not None:
            self._release_bit(bit)
            _CACHE_INVALIDATIONS.inc()

    def __len__(self) -> int:
        return len(self._entries)


def _pack_params_key(request: msg.Request) -> bytes:
    """Deterministic byte form of the cache key for the native cache's
    opaque comparison — derived from ``ResponseCache._params_key`` so the
    two implementations can never disagree on what makes a key."""
    return repr(ResponseCache._params_key(request)).encode()


class NativeResponseCache:
    """Same interface and exact semantics as :class:`ResponseCache`,
    executed by the C++ engine (cpp/cycle.cc) — the reference keeps this
    per-cycle path native (reference: response_cache.cc). Responses cross
    the ABI as packed wire bytes (runtime/message.py), so the C++ side
    stays schema-free. Differential parity with the Python implementation
    is asserted by tests/test_native_cycle.py."""

    def __init__(self, capacity: int = 1024):
        from horovod_tpu.runtime import native

        self.capacity = capacity
        self._lib = native.load_library()
        self._h = self._lib.hvc_cache_new(capacity)
        if not self._h:
            raise native.NativeUnavailableError("hvc_cache_new failed")

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.hvc_cache_free(h)

    def cached(self, request: msg.Request) -> CacheState:
        key = _pack_params_key(request)
        state = self._lib.hvc_cache_cached(
            self._h, request.tensor_name.encode(), key, len(key))
        return _record_lookup(CacheState(state))

    def put(self, response: msg.Response, request: msg.Request) -> int:
        if len(response.tensor_names) != 1:
            raise ValueError(
                "response cache stores single-tensor responses only")
        if self.capacity <= 0:
            return -1
        key = _pack_params_key(request)
        blob = response.pack()
        return self._lib.hvc_cache_put(
            self._h, request.tensor_name.encode(), key, len(key),
            blob, len(blob))

    def get_by_bit(self, bit: int) -> Optional[msg.Response]:
        n = self._lib.hvc_cache_get_len(self._h, bit)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(n)
        if self._lib.hvc_cache_get(self._h, bit, buf, n) < 0:
            return None
        return msg.Response.unpack(buf.raw)[0]

    def bit_for_name(self, name: str) -> Optional[int]:
        bit = self._lib.hvc_cache_bit_for_name(self._h, name.encode())
        return None if bit < 0 else bit

    def invalidate(self, name: str) -> None:
        # count only real drops so the Python/native counters agree
        if self.bit_for_name(name) is not None:
            _CACHE_INVALIDATIONS.inc()
        self._lib.hvc_cache_invalidate(self._h, name.encode())

    def __len__(self) -> int:
        return int(self._lib.hvc_cache_size(self._h))


def native_cycle_enabled() -> bool:
    """Native per-cycle engine knob: ``HOROVOD_NATIVE_CYCLE=0`` forces the
    Python implementations (mirrors how the reference selects op backends
    via env, utils/env_parser.cc)."""
    return os.environ.get("HOROVOD_NATIVE_CYCLE", "1").lower() not in (
        "0", "false", "off")


def make_response_cache(capacity: int = 1024):
    """Native cache when the library is available (built on demand), the
    Python implementation otherwise. Only genuine unavailability falls
    back — a bug in the native path must surface, not be masked."""
    if native_cycle_enabled():
        from horovod_tpu.runtime import native

        try:
            return NativeResponseCache(capacity)
        except native.NativeUnavailableError:
            pass
    return ResponseCache(capacity)


class CacheCoordinator:
    """Packs per-cycle cache hits + status flags into an int bitvector
    synchronized across workers with bitwise AND (reference:
    response_cache.h:104-167, response_cache.cc:308-430).

    Status bits occupy the lowest positions (reference:
    response_cache.h:128-132): SHOULD_SHUT_DOWN, UNCACHED_IN_QUEUE,
    INVALID_IN_QUEUE. Unlike the reference's fixed ``long long`` chunks we
    use Python/any-width ints (the C++ backend uses uint64 words).
    """

    SHOULD_SHUT_DOWN = 0
    UNCACHED_IN_QUEUE = 1
    INVALID_IN_QUEUE = 2
    _NUM_STATUS_BITS = 3

    def __init__(self):
        self._bits = 0

    def record_hit(self, bit: int) -> None:
        self._bits |= 1 << (bit + self._NUM_STATUS_BITS)

    def set_uncached_in_queue(self) -> None:
        self._bits |= 1 << self.UNCACHED_IN_QUEUE

    def set_invalid_in_queue(self) -> None:
        self._bits |= 1 << self.INVALID_IN_QUEUE

    def set_should_shut_down(self) -> None:
        self._bits |= 1 << self.SHOULD_SHUT_DOWN

    @property
    def bitvector(self) -> int:
        return self._bits

    @staticmethod
    def common_hits(anded_bits: int) -> List[int]:
        """Cache bits hit on every worker, from the AND-reduced vector."""
        bits = anded_bits >> CacheCoordinator._NUM_STATUS_BITS
        out = []
        i = 0
        while bits:
            if bits & 1:
                out.append(i)
            bits >>= 1
            i += 1
        return out

    @staticmethod
    def flags(ored_bits: int) -> Tuple[bool, bool, bool]:
        """(should_shut_down, uncached_in_queue, invalid_in_queue) from the
        OR-reduced vector — any worker setting a flag sets it globally."""
        return (
            bool(ored_bits & (1 << CacheCoordinator.SHOULD_SHUT_DOWN)),
            bool(ored_bits & (1 << CacheCoordinator.UNCACHED_IN_QUEUE)),
            bool(ored_bits & (1 << CacheCoordinator.INVALID_IN_QUEUE)),
        )
