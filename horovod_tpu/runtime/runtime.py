"""The background enqueue runtime: cycle loop + handles.

TPU-native analogue of the reference's core runtime (reference:
horovod/common/operations.cc — ``BackgroundThreadLoop``/``RunLoopOnce``
:303-550, enqueue APIs :736-843, and the architecture note :281-300): all
caller threads *enqueue* named tensors; ONE background thread per process
runs a cycle every ``HOROVOD_CYCLE_TIME`` ms that (a) negotiates via the
controller which tensors are ready on all workers, (b) fuses them under the
threshold, (c) executes the fused XLA collectives, and (d) fires completion
callbacks. This decouples caller enqueue order from collective execution
order — the property that lets different workers produce gradients in
different orders.

On TPU the data plane is XLA programs over the global mesh, so step (c) is
"dispatch a cached compiled collective"; negotiation + caching amortize to
the bitvector fast path in steady state, mirroring how jit amortizes
tracing.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

import jax

from horovod_tpu import flight_recorder
from horovod_tpu.analysis import witness
from horovod_tpu.core import state as state_mod
from horovod_tpu.metrics import COUNT_BUCKETS, registry as _metrics
from horovod_tpu.runtime import message as msg
from horovod_tpu.runtime import types
from horovod_tpu.runtime.controller import Controller, LocalController
from horovod_tpu.runtime.executor import Executor
from horovod_tpu.runtime.tensor_queue import TensorQueue
from horovod_tpu.utils import logging as log

_CYCLES = _metrics().counter(
    "horovod_cycles_total", "Background negotiation+execution cycles run.")
_CYCLE_DURATION = _metrics().histogram(
    "horovod_cycle_duration_seconds",
    "Wall time of one cycle body (negotiation + execution).")
_CYCLE_TENSORS = _metrics().histogram(
    "horovod_cycle_tensors",
    "Tensors agreed for execution per cycle.", buckets=COUNT_BUCKETS)
_HANDLE_WAIT = _metrics().histogram(
    "horovod_handle_wait_seconds",
    "Caller time blocked in RuntimeHandle.wait().")
_PIPELINE_DEPTH = _metrics().gauge(
    "horovod_cycle_pipeline_depth",
    "Responses currently in flight on the pipelined data plane (bounded "
    "by HOROVOD_CYCLE_PIPELINE_DEPTH).")
_AUTOTUNE_PARAM = _metrics().gauge(
    "horovod_autotune_param",
    "Runtime parameter value most recently committed by the per-cycle "
    "autotune sync, by knob (string-valued knobs are encoded: the "
    "hierarchy codec reports its COMPRESSION_CODECS index).",
    labelnames=("knob",))
_AUTOTUNE_COMMITS = _metrics().counter(
    "horovod_autotune_commits_total",
    "Parameter-blob changes applied at a cycle boundary (the broadcast "
    "blob differed from the previously applied one).")


class RuntimeHandle:
    """Completion future for an enqueued named tensor (reference:
    horovod/torch/handle_manager.cc + mpi_ops.py poll/synchronize)."""

    def __init__(self, name: str, runtime: "Optional[Runtime]" = None):
        self.name = name
        self._event = threading.Event()
        self._status: Optional[types.Status] = None
        self._output: Any = None
        self._runtime = runtime

    def _complete(self, status: types.Status, output) -> None:
        self._status = status
        self._output = output
        self._event.set()

    def poll(self) -> bool:
        # a poll loop is as much "waiting on the lane" as a parked wait()
        # — stamp the runtime so the lane-hazard watchdog doesn't read a
        # busy-polling caller as a silent one (advisor r3)
        rt = self._runtime
        if rt is not None:
            rt._last_poll_time = time.monotonic()
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None):
        # a caller parked HERE is waiting on the lane, not racing it —
        # the lane-hazard watchdog suppresses its diagnostic while any
        # waiter is registered (a straggler peer is the stall
        # inspector's case, not the watchdog's)
        rt = self._runtime
        if rt is not None:
            with rt._inflight_lock:
                rt._waiters += 1
        t0 = time.monotonic()
        try:
            if not self._event.wait(timeout):
                raise TimeoutError(
                    f"collective '{self.name}' did not complete within "
                    f"{timeout}s")
        finally:
            _HANDLE_WAIT.observe(time.monotonic() - t0)
            if rt is not None:
                with rt._inflight_lock:
                    rt._waiters -= 1
        if not self._status.ok():
            # integrity verdicts outrank transport failures and must NOT
            # mark the runtime as down: lift-and-clear so the runtime
            # survives the in-place rollback-and-replay (integrity/) and
            # a later unrelated failure can't re-raise a stale verdict
            integ = (getattr(rt.executor, "integrity_failure", None)
                     if rt is not None else None)
            if integ is not None:
                rt.executor.integrity_failure = None
                raise type(integ)(
                    f"collective '{self.name}' failed integrity check: "
                    f"{integ}", bucket=integ.bucket, tensor=integ.tensor,
                    suspect_rank=integ.suspect_rank) from integ
            # typed propagation for the elastic layer: when the runtime
            # recorded a workers-down failure, surface it as the same
            # exception type (WorkersDownError subclasses RuntimeError, so
            # non-elastic callers are unaffected)
            failure = getattr(rt, "failure", None) if rt is not None else None
            if failure is None and rt is not None:
                # the executor records data-plane losses on itself before
                # completing entries; the runtime lifts it only at cycle
                # end — after this waiter already woke
                failure = getattr(rt.executor, "failure", None)
            if failure is not None:
                from horovod_tpu import exceptions

                if isinstance(failure, exceptions.WorkersDownError):
                    raise type(failure)(
                        f"collective '{self.name}' failed: "
                        f"{self._status.reason}",
                        ranks=failure.ranks) from failure
            raise RuntimeError(
                f"collective '{self.name}' failed: {self._status.reason}")
        return self._output


def _fail_incomplete_entries(entries) -> None:
    status = types.Status.Aborted("background cycle failed; see runtime log")
    for e in entries:
        e.complete(status, None)  # exactly-once guard lives on the entry


class Runtime:
    """Owns the cycle thread, queue, controller and executor."""

    def __init__(self, controller: Optional[Controller] = None):
        st = state_mod.global_state()
        self._st = st
        self.queue = TensorQueue()
        if controller is None:
            controller = self._controller_from_env(st)
        self.controller = controller
        net = getattr(controller, "net", None)
        self.executor = Executor(st.mesh, net=net)
        self.timeline = st.timeline
        from horovod_tpu.stall import StallInspector

        self.stall_inspector = StallInspector(
            warning_time_seconds=st.config.stall_check_time_seconds,
            shutdown_time_seconds=st.config.stall_shutdown_time_seconds,
            enabled=not st.config.stall_check_disable,
            elastic=st.config.elastic)
        # the typed reason this runtime went down (WorkersDownError
        # subclass), when an involuntary failure path could tell; a
        # deliberate stop() leaves it None
        self.failure: Optional[Exception] = None
        # stale deferred hits renegotiate on the same clock as stall warnings
        self.controller.STALE_HIT_SECONDS = st.config.stall_check_time_seconds
        self._cycle_time_s = st.config.cycle_time_ms / 1000.0
        # Straggler attribution rides the coordinator's negotiation table
        # (per-rank arrival stamps); the tracker feeds the lag EWMA gauge,
        # skew histogram, periodic report and enriched stall warnings.
        if self.controller.is_coordinator:
            from horovod_tpu.stall import StragglerTracker

            self.controller.straggler = StragglerTracker(
                world=getattr(self.controller, "world", 1),
                report_seconds=st.config.straggler_report_seconds)
        # postmortem visibility into the live cycle: the flight recorder
        # embeds this runtime's in-flight state in every dump
        self._cycle_pending: "Optional[collections.deque]" = None
        flight_recorder.set_state_provider("runtime", self._debug_state)

        # Autotuning (reference: parameter_manager wired into RunLoopOnce +
        # SynchronizeParameters each cycle, operations.cc:500-550 /
        # controller.cc:32-46). Coordinator tunes; everyone applies.
        self.param_manager = None
        self._autotune_active = bool(st.config.autotune)
        if self._autotune_active and st.config.autotune_probe:
            # Seed the fusion threshold from measured HBM/ICI bandwidth
            # (north star: autotuner backed by hardware probes). EVERY
            # process probes — the probe programs run over the global
            # mesh, which all processes of a multi-controller world must
            # enter together; the coordinator's seeded value then governs
            # via the per-cycle parameter broadcast. Skipped when the
            # data plane is the host TCP ring (socket mode without a
            # global mesh), whose bandwidth the XLA-mesh probe does not
            # measure — seeding from it would overshoot by orders of
            # magnitude.
            host_ring_data_plane = (net is not None
                                    and not self.executor._spmd_world)
            if host_ring_data_plane:
                # the XLA-mesh probe cannot measure the socket plane,
                # but the hierarchy hops CAN be probed over the live
                # sockets themselves (collective: every rank takes this
                # branch — the predicate is env-derived and identical
                # fleet-wide)
                from horovod_tpu.autotune.probe import (
                    probe_host_hier_and_seed)

                hier_probe = probe_host_hier_and_seed(net, st.config)
                if self.controller.is_coordinator:
                    if hier_probe is not None:
                        log.info(
                            "autotune probe (socket hierarchy): intra "
                            "%.2f GB/s, cross %.2f GB/s busbw%s",
                            hier_probe["hier_intra_busbw_gbps"],
                            hier_probe["hier_cross_busbw_gbps"],
                            " (cached)" if hier_probe["cached"] else "")
                    else:
                        log.warning(
                            "HOROVOD_AUTOTUNE_PROBE ignored: the host "
                            "TCP data plane is active, the XLA-mesh "
                            "probe does not measure it, and the world "
                            "cannot form a hierarchy to probe; tuning "
                            "starts from the default threshold")
            else:
                from horovod_tpu.autotune.probe import probe_and_seed

                measured = probe_and_seed(st.config, st.mesh)
                if self.controller.is_coordinator:
                    log.info(
                        "autotune probe: HBM %.1f GB/s, allreduce %.1f "
                        "GB/s -> initial fusion threshold %d MB",
                        measured["hbm_gbps"], measured["allreduce_gbps"],
                        measured["fusion_threshold_bytes"] >> 20)
                # Each process's probe is independently noisy, but
                # fuse_responses runs per-worker inside the cycle — every
                # rank must bin-pack cycle 1 with the SAME threshold or
                # workers dispatch mismatched fused programs. Agree on the
                # coordinator's measurement before the cycle thread starts
                # (the per-cycle _autotune_sync takes over from cycle 1's
                # end).
                if getattr(self.controller, "world", 1) > 1:
                    import struct

                    blob = (struct.pack(
                        "<q", st.config.fusion_threshold_bytes)
                        if self.controller.is_coordinator else None)
                    agreed = struct.unpack(
                        "<q", bytes(self.controller.bcast_blob(blob)))[0]
                    st.config.fusion_threshold_bytes = agreed
        if self._autotune_active and self.controller.is_coordinator:
            from horovod_tpu.autotune.parameter_manager import (
                ParameterManager, Params, normalize_codec,
                search_box_from_roofline)
            from horovod_tpu.parallel import buckets as buckets_mod
            from horovod_tpu.parallel import zero as zero_mod

            initial = Params(
                fusion_threshold_bytes=st.config.fusion_threshold_bytes,
                cycle_time_ms=st.config.cycle_time_ms,
                cache_enabled=self.controller.cache_enabled,
                hierarchical_allreduce=st.config.hierarchical_allreduce,
                hierarchical_allgather=st.config.hierarchical_allgather,
                hierarchy_group_size=st.config.hierarchy_group_size,
                hierarchy_compression=normalize_codec(
                    st.config.hierarchy_compression),
                grad_bucket_bytes=buckets_mod.bucket_bytes_from_env(),
                cycle_pipeline_depth=st.config.cycle_pipeline_depth,
                zero_prefetch_buckets=zero_mod.prefetch_buckets_from_env())
            # hierarchical knobs join the sweep only where the data plane
            # consults them; the cache knob only when a cache exists to
            # toggle. hierarchical_available() is a static predicate on
            # BOTH planes now — the old gate additionally required
            # ``controller.net is None`` (single-controller mesh), so
            # host-ring jobs, the plane that actually grew a hierarchical
            # lane, never swept these knobs.
            sweep = (["cache_enabled"] if st.config.cache_capacity > 0
                     else [])
            host_ring = getattr(self.controller, "net", None) is not None
            if self.executor.hierarchical_available():
                sweep += ["hierarchical_allreduce"]
                if host_ring:
                    # the slow-hop codec only exists on the socket
                    # hierarchy's cross-group exchange
                    sweep += ["hierarchy_compression"]
                else:
                    # the allgather decomposition is mesh-plane-only
                    sweep += ["hierarchical_allgather"]
            # seed the continuous search box from the persisted probe
            # rooflines (PR 16 artifact; schema 2 adds the per-hop
            # hierarchy numbers) so BO starts inside the feasible region
            try:
                from horovod_tpu.autotune import probe

                roofline = probe.load_cached_roofline(
                    world=getattr(self.controller, "world", 1))
            except Exception:
                roofline = None
            self.param_manager = ParameterManager(
                initial,
                warmup_samples=st.config.autotune_warmup_samples,
                steps_per_sample=st.config.autotune_steps_per_sample,
                bayes_opt_max_samples=st.config.autotune_bayes_opt_max_samples,
                gp_noise=st.config.autotune_gaussian_process_noise,
                log_path=st.config.autotune_log, rank=st.rank,
                sweep=tuple(sweep),
                bounds=search_box_from_roofline(roofline))
        self._applied_params_blob: Optional[bytes] = None
        # enqueued-but-not-completed count, for the ordered-lane misuse
        # guard (ops/collectives._lane_check): covers both queued entries
        # and entries popped for execution
        self._inflight = 0  # guarded-by: _inflight_lock
        self._inflight_lock = witness.make_lock("Runtime._inflight_lock")
        # lane-hazard watchdog bookkeeping (VERDICT r2 ask 8): names and
        # enqueue times of in-flight entries + when the enqueue side last
        # spoke, so the cycle loop can flag "named ops stuck while the
        # caller thread is busy elsewhere" — the user-owned-global-program
        # interleaving hazard _lane_check cannot intercept
        self._inflight_names: dict = {}  # guarded-by: _inflight_lock
        self._last_enqueue_time = time.monotonic()  # guarded-by: _inflight_lock
        self._lane_last_warn = 0.0
        self._waiters = 0  # callers parked in RuntimeHandle.wait(); guarded-by: _inflight_lock
        self._last_poll_time = 0.0  # callers spinning on RuntimeHandle.poll()
        self._stop = threading.Event()
        self._deliberate_stop = False  # set by stop(): not a failure
        self._woken = threading.Event()
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True, name="hvd-background-loop")
        self._thread.start()

    @staticmethod
    def _controller_from_env(st) -> Controller:
        """Select the controller like the reference selects its LibType
        (reference: utils/env_parser.cc:50-90 ParseControllerOpsFromEnv):
        ``HOROVOD_CONTROLLER=socket`` (or a multi-process launcher env with
        HOROVOD_SIZE>1) picks the TCP coordinator; default is local."""
        import os

        kind = os.environ.get("HOROVOD_CONTROLLER", "").lower()
        env_world = int(os.environ.get("HOROVOD_SIZE", "1"))
        if kind == "socket" or (kind == "" and env_world > 1
                                and "HOROVOD_RANK" in os.environ):
            from horovod_tpu.runtime.socket_controller import SocketController

            return SocketController.from_env(
                cache_capacity=st.config.cache_capacity)
        return LocalController(rank=0, world=1,
                               cache_capacity=st.config.cache_capacity)

    # -- enqueue APIs (reference: operations.cc:736-843) -------------------
    def _enqueue(self, request_type: str, name: str, tensor,
                 root_rank: int = 0,
                 reduce_op: str = types.REDUCE_AVERAGE,
                 priority: int = 0) -> RuntimeHandle:
        if self._stop.is_set():
            from horovod_tpu import exceptions

            if isinstance(self.failure, exceptions.WorkersDownError):
                raise type(self.failure)(
                    f"{types.SHUT_DOWN_ERROR} (cause: {self.failure})",
                    ranks=self.failure.ranks) from self.failure
            raise RuntimeError(types.SHUT_DOWN_ERROR)
        handle = RuntimeHandle(name, runtime=self)

        def _on_complete(status, output, _h=handle, _name=name):
            with self._inflight_lock:
                self._inflight -= 1
                self._inflight_names.pop(_name, None)
            _h._complete(status, output)

        entry = types.TensorTableEntry(
            name=name, tensor=tensor, request_type=request_type,
            root_rank=root_rank, reduce_op=reduce_op,
            callback=_on_complete,
            dtype=str(tensor.dtype), shape=tuple(tensor.shape),
            enqueue_time=time.monotonic(), priority=priority)
        # The announced shape is the PER-WORKER tensor shape — for a
        # worker-stacked array that is shape[1:] (the wire protocol matches
        # what each process would announce in multi-process mode, and
        # fusion byte accounting counts real payload, not payload x world).
        from horovod_tpu.ops import collectives as coll

        wire_shape = (tuple(int(d) for d in tensor.shape[1:])
                      if coll._is_worker_stacked(tensor)
                      else tuple(int(d) for d in tensor.shape))
        request = msg.Request(
            rank=self.controller.rank, request_type=request_type,
            tensor_name=name, dtype=str(tensor.dtype),
            shape=wire_shape, root_rank=root_rank, reduce_op=reduce_op)
        # count BEFORE the entry becomes visible to the cycle thread —
        # otherwise a fast cycle can complete (and decrement) first and
        # the counter transiently goes negative
        now = time.monotonic()
        with self._inflight_lock:
            self._inflight += 1
            # a duplicate-name enqueue must not clobber (or, on its
            # failure below, evict) the ORIGINAL in-flight op's
            # watchdog entry
            prior_seen = self._inflight_names.get(name)
            if prior_seen is None:
                self._inflight_names[name] = now
            self._last_enqueue_time = now
        try:
            self.queue.add(entry, request)  # DuplicateNameError on misuse
        except BaseException:
            with self._inflight_lock:
                self._inflight -= 1
                if prior_seen is None:
                    self._inflight_names.pop(name, None)
            raise
        self._woken.set()  # don't wait out the full cycle for new work
        return handle

    def in_flight(self) -> int:
        """Named async collectives enqueued but not yet completed."""
        with self._inflight_lock:
            return self._inflight

    def enqueue_allreduce(self, name: str, tensor, average: bool = None,
                          reduce_op: str = None,
                          priority: int = 0) -> RuntimeHandle:
        if reduce_op is None:
            reduce_op = (types.REDUCE_AVERAGE
                         if average is None or average else types.REDUCE_SUM)
        elif average is not None:
            raise ValueError("specify either average or reduce_op, not both")
        elif reduce_op not in types.REDUCE_OPS:
            raise ValueError(f"unknown reduce_op {reduce_op!r}")
        return self._enqueue(types.ALLREDUCE, name, tensor,
                             reduce_op=reduce_op, priority=priority)

    def enqueue_allreduce_group(self, names, tensors,
                                reduce_op: str = types.REDUCE_AVERAGE,
                                priority: int = 0,
                                group_callback=None):
        """Enqueue a released gradient bucket as one atomic group.

        All entries land in the tensor queue under a single lock scope
        (``TensorQueue.add_group``) with one wake of the cycle thread, so
        the whole bucket negotiates in the same cycle and the fusion
        planner packs it into as few dispatches as the fusion threshold
        allows — the per-bucket analogue of the reference's grouped
        enqueue. ``group_callback(ok)``, if given, fires on the cycle
        thread once per entry as it completes or fails (bucket-release
        wire accounting). Returns one handle per tensor, in order."""
        if self._stop.is_set():
            from horovod_tpu import exceptions

            if isinstance(self.failure, exceptions.WorkersDownError):
                raise type(self.failure)(
                    f"{types.SHUT_DOWN_ERROR} (cause: {self.failure})",
                    ranks=self.failure.ranks) from self.failure
            raise RuntimeError(types.SHUT_DOWN_ERROR)
        names = list(names)
        tensors = list(tensors)
        if len(names) != len(tensors):
            raise ValueError("names and tensors must pair up")
        if reduce_op not in types.REDUCE_OPS:
            raise ValueError(f"unknown reduce_op {reduce_op!r}")
        from horovod_tpu.ops import collectives as coll

        handles = []
        entries = []
        requests = []
        for name, tensor in zip(names, tensors):
            handle = RuntimeHandle(name, runtime=self)
            handles.append(handle)

            def _on_complete(status, output, _h=handle, _name=name):
                with self._inflight_lock:
                    self._inflight -= 1
                    self._inflight_names.pop(_name, None)
                if group_callback is not None:
                    try:
                        group_callback(status.ok())
                    except Exception:
                        pass  # accounting must never poison completion
                _h._complete(status, output)

            entries.append(types.TensorTableEntry(
                name=name, tensor=tensor, request_type=types.ALLREDUCE,
                root_rank=0, reduce_op=reduce_op, callback=_on_complete,
                dtype=str(tensor.dtype), shape=tuple(tensor.shape),
                enqueue_time=time.monotonic(), priority=priority))
            wire_shape = (tuple(int(d) for d in tensor.shape[1:])
                          if coll._is_worker_stacked(tensor)
                          else tuple(int(d) for d in tensor.shape))
            requests.append(msg.Request(
                rank=self.controller.rank, request_type=types.ALLREDUCE,
                tensor_name=name, dtype=str(tensor.dtype),
                shape=wire_shape, root_rank=0, reduce_op=reduce_op))
        # count BEFORE visibility, rolled back as a block on a duplicate —
        # same transient-negative protection as _enqueue
        now = time.monotonic()
        fresh = []
        with self._inflight_lock:
            self._inflight += len(entries)
            for name in names:
                if name not in self._inflight_names:
                    self._inflight_names[name] = now
                    fresh.append(name)
            self._last_enqueue_time = now
        try:
            self.queue.add_group(entries, requests)
        except BaseException:
            with self._inflight_lock:
                self._inflight -= len(entries)
                for name in fresh:
                    self._inflight_names.pop(name, None)
            raise
        self._woken.set()
        return handles

    def enqueue_allgather(self, name: str, tensor,
                          priority: int = 0) -> RuntimeHandle:
        return self._enqueue(types.ALLGATHER, name, tensor,
                             priority=priority)

    def enqueue_broadcast(self, name: str, tensor, root_rank: int,
                          priority: int = 0) -> RuntimeHandle:
        return self._enqueue(types.BROADCAST, name, tensor,
                             root_rank=root_rank, priority=priority)

    def enqueue_reducescatter(self, name: str, tensor,
                              reduce_op: str = types.REDUCE_SUM,
                              priority: int = 0) -> RuntimeHandle:
        if reduce_op not in types.REDUCE_OPS:
            raise ValueError(f"unknown reduce_op {reduce_op!r}")
        return self._enqueue(types.REDUCESCATTER, name, tensor,
                             reduce_op=reduce_op, priority=priority)

    def enqueue_alltoall(self, name: str, tensor,
                         priority: int = 0) -> RuntimeHandle:
        return self._enqueue(types.ALLTOALL, name, tensor,
                             priority=priority)

    # -- cycle loop (reference: RunLoopOnce, operations.cc:500-550) --------
    def _check_lane_hazard(self) -> None:
        """Lane-hazard watchdog (VERDICT r2 ask 8): the ordered-lane
        guard (_lane_check) raises when LIBRARY calls would interleave
        with in-flight named ops, but a user's OWN pjit/jit global
        program dispatched while named ops are pending is invisible to
        it — cross-rank the two program streams can interleave in
        different orders and deadlock with no error. The observable
        process-local signature: named ops in flight beyond the stall
        warn threshold while the enqueue side has gone silent (the
        caller thread is busy/blocked elsewhere). Log the specific
        diagnostic naming the stuck tensors, once per stall period."""
        ins = self.stall_inspector
        if not ins.enabled or ins.warning_time <= 0:
            return
        now = time.monotonic()
        with self._inflight_lock:
            if (not self._inflight_names or self._waiters > 0
                    or now - self._last_poll_time < ins.warning_time):
                # a caller parked in synchronize() — or spinning on the
                # public poll() API — is waiting on the lane, not racing
                # it; a slow peer there is the stall inspector's
                # diagnosis, not a lane hazard
                return
            oldest = min(self._inflight_names.values())
            quiet = now - self._last_enqueue_time
            names = sorted(self._inflight_names)
        if (now - oldest < ins.warning_time or quiet < ins.warning_time
                or now - self._lane_last_warn < ins.warning_time):
            return
        self._lane_last_warn = now
        log.warning(
            "Named collective ops have been in flight for %.0fs with no "
            "new enqueues for %.0fs — if the caller thread is running its "
            "own jit-compiled global program, that program and the pending "
            "named ops may be interleaved in different orders across "
            "ranks (cross-rank deadlock with no error). Call "
            "hvd.assert_collective_lane_clear() before dispatching your "
            "own global programs. In-flight tensors: %s",
            now - oldest, quiet, names)

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            self._woken.wait(self._cycle_time_s)
            self._woken.clear()
            if self._stop.is_set():
                break
            self._check_lane_hazard()
            try:
                keep_going = self.run_cycle()
            except Exception as exc:
                log.get_logger().exception("background cycle failed")
                # In multi-process mode a transport failure means a peer
                # died or shut down — treat as global shutdown (reference:
                # any rank failure aborts the job, gloo_run.py:256-262).
                keep_going = getattr(self.controller, "net", None) is None
                self._record_failure(exc)  # no-op if run_cycle already did
            if not keep_going:
                break
        # Every exit path (peer shutdown bit, transport failure, stop())
        # must gate future enqueues — otherwise a framework thread can
        # queue into the dead loop and hang forever in synchronize().
        self._stop.set()
        self._finalize()

    def run_cycle(self) -> bool:
        """One negotiation+execution cycle; False triggers shutdown."""
        if self.timeline is not None:
            self.timeline.mark_cycle_start()
        # deferred = announced-but-not-yet-agreed tensors from earlier
        # cycles (cache hits awaiting the other workers) — re-announced
        # ahead of the new requests so their bits re-enter the sync.
        requests = self.controller.take_deferred() + self.queue.pop_requests()
        # Multi-process controllers sync EVERY cycle even with nothing
        # queued — the coordination collectives are globally lock-stepped
        # (reference: RunLoopOnce runs ComputeResponseList unconditionally,
        # operations.cc:500-550); skipping only safe single-process.
        if not requests and getattr(self.controller, "net", None) is None \
                and not self.controller._should_shut_down:
            return True
        try:
            return self._run_cycle_body(requests, cycle_t0=time.monotonic())
        except Exception as exc:
            # Record the typed failure BEFORE completing any entry: the
            # waiter wakes on complete() and immediately reads
            # self.failure — recording later (in _run_loop) loses the
            # race and callers see a generic abort instead of
            # WorkersDownError.
            self._record_failure(exc)
            from horovod_tpu.utils import resilience

            flight_recorder.emit(
                "cycle_abort",
                generation=resilience.current_generation(),
                error="%s: %s" % (type(exc).__name__, str(exc)[:200]))
            flight_recorder.dump_on_failure("cycle_abort")
            # The popped requests' entries would otherwise be stranded in
            # the table with their handles never completing (and the names
            # permanently poisoned for re-enqueue) — fail them loudly.
            status = types.Status.Aborted(
                "background cycle failed; see runtime log")
            for e in self.queue.get_entries(
                    [r.tensor_name for r in requests]):
                e.complete(status, None)
            raise

    def _record_failure(self, exc: Exception) -> None:
        """Store the typed reason this runtime is going down (first failure
        wins). Single-process cycles (no net) survive cycle errors, so
        nothing is recorded for them."""
        if getattr(self.controller, "net", None) is None \
                or self.failure is not None:
            return
        from horovod_tpu import exceptions

        self.failure = (
            exc if isinstance(exc, exceptions.WorkersDownError)
            else exceptions.WorkerLostError(f"control-plane failure: {exc}"))

    def _run_cycle_body(self, requests, cycle_t0: float) -> bool:
        responses, shut_down = self.controller.compute_response_list(
            requests, self._st.config.fusion_threshold_bytes,
            timeline=self.timeline, stall_inspector=self.stall_inspector)
        # the coordinator's stall scan records its typed verdict on the
        # controller (controller.py) while the shutdown bit propagates —
        # lift it so handles raise WorkerStallError, not a generic abort
        ctrl_failure = getattr(self.controller, "failure", None)
        if ctrl_failure is not None and self.failure is None:
            self.failure = ctrl_failure
        if shut_down and self.failure is None and not self._deliberate_stop \
                and getattr(self.controller, "net", None) is not None \
                and self._st.config.elastic:
            # elastic: a REMOTE-initiated shutdown bit with no local cause
            # means a peer evicted someone (stall) or is tearing down —
            # survivors must re-form rather than die on a generic abort
            from horovod_tpu import exceptions

            self.failure = exceptions.WorkersDownError(
                "peer requested shutdown (remote stall eviction or "
                "failure)")
        _CYCLES.inc()
        _CYCLE_TENSORS.observe(
            sum(len(r.tensor_names) for r in responses))
        cycle_bytes = 0
        # Pipelined execution: dispatch up to ``depth`` responses before
        # draining the oldest completion, so host packing of bin k+1
        # overlaps the device reduction and transfer of bin k (the
        # reference likewise overlaps the fusion-buffer memcpy with the
        # in-flight collective). Completions drain in dispatch order.
        depth = max(1, self._st.config.cycle_pipeline_depth)
        pending: "collections.deque" = collections.deque()
        self._cycle_pending = pending  # dump-visible while ops in flight

        def drain_one() -> None:
            nonlocal cycle_bytes
            tok, tok_entries = pending.popleft()
            _PIPELINE_DEPTH.set(len(pending))
            flight_recorder.emit("pipeline_depth", depth=len(pending))
            tok.complete()  # never raises: failures become entry statuses
            if self._autotune_active:
                # JAX dispatch is async: block so the score measures the
                # collective itself, not host dispatch latency (the
                # reference scores completed-op wall time)
                jax.block_until_ready(
                    [e.output for e in tok_entries
                     if e.output is not None])
                for e in tok_entries:
                    cycle_bytes += types.entry_nbytes(e)

        cycle_entries = []  # every entry list touched this cycle
        try:
            for response in responses:
                entries = self.queue.get_entries(response.tensor_names)
                if not entries:
                    continue
                cycle_entries.append(entries)
                tok = self.executor.dispatch(response, entries,
                                             timeline=self.timeline)
                pending.append((tok, entries))
                _PIPELINE_DEPTH.set(len(pending))
                flight_recorder.emit("pipeline_depth", depth=len(pending))
                while len(pending) >= depth:
                    drain_one()
            while pending:
                drain_one()
        except Exception:
            # In-flight tokens first: tok.fail() closes the timeline span
            # opened at dispatch, observes op latency, and releases any
            # fusion-buffer lease riding on the token — abandoning them
            # would leave perpetually-open timeline ops and stranded
            # slabs after an elastic restart. fail() is idempotent, so
            # tokens that already completed or failed are left alone.
            status = types.Status.UnknownError(
                "background cycle failed; see runtime log")
            while pending:
                tok, _ = pending.popleft()
                tok.fail(status)
            # these entries left the table already — complete any whose
            # handle hasn't fired so callers error instead of hanging
            # (dispatch/complete handle their own failures; this covers
            # everything around them, for every response in flight)
            for entries in cycle_entries:
                _fail_incomplete_entries(entries)
            raise
        finally:
            _PIPELINE_DEPTH.set(0)
        if self.executor.failure is not None and self.failure is None:
            self.failure = self.executor.failure
        if self._autotune_active:
            self._autotune_sync(cycle_bytes, time.monotonic() - cycle_t0)
        _CYCLE_DURATION.observe(time.monotonic() - cycle_t0)
        self._emit_timeline_counters()
        return not shut_down

    def _debug_state(self) -> dict:
        """In-flight runtime state for flight-recorder dumps: live
        pending-op tokens, watchdog-tracked entry ages, parked waiters,
        and the recorded failure. Read without the cycle lock — a dying
        process must not block on the thread that may be wedged; the
        values are advisory snapshots."""
        now = time.monotonic()
        with self._inflight_lock:
            inflight = {n: round(now - t, 3)
                        for n, t in self._inflight_names.items()}
            waiters = self._waiters
        ops = []
        cycle_pending = self._cycle_pending
        if cycle_pending:
            for tok, _ in list(cycle_pending):
                ops.append({
                    "op": tok.op, "name": tok.name0,
                    "bytes": tok.nbytes, "bucket": tok.bucket,
                    "age_seconds":
                        round(time.perf_counter() - tok.t0, 3)})
        return {
            "in_flight_names": inflight,
            "waiters": waiters,
            "pending_ops": ops,
            "failure": repr(self.failure) if self.failure else None,
            "stopped": self._stop.is_set(),
        }

    def _emit_timeline_counters(self) -> None:
        """Overlay the quantitative plane on the per-tensor trace: one
        Chrome ``"C"`` (counter) event per series per cycle, through the
        same writer and epoch clock domain, so counter curves line up with
        NEGOTIATE/ALLREDUCE bars in the merged view."""
        if self.timeline is None:
            return
        from horovod_tpu.runtime import fusion as fusion_mod
        from horovod_tpu.runtime import response_cache as cache_mod
        from horovod_tpu.runtime import tensor_queue as queue_mod

        self.timeline.counters({
            "queue_depth": queue_mod._QUEUE_DEPTH.value,
            "cache_hits": cache_mod._CACHE_HITS.value,
            "cache_misses": cache_mod._CACHE_MISSES.value,
            "fusion_bytes": fusion_mod._FUSED_BYTES.value,
            "cycles": _CYCLES.value,
        })

    def _autotune_sync(self, nbytes: int, seconds: float) -> None:
        """Coordinator scores the cycle and broadcasts current params;
        every worker applies them at the same cycle boundary (reference:
        SynchronizeParameters, controller.cc:32-46)."""
        from horovod_tpu import comms
        from horovod_tpu.autotune.parameter_manager import (
            COMPRESSION_CODECS, Params, normalize_codec)

        if self.param_manager is not None:
            self.param_manager.update(
                nbytes, seconds, busbw_gbs=comms.data_lane_busbw_gbs())
            blob = self.param_manager.params().pack()
            blob = self.controller.bcast_blob(blob)
        else:
            blob = self.controller.bcast_blob(None)
        blob = bytes(blob)
        params = Params.unpack(blob)
        cfg = self._st.config
        cfg.fusion_threshold_bytes = params.fusion_threshold_bytes
        cfg.cycle_time_ms = params.cycle_time_ms
        cfg.hierarchical_allreduce = params.hierarchical_allreduce
        cfg.hierarchical_allgather = params.hierarchical_allgather
        cfg.hierarchy_group_size = params.hierarchy_group_size
        cfg.hierarchy_compression = params.hierarchy_compression
        if params.cycle_pipeline_depth > 0:
            cfg.cycle_pipeline_depth = params.cycle_pipeline_depth
        if params.grad_bucket_bytes > 0:
            from horovod_tpu.parallel import buckets as buckets_mod

            buckets_mod.set_autotuned_bucket_bytes(params.grad_bucket_bytes)
        if params.zero_prefetch_buckets > 0:
            from horovod_tpu.parallel import zero as zero_mod

            zero_mod.set_autotuned_prefetch_buckets(
                params.zero_prefetch_buckets)
        self._cycle_time_s = params.cycle_time_ms / 1000.0
        self.controller.cache_enabled = params.cache_enabled
        if blob != self._applied_params_blob:
            # commit telemetry: one flight event + a gauge refresh per
            # applied change, on EVERY rank (the postmortem question is
            # "what params was THIS worker running", not just rank 0's)
            self._applied_params_blob = blob
            codec_idx = COMPRESSION_CODECS.index(
                normalize_codec(params.hierarchy_compression))
            for knob, val in (
                    ("fusion_threshold_bytes",
                     params.fusion_threshold_bytes),
                    ("cycle_time_ms", params.cycle_time_ms),
                    ("cache_enabled", int(params.cache_enabled)),
                    ("hierarchical_allreduce",
                     int(params.hierarchical_allreduce)),
                    ("hierarchical_allgather",
                     int(params.hierarchical_allgather)),
                    ("hierarchy_group_size", params.hierarchy_group_size),
                    ("hierarchy_compression_codec", codec_idx),
                    ("grad_bucket_bytes", params.grad_bucket_bytes),
                    ("cycle_pipeline_depth", params.cycle_pipeline_depth),
                    ("zero_prefetch_buckets", params.zero_prefetch_buckets),
                    ("active", int(params.active))):
                _AUTOTUNE_PARAM.labels(knob=knob).set(float(val))
            _AUTOTUNE_COMMITS.inc()
            flight_recorder.emit(
                "autotune_commit",
                fusion_threshold_bytes=params.fusion_threshold_bytes,
                cycle_time_ms=round(params.cycle_time_ms, 3),
                cache_enabled=params.cache_enabled,
                hierarchical_allreduce=params.hierarchical_allreduce,
                hierarchical_allgather=params.hierarchical_allgather,
                hierarchy_group_size=params.hierarchy_group_size,
                hierarchy_compression=params.hierarchy_compression,
                grad_bucket_bytes=params.grad_bucket_bytes,
                cycle_pipeline_depth=params.cycle_pipeline_depth,
                zero_prefetch_buckets=params.zero_prefetch_buckets,
                active=params.active)
        if not params.active:
            self._autotune_active = False

    def _finalize(self) -> None:
        self.queue.finalize(types.Status.Aborted(types.SHUT_DOWN_ERROR))
        close = getattr(self.controller, "close", None)
        if close is not None:
            close()

    def stop(self) -> None:
        """reference: horovod_shutdown — pending entries get
        SHUT_DOWN_ERROR callbacks (operations.cc:480-486). In multi-process
        mode, shutdown is announced through the SHOULD_SHUT_DOWN status bit
        so every worker exits its cycle loop together (reference:
        response_cache.h:128-132 + controller shutdown propagation)."""
        self._deliberate_stop = True
        flight_recorder.emit("runtime_stop")
        if getattr(self.controller, "net", None) is not None \
                and self._thread.is_alive():
            self.controller.request_shutdown()
            self._woken.set()
            self._thread.join(timeout=10.0)  # exits via bit propagation
        self._stop.set()
        self._woken.set()
        self._thread.join(timeout=10.0)
        if self._thread.is_alive():
            log.warning("background loop did not stop within 10s")


# Serializes only the blocking Runtime construction below. Nothing else
# ever takes it, and it never nests inside another lock (order:
# _runtime_init_lock -> GlobalState.lock).
_runtime_init_lock = witness.make_lock("runtime._runtime_init_lock")


def get_runtime() -> Runtime:
    """Lazily start the background runtime (reference:
    InitializeHorovodOnce spawns the background thread on first init).

    ``Runtime()`` blocks on controller setup (socket connect, probe,
    autotune broadcast), so it must never run under ``GlobalState.lock``
    — rendezvous handlers and init/shutdown paths contend on that lock
    and would wedge behind a slow coordinator. A dedicated init lock
    serializes construction; the winner publishes under ``st.lock``."""
    st = state_mod.global_state()
    if not st.initialized:
        from horovod_tpu.core.basics import NotInitializedError

        raise NotInitializedError()
    with st.lock:
        rt = st.runtime
    if rt is not None:
        return rt
    with _runtime_init_lock:
        with st.lock:
            rt = st.runtime
        if rt is None:
            rt = Runtime()
            with st.lock:
                st.runtime = rt
    return rt
