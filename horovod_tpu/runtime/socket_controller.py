"""Multi-process controller over the native TCP transport.

TPU-native analogue of the reference's ``GlooController`` (reference:
horovod/common/gloo/gloo_controller.cc): the negotiation verbs —
bitvector AND/OR, gather-ready-tensors, broadcast-final-responses,
barrier — run over ``NetComm`` (horovod_tpu/cpp/net.cc), with rank 0 as
coordinator. Process membership comes from the launcher's environment
contract (reference: gloo_context.cc:128-133 reads HOROVOD_RANK/SIZE/...;
rendezvous address knobs gloo_context.cc:37-40).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from horovod_tpu.exceptions import WorkerLostError
from horovod_tpu.runtime import message as msg
from horovod_tpu.runtime.controller import Controller
from horovod_tpu.runtime.native import NetComm


class SocketController(Controller):
    def __init__(self, rank: int, world: int, coord_host: str,
                 coord_port: int, cache_capacity: int = 1024,
                 timeout_ms: int = 30_000):
        super().__init__(rank, world, cache_capacity)
        # bitvector width: capacity cache bits + 3 status bits, fixed for
        # the life of the communicator (single round trip per cycle)
        bit_words = (cache_capacity + 3 + 63) // 64
        self.net = NetComm(rank, world, coord_host, coord_port, timeout_ms,
                           bit_words=bit_words)

    @classmethod
    def from_env(cls, cache_capacity: int = 1024) -> "SocketController":
        """Build from the launcher's env contract (reference:
        gloo_context.cc:128-133)."""
        rank = int(os.environ["HOROVOD_RANK"])
        world = int(os.environ["HOROVOD_SIZE"])
        host = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
        port = int(os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT", "29500"))
        timeout_s = float(os.environ.get("HOROVOD_GLOO_TIMEOUT_SECONDS", "30"))
        return cls(rank, world, host, port, cache_capacity,
                   timeout_ms=int(timeout_s * 1000))

    def _lost(self, phase: str, exc: Exception) -> WorkerLostError:
        """Annotate a transport loss with the negotiation phase and this
        rank — the context the elastic re-form logs need to explain WHY a
        generation ended (the raw verb error only names the syscall)."""
        return WorkerLostError(
            f"rank {self.rank}/{self.world}: {phase} failed — a peer "
            f"died or closed its transport ({exc})", ranks=exc.ranks
            if isinstance(exc, WorkerLostError) else ())

    # -- verbs -------------------------------------------------------------
    def sync_bitvectors(self, bits: int) -> Tuple[int, int]:
        try:
            return self.net.bit_and_or(bits)
        except WorkerLostError as exc:
            raise self._lost("bitvector sync", exc) from exc

    def send_ready_tensors(self, requests: List[msg.Request]
                           ) -> Optional[List[List[msg.Request]]]:
        try:
            blobs = self.net.gatherv(msg.pack_request_list(requests))
        except WorkerLostError as exc:
            raise self._lost("ready-tensor gather", exc) from exc
        if blobs is None:
            return None
        return [msg.unpack_request_list(b) for b in blobs]

    def bcast_responses(self, responses: Optional[List[msg.Response]]
                        ) -> List[msg.Response]:
        try:
            if self.rank == 0:
                assert responses is not None
                blob = self.net.bcast(msg.pack_response_list(responses))
            else:
                blob = self.net.bcast(None)
        except WorkerLostError as exc:
            raise self._lost("response broadcast", exc) from exc
        return msg.unpack_response_list(blob)

    def bcast_blob(self, blob: Optional[bytes]) -> bytes:
        if self.rank == 0:
            assert blob is not None
            return self.net.bcast(blob)
        return self.net.bcast(None)

    def barrier(self) -> None:
        self.net.barrier()

    def close(self) -> None:
        self.net.close()
