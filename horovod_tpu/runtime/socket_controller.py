"""Multi-process controller over the native TCP transport.

TPU-native analogue of the reference's ``GlooController`` (reference:
horovod/common/gloo/gloo_controller.cc): the negotiation verbs —
bitvector AND/OR, gather-ready-tensors, broadcast-final-responses,
barrier — run over ``NetComm`` (horovod_tpu/cpp/net.cc), with rank 0 as
coordinator. Process membership comes from the launcher's environment
contract (reference: gloo_context.cc:128-133 reads HOROVOD_RANK/SIZE/...;
rendezvous address knobs gloo_context.cc:37-40).

Resilience (utils/resilience.py): every verb is a sequence-numbered
control round executed through ``_verb``. Injected connection resets
(chaos ``flaky``) are raised BEFORE any byte moves, so the same round is
simply replayed after backoff — the byte stream stays aligned. A real
transport loss triggers reconnect-and-resume: the communicator is fully
rebuilt (the C layer keeps per-connection state, so reconnection is
cooperative — closing our side makes every peer's blocked verb fail
promptly and funnel into the same rebuild), then an alignment handshake
allgathers each rank's (generation, round); only when EVERY rank is
replaying the same round does the verb re-run — otherwise the typed
``WorkerLostError`` surfaces and the elastic reform takes over. A verb
that stays blocked past ``HOROVOD_COLLECTIVE_TIMEOUT`` is classified as
a generation-stamped ``WorkerStallError`` instead (a stalled/partitioned
peer, not a dead one), feeding the same elastic recovery. Rounds from a
superseded membership generation are fenced off: their results and
errors are discarded rather than delivered into the new epoch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional, Tuple

from horovod_tpu import flight_recorder
from horovod_tpu.exceptions import WorkerLostError, WorkerStallError
from horovod_tpu.runtime import message as msg
from horovod_tpu.runtime.controller import Controller
from horovod_tpu.runtime.native import NetComm
from horovod_tpu.utils import logging as log
from horovod_tpu.utils import resilience


class SocketController(Controller):
    def __init__(self, rank: int, world: int, coord_host: str,
                 coord_port: int, cache_capacity: int = 1024,
                 timeout_ms: int = 30_000,
                 retry: Optional[resilience.RetryPolicy] = None):
        super().__init__(rank, world, cache_capacity)
        # bitvector width: capacity cache bits + 3 status bits, fixed for
        # the life of the communicator (single round trip per cycle)
        self._bit_words = (cache_capacity + 3 + 63) // 64
        self._coord_host = coord_host
        self._coord_port = coord_port
        self._timeout_ms = timeout_ms
        self._retry = retry or resilience.RetryPolicy.from_env("ctrl")
        # the membership generation this communicator belongs to: verbs
        # of a superseded generation are fenced (their late replies and
        # errors must not leak into the re-formed epoch)
        self._generation = resilience.current_generation()
        # sequence number of control rounds; _acked_round is the last
        # round known completed on this rank (reconnects resume from it)
        self._round = 0
        self._acked_round = 0
        self.net = self._retry.call(
            self._connect, phase="connect",
            classify=lambda e: isinstance(e, (RuntimeError, OSError)))
        # collective-timeout watchdog: the steady-state verb reads in the
        # C layer are unbounded (a partitioned-but-alive peer keeps its
        # socket open, so nothing ever fails), so when a deadline is
        # armed a sidecar thread shutdown(2)s the communicator's sockets
        # once a round overruns it — the blocked verb fails promptly and
        # _verb classifies the loss as a WorkerStallError
        self._wd_deadline: Optional[float] = None
        self._wd_lock = threading.Lock()
        self._wd_stop = threading.Event()
        if resilience.collective_timeout() > 0:
            wd = threading.Thread(target=self._watchdog,
                                  name="hvd-collective-watchdog",
                                  daemon=True)
            wd.start()

    def _watchdog(self) -> None:
        while not self._wd_stop.wait(0.1):
            with self._wd_lock:
                deadline = self._wd_deadline
                if deadline is None or time.monotonic() < deadline:
                    continue
                self._wd_deadline = None  # one abort per overrun round
            log.warning(
                "rank %d: control round exceeded "
                "HOROVOD_COLLECTIVE_TIMEOUT=%gs — aborting the blocked "
                "transport verb", self.rank, resilience.collective_timeout())
            try:
                self.net.abort()
            except Exception:
                pass

    def _connect(self, timeout_ms: Optional[int] = None) -> NetComm:
        return NetComm(self.rank, self.world, self._coord_host,
                       self._coord_port,
                       self._timeout_ms if timeout_ms is None else timeout_ms,
                       bit_words=self._bit_words)

    @classmethod
    def from_env(cls, cache_capacity: int = 1024) -> "SocketController":
        """Build from the launcher's env contract (reference:
        gloo_context.cc:128-133)."""
        rank = int(os.environ["HOROVOD_RANK"])
        world = int(os.environ["HOROVOD_SIZE"])
        host = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
        port = int(os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT", "29500"))
        timeout_s = float(os.environ.get("HOROVOD_GLOO_TIMEOUT_SECONDS", "30"))
        # an armed collective timeout bounds every verb: a partitioned
        # peer must fail the round within the deadline, not the (often
        # much longer) transport timeout
        ct = resilience.collective_timeout()
        if ct > 0:
            timeout_s = min(timeout_s, ct)
        return cls(rank, world, host, port, cache_capacity,
                   timeout_ms=int(timeout_s * 1000))

    def _lost(self, phase: str, exc: Exception) -> WorkerLostError:
        """Annotate a transport loss with the negotiation phase and this
        rank — the context the elastic re-form logs need to explain WHY a
        generation ended (the raw verb error only names the syscall)."""
        return WorkerLostError(
            f"rank {self.rank}/{self.world}: {phase} failed — a peer "
            f"died or closed its transport ({exc})", ranks=exc.ranks
            if isinstance(exc, WorkerLostError) else ())

    def _check_fence(self, phase: str) -> None:
        """Generation fence: once the elastic runner has moved on to a
        newer membership generation, anything this communicator produces
        is a late reply from a dead epoch — discard it."""
        current = resilience.current_generation()
        if current != self._generation:
            raise WorkerLostError(
                f"rank {self.rank}: discarding {phase} from stale "
                f"generation {self._generation} (current generation "
                f"{current})")

    # -- resilient verb execution -----------------------------------------
    def _verb(self, phase: str, fn):
        """Run one sequence-numbered control round with retry,
        reconnect-and-resume, collective-timeout classification, and
        generation fencing."""
        self._round += 1
        seq = self._round
        ct = resilience.collective_timeout()
        attempt = 0
        while True:
            self._check_fence(phase)
            t0 = time.monotonic()
            try:
                resilience.inject("ctrl", phase)
                if ct > 0:
                    with self._wd_lock:
                        self._wd_deadline = time.monotonic() + ct
                try:
                    out = fn()
                finally:
                    if ct > 0:
                        with self._wd_lock:
                            self._wd_deadline = None
                self._acked_round = seq
                return out
            except resilience.ChaosError as exc:
                # injected before any byte moved: the stream is intact,
                # the round replays in place after backoff
                attempt += 1
                delay = self._retry.delay_for(attempt)
                if attempt > self._retry.max_retries:
                    resilience.give_up(self._retry.transport, phase,
                                       attempt, exc)
                    raise self._lost(phase, exc) from exc
                resilience.note_retry(self._retry.transport, phase,
                                      attempt, delay, exc)
                time.sleep(delay)
            except WorkerLostError as exc:
                elapsed = time.monotonic() - t0
                self._check_fence(phase)
                if ct > 0 and elapsed >= ct - 0.05:
                    # the verb sat blocked for the whole deadline: a
                    # stalled/partitioned peer, not a clean death —
                    # surface the catchable stall for elastic recovery
                    raise self._stalled(phase, seq, ct, elapsed,
                                        exc) from exc
                attempt += 1
                if attempt <= self._retry.max_retries \
                        and self._reconnect(seq, phase):
                    continue  # aligned on (generation, round) — replay
                raise self._lost(phase, exc) from exc

    def _stalled(self, phase: str, seq: int, ct: float, elapsed: float,
                 exc: WorkerLostError) -> WorkerStallError:
        flight_recorder.emit("collective_timeout", phase=phase, round=seq,
                             generation=self._generation,
                             elapsed=round(elapsed, 3))
        return WorkerStallError(
            f"rank {self.rank}/{self.world}: {phase} (control round {seq}, "
            f"generation {self._generation}) blocked {elapsed:.1f}s — "
            f"HOROVOD_COLLECTIVE_TIMEOUT={ct:g}s exceeded; aborting the "
            f"cycle for elastic recovery ({exc})", ranks=exc.ranks)

    def _reconnect(self, seq: int, phase: str) -> bool:
        """Reconnect-and-resume: rebuild the communicator, then allgather
        every rank's (generation, round). True — replay round ``seq`` —
        only when ALL ranks report the identical round of the identical
        generation, so the replayed verb is stream-aligned everywhere
        (allgather gives every rank the same view, so the go/no-go
        decision is itself consistent). Any mismatch or rebuild failure
        returns False and the caller raises the typed loss for the
        elastic reform to handle."""
        try:
            self.net.close()  # cascades: peers' blocked verbs fail fast
        except Exception:
            pass
        mine = json.dumps({"gen": self._generation, "round": seq})
        # A cooperative rebuild succeeds fast or not at all: every peer's
        # blocked verb failed when we closed our side, so live peers are
        # already re-dialing. The far more common cause of a lost verb is
        # a DEAD peer, where each dial burns its whole window — so probe
        # with a short budget instead of the full transport timeout
        # (which defaults to 30s and would turn every clean peer-death
        # shutdown into a multi-minute reconnect storm).
        probe_ms = min(self._timeout_ms, 2_000)
        for attempt in range(1, 3):
            self._check_fence(phase)
            try:
                net = self._connect(timeout_ms=probe_ms)
            except Exception as exc:
                delay = self._retry.delay_for(attempt)
                resilience.note_retry(self._retry.transport,
                                      phase + ".reconnect", attempt, delay,
                                      exc)
                time.sleep(delay)
                continue
            try:
                peers = [json.loads(b.decode())
                         for b in net.allgatherv(mine.encode())]
            except Exception:
                try:
                    net.close()
                except Exception:
                    pass
                return False
            if all(p == {"gen": self._generation, "round": seq}
                   for p in peers):
                self.net = net
                log.warning(
                    "rank %d: transport re-established; resuming control "
                    "round %d (generation %d)", self.rank, seq,
                    self._generation)
                flight_recorder.emit("net_resume", round=seq,
                                     generation=self._generation,
                                     phase=phase)
                return True
            # some rank already completed this round (or sits in another
            # generation): a verb replay would desynchronize the stream
            try:
                net.close()
            except Exception:
                pass
            log.warning(
                "rank %d: reconnect alignment failed for round %d "
                "(peers report %s) — falling back to elastic re-form",
                self.rank, seq, peers)
            return False
        return False

    # -- verbs -------------------------------------------------------------
    def sync_bitvectors(self, bits: int) -> Tuple[int, int]:
        return self._verb("bitvector sync",
                          lambda: self.net.bit_and_or(bits))

    def send_ready_tensors(self, requests: List[msg.Request]
                           ) -> Optional[List[List[msg.Request]]]:
        blobs = self._verb(
            "ready-tensor gather",
            lambda: self.net.gatherv(msg.pack_request_list(requests)))
        if blobs is None:
            return None
        return [msg.unpack_request_list(b) for b in blobs]

    def bcast_responses(self, responses: Optional[List[msg.Response]]
                        ) -> List[msg.Response]:
        if self.rank == 0:
            assert responses is not None
            packed = msg.pack_response_list(responses)
            blob = self._verb("response broadcast",
                              lambda: self.net.bcast(packed))
        else:
            blob = self._verb("response broadcast",
                              lambda: self.net.bcast(None))
        return msg.unpack_response_list(blob)

    def bcast_blob(self, blob: Optional[bytes]) -> bytes:
        if self.rank == 0:
            assert blob is not None
            return self._verb("blob broadcast",
                              lambda: self.net.bcast(blob))
        return self._verb("blob broadcast", lambda: self.net.bcast(None))

    def barrier(self) -> None:
        self._verb("barrier", lambda: self.net.barrier())

    def close(self) -> None:
        self._wd_stop.set()
        self.net.close()
