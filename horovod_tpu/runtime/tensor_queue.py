"""Thread-safe named-tensor queue.

TPU-native analogue of the reference's ``TensorQueue`` (reference:
horovod/common/tensor_queue.cc/.h): framework threads add
``TensorTableEntry``s + negotiation ``Request``s; the background cycle pops
pending requests and retrieves entries when responses arrive. Duplicate
in-flight names are rejected (reference: tensor_queue.cc:26-29) — the
API-misuse race the reference detects and raises.
"""

from __future__ import annotations

import threading
from typing import Dict, List

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.runtime import message as msg
from horovod_tpu.runtime import types

_QUEUE_DEPTH = _metrics().gauge(
    "horovod_tensor_queue_depth",
    "Named tensors enqueued and not yet handed to the executor.")
_ENQUEUED = _metrics().counter(
    "horovod_tensor_queue_enqueued_total",
    "Named tensors accepted into the tensor queue.")


class DuplicateNameError(ValueError):
    pass


class TensorQueue:
    def __init__(self):
        self._lock = witness.make_lock("TensorQueue._lock")
        self._table: Dict[str, types.TensorTableEntry] = {}  # guarded-by: _lock
        self._pending: List[tuple] = []  # (-priority, seq, request); guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    def add(self, entry: types.TensorTableEntry, request: msg.Request) -> None:
        """reference: TensorQueue::AddToTensorQueue (tensor_queue.cc:18-36)."""
        with self._lock:
            if entry.name in self._table:
                raise DuplicateNameError(
                    types.DUPLICATE_NAME_ERROR_FMT.format(
                        op=entry.request_type.lower()))
            self._table[entry.name] = entry
            self._pending.append((-entry.priority, self._seq, request))
            self._seq += 1
            _ENQUEUED.inc()
            _QUEUE_DEPTH.set(len(self._table))

    def add_group(self, entries: List[types.TensorTableEntry],
                  requests: List[msg.Request]) -> None:
        """Atomically add a released gradient bucket: all entries become
        visible to the cycle thread under one lock acquisition (so one
        negotiation cycle sees the whole bucket and the fusion planner can
        pack it into one dispatch), and the duplicate check is
        all-or-nothing — a clash on any name leaves the table untouched."""
        if len(entries) != len(requests):
            raise ValueError("entries and requests must pair up")
        with self._lock:
            for entry in entries:
                if entry.name in self._table:
                    raise DuplicateNameError(
                        types.DUPLICATE_NAME_ERROR_FMT.format(
                            op=entry.request_type.lower()))
            for entry, request in zip(entries, requests):
                self._table[entry.name] = entry
                self._pending.append((-entry.priority, self._seq, request))
                self._seq += 1
                _ENQUEUED.inc()
            _QUEUE_DEPTH.set(len(self._table))

    def pop_requests(self) -> List[msg.Request]:
        """Drain pending negotiation messages for this cycle, highest
        priority first, enqueue order within a priority level (reference:
        PopMessagesFromQueue, controller.cc:68; priority hint from the
        mxnet binding's engine-ordering semantics,
        horovod/mxnet/mpi_ops.py:52)."""
        with self._lock:
            pending, self._pending = self._pending, []
        return [r for _, _, r in sorted(pending)]

    def get_entries(self, names: List[str]) -> List[types.TensorTableEntry]:
        """Remove and return entries for a (fused) response (reference:
        GetTensorEntriesFromResponse, tensor_queue.cc:71). Missing names
        are skipped — a partial failure must not strand the entries that
        WERE popped with their callbacks unfired."""
        with self._lock:
            out = []
            for n in names:
                e = self._table.pop(n, None)
                if e is not None:
                    out.append(e)
            _QUEUE_DEPTH.set(len(self._table))
            return out

    def peek(self, name: str):
        with self._lock:
            return self._table.get(name)

    def pending_names(self) -> List[str]:
        with self._lock:
            return list(self._table.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._table)

    def finalize(self, status: types.Status) -> None:
        """Flush every in-flight entry with an error callback on shutdown
        (reference: FinalizeTensorQueue — SHUT_DOWN_ERROR to all pending)."""
        with self._lock:
            entries = list(self._table.values())
            self._table.clear()
            self._pending.clear()
            _QUEUE_DEPTH.set(0)
        for e in entries:
            e.complete(status, None)
