"""Core runtime types: Status, TensorTableEntry, request/response kinds.

TPU-native analogue of the reference's core type layer (reference:
horovod/common/common.h:118-242 — ``Status``, ``StatusType``, ``Tensor``/
``OpContext`` interfaces, ``TensorTableEntry``). Arrays are ``jax.Array``s
(no framework adapter classes needed), so what remains is the status
plumbing and the table entry that flows from enqueue to completion.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Any, Callable, Optional

# reference: horovod/common/message.h RequestType / ResponseType
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
REDUCESCATTER = "REDUCESCATTER"
ALLTOALL = "ALLTOALL"
ERROR = "ERROR"
# Synchronized cache-invalidation notice (no reference analogue as a wire
# type; the reference syncs invalidated cache bits inside its
# CacheCoordinator protocol, response_cache.cc:308-430 — this is our
# explicit-message equivalent keeping every worker's cache bit-aligned).
INVALIDATE = "INVALIDATE"


class StatusType(enum.Enum):
    # reference: common/common.h:124-131
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclasses.dataclass(frozen=True)
class Status:
    type: StatusType = StatusType.OK
    reason: str = ""

    def ok(self) -> bool:
        return self.type == StatusType.OK

    def in_progress(self) -> bool:
        return self.type == StatusType.IN_PROGRESS

    @staticmethod
    def OK() -> "Status":
        return Status()

    @staticmethod
    def Aborted(reason: str) -> "Status":
        return Status(StatusType.ABORTED, reason)

    @staticmethod
    def InvalidArgument(reason: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, reason)

    @staticmethod
    def PreconditionError(reason: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, reason)

    @staticmethod
    def UnknownError(reason: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, reason)


# reference error texts (common.h:141-158), kept recognizable for users
# migrating from the reference.
DUPLICATE_NAME_ERROR_FMT = (
    "Requested to {op} a tensor with the same name as another tensor that is "
    "currently being processed. If you want to request another tensor, use a "
    "different tensor name."
)
SHUT_DOWN_ERROR = (
    "Framework has been shut down. This was caused by an exception on one of "
    "the workers or an attempt to run a collective after shutdown."
)

StatusCallback = Callable[[Status, Optional[Any]], None]

# Reduction ops carried on allreduce entries/requests over the wire
# (reference: the op-type dispatch in horovod/torch/mpi_ops_v2.cc:52-76,
# generalized beyond sum/average).
REDUCE_SUM = "sum"
REDUCE_AVERAGE = "average"
REDUCE_MIN = "min"
REDUCE_MAX = "max"
REDUCE_PRODUCT = "product"
REDUCE_OPS = (REDUCE_SUM, REDUCE_AVERAGE, REDUCE_MIN, REDUCE_MAX,
              REDUCE_PRODUCT)


@dataclasses.dataclass
class TensorTableEntry:
    """One enqueued named tensor (reference: common/common.h:225-242).

    ``tensor`` is the input (stacked per-worker or replicated ``jax.Array``);
    ``output`` is filled by the runtime before the callback fires.
    """

    name: str
    tensor: Any
    request_type: str = ALLREDUCE
    root_rank: int = 0
    reduce_op: str = REDUCE_AVERAGE
    callback: Optional[StatusCallback] = None
    output: Any = None
    # set at enqueue time for negotiation/validation
    dtype: Any = None
    shape: tuple = ()
    enqueue_time: float = 0.0
    # execution-order hint: higher-priority tensors enter negotiation (and
    # thus fusion) first within a cycle (reference: mxnet ops pass priority
    # to the MXNet engine, horovod/mxnet/mpi_ops.py:52)
    priority: int = 0
    # completion is tracked on the entry itself so the exactly-once guard
    # works for ANY callable — not just bound methods of a pollable handle
    completed: bool = False
    # the cycle thread and the caller thread (stop() -> finalize) can race
    # to complete the same entry; the lock makes the check-then-set atomic
    _complete_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    def complete(self, status, output=None) -> None:
        """Fire the completion callback exactly once. All runtime paths
        (success, error, shutdown, cycle-failure cleanup) funnel through
        here, so a double fire is structurally impossible no matter what
        the callback is wrapped in."""
        with self._complete_lock:
            if self.completed:
                return
            self.completed = True
        if self.callback is not None:
            self.callback(status, output)


def entry_nbytes(entry: "TensorTableEntry") -> int:
    """Per-worker payload bytes of one enqueued tensor (autotune throughput
    scoring; reference: parameter_manager scores bytes/us of processed
    tensors). Uses the same wire-shape convention as the announcement path
    (runtime._enqueue): a worker-stacked array counts shape[1:], so scores
    are comparable across single- and multi-process modes."""
    from horovod_tpu.ops import collectives
    from horovod_tpu.runtime import fusion

    shape = (entry.shape[1:] if collectives._is_worker_stacked(entry.tensor)
             else entry.shape)
    n = 1
    for d in shape:
        n *= int(d)
    try:
        item = fusion._dtype_size(str(entry.dtype))
    except TypeError:
        item = 4
    return n * item
