"""Online inference plane: continuous batching on the elastic runtime.

Layout (docs/inference.md is the full architecture doc):

* ``api``      — ``hvd.serve()``, :class:`ServePolicy`, ``serve_state()``
* ``queue``    — shared request queue (in-process + rendezvous-KV)
* ``batcher``  — iteration-level admission/retire scheduling
* ``kv_cache`` — per-slot KV cache + bucketed serving program caches
* ``paging``   — paged KV cache: block pool, prefix reuse, COW sharing
* ``replica``  — the per-replica loop; ``run_kv_replica`` for fleets
* ``__main__`` — the ``tpurun --serve`` demo worker
"""

from horovod_tpu.serve.api import (ServeHandle, ServePolicy, serve,
                                   serve_state)
from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.kv_cache import DecodeEngine, prompt_bucket
from horovod_tpu.serve.paging import (PagedDecodeEngine, PagePool,
                                      PagePoolExhausted, PrefixCache,
                                      total_pool_bytes)
from horovod_tpu.serve.queue import (Completion, KVQueueFrontend,
                                     KVQueueReplica, QueueFull, Request,
                                     RequestQueue)
from horovod_tpu.serve.replica import Replica, run_kv_replica

__all__ = [
    "Completion", "ContinuousBatcher", "DecodeEngine", "KVQueueFrontend",
    "KVQueueReplica", "PagePool", "PagePoolExhausted", "PagedDecodeEngine",
    "PrefixCache", "QueueFull", "Replica", "Request", "RequestQueue",
    "ServeHandle", "ServePolicy", "prompt_bucket", "run_kv_replica",
    "serve", "serve_state", "total_pool_bytes",
]
