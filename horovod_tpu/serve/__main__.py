"""``python -m horovod_tpu.serve`` — a KV-queue replica worker.

This is what ``tpurun --serve`` launches per slot when no command is
given: each rank builds the demo model (random weights, deterministic
seed — every replica must hold identical params), registers with the
rendezvous KV queue, and serves until a dispatcher publishes the stop
key. Point a :class:`~horovod_tpu.serve.queue.KVQueueFrontend` at the
same rendezvous server to drive it (bench.py's ``--serve`` load
generator, or the chaos matrix's ``serve_chaos_worker.py``).

Model shape flags exist so smoke runs stay tiny; a real deployment
replaces this module with its own worker that loads trained params and
calls :func:`horovod_tpu.serve.run_kv_replica`.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve", description=__doc__)
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--heads", type=int, default=2)
    parser.add_argument("--d-ff", type=int, default=128)
    parser.add_argument("--max-seq", type=int, default=128)
    parser.add_argument("--seed", type=int, default=0,
                        help="param seed; identical across the fleet")
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from horovod_tpu import profiler, tracing
    from horovod_tpu.models.transformer import Transformer
    from horovod_tpu.serve import ServePolicy, run_kv_replica
    from horovod_tpu.serve.api import _serve_guard

    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    addr = os.environ.get("HOROVOD_RENDEZVOUS_HTTP_ADDR", "127.0.0.1")
    port = int(os.environ.get("HOROVOD_RENDEZVOUS_HTTP_PORT", "0"))
    if not port:
        print("horovod_tpu.serve: HOROVOD_RENDEZVOUS_HTTP_PORT not set "
              "(run under tpurun --serve)", file=sys.stderr)
        return 2

    model = Transformer(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads, d_ff=args.d_ff,
        max_seq=args.max_seq, causal=True, dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), tokens,
                        train=False)["params"]

    # no hvd.init() here (the serving plane rides the KV store alone),
    # so the tracing/profiling planes adopt the rank explicitly — a
    # replica launched under --profile-dir must dump its request spans
    # for the launcher's merged Perfetto trace
    tracing.configure(rank=rank)
    tracing.note_serve_started()
    profiler.configure(rank=rank)

    policy = ServePolicy.from_env()
    guard = _serve_guard(rank) if policy.quarantine else None
    try:
        replica = run_kv_replica(model, params, policy, rank=rank,
                                 addr=addr, port=port, guard=guard)
    finally:
        profiler.finalize()
    print(f"horovod_tpu.serve: rank {rank} drained "
          f"({replica.completed} completed)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
