"""``hvd.serve()`` — the public face of the serving plane.

    import horovod_tpu as hvd
    from horovod_tpu.models.transformer import GPT2Small

    handle = hvd.serve(model, params, replicas=2, max_new_tokens=32)
    uid = handle.submit([12, 7, 99])
    out = handle.result(uid, timeout=30.0)   # Completion(tokens=...)
    handle.close()

In-process mode (above) runs ``replicas`` replica threads — each with
its own :class:`~horovod_tpu.serve.kv_cache.DecodeEngine` (own cache,
own program set) — against one shared in-memory
:class:`~horovod_tpu.serve.queue.RequestQueue`. Cross-process fleets
(``tpurun --serve``) run :func:`~horovod_tpu.serve.replica.
run_kv_replica` per rank against the rendezvous KV queue instead; the
policy/metrics/guard machinery is identical.

Every policy knob has a ``HOROVOD_SERVE_*`` env default
(:meth:`ServePolicy.from_env`; docs/inference.md has the table) and a
keyword override on :func:`serve`.

``serve_state()`` is the ``/serve`` route of the metrics server: a
JSON snapshot of every live handle's replicas, queue, and program
caches.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional

from horovod_tpu import flight_recorder, tracing
from horovod_tpu.analysis import witness
from horovod_tpu.integrity.guards import StepGuard
from horovod_tpu.serve.kv_cache import DecodeEngine
from horovod_tpu.serve.paging import (DEFAULT_PAGE_TOKENS,
                                      DEFAULT_PREFIX_ENTRIES,
                                      HOROVOD_SERVE_PAGE_POOL,
                                      HOROVOD_SERVE_PAGE_TOKENS,
                                      HOROVOD_SERVE_PAGED,
                                      HOROVOD_SERVE_PREFIX_CACHE,
                                      PagedDecodeEngine)
from horovod_tpu.serve.queue import Completion, RequestQueue
from horovod_tpu.serve.replica import Replica, _LocalTransport
from horovod_tpu.utils.env import _get_bool, _get_float, _get_int

HOROVOD_SERVE_MAX_BATCH_TOKENS = "HOROVOD_SERVE_MAX_BATCH_TOKENS"
HOROVOD_SERVE_ADMISSION_MS = "HOROVOD_SERVE_ADMISSION_MS"
HOROVOD_SERVE_QUEUE_CAPACITY = "HOROVOD_SERVE_QUEUE_CAPACITY"
HOROVOD_SERVE_DECODE_BLOCK = "HOROVOD_SERVE_DECODE_BLOCK"
HOROVOD_SERVE_SLOTS = "HOROVOD_SERVE_SLOTS"
HOROVOD_SERVE_MAX_NEW_TOKENS = "HOROVOD_SERVE_MAX_NEW_TOKENS"
HOROVOD_SERVE_QUARANTINE = "HOROVOD_SERVE_QUARANTINE"


@dataclasses.dataclass(frozen=True)
class ServePolicy:
    """Continuous-batching policy; docs/inference.md explains each knob
    and batcher.py the precedence (budget > slots > deadline > block)."""

    max_batch_tokens: int = 4096
    admission_ms: float = 50.0
    queue_capacity: int = 1024
    decode_block: int = 8
    slots: int = 8
    max_new_tokens: int = 64
    quarantine: bool = True
    # paged KV cache (serve/paging.py; docs/inference.md): page_pool=0
    # sizes the pool to half the dense slots x max_seq capacity,
    # prefix_cache=0 disables prefix reuse
    paged: bool = False
    page_tokens: int = DEFAULT_PAGE_TOKENS
    page_pool: int = 0
    prefix_cache: int = DEFAULT_PREFIX_ENTRIES

    @classmethod
    def from_env(cls, **overrides) -> "ServePolicy":
        base = {
            "max_batch_tokens": _get_int(HOROVOD_SERVE_MAX_BATCH_TOKENS,
                                         cls.max_batch_tokens),
            "admission_ms": _get_float(HOROVOD_SERVE_ADMISSION_MS,
                                       cls.admission_ms),
            "queue_capacity": _get_int(HOROVOD_SERVE_QUEUE_CAPACITY,
                                       cls.queue_capacity),
            "decode_block": _get_int(HOROVOD_SERVE_DECODE_BLOCK,
                                     cls.decode_block),
            "slots": _get_int(HOROVOD_SERVE_SLOTS, cls.slots),
            "max_new_tokens": _get_int(HOROVOD_SERVE_MAX_NEW_TOKENS,
                                       cls.max_new_tokens),
            "quarantine": _get_bool(HOROVOD_SERVE_QUARANTINE,
                                    cls.quarantine),
            "paged": _get_bool(HOROVOD_SERVE_PAGED, cls.paged),
            "page_tokens": _get_int(HOROVOD_SERVE_PAGE_TOKENS,
                                    cls.page_tokens),
            "page_pool": _get_int(HOROVOD_SERVE_PAGE_POOL, cls.page_pool),
            "prefix_cache": _get_int(HOROVOD_SERVE_PREFIX_CACHE,
                                     cls.prefix_cache),
        }
        unknown = set(overrides) - set(base)
        if unknown:
            raise TypeError(f"unknown serve policy knob(s): "
                            f"{sorted(unknown)}")
        base.update(overrides)
        return cls(**base)


def _serve_guard(rank: int) -> StepGuard:
    """The serving integrity guard watches per-step max-|logit|, which
    legitimately swings with the prompt mix — unlike the allreduced loss
    the training default (sigma=6, warmup=5) was tuned for. Serving
    relaxes to sigma=12 / warmup=32 so healthy variation only ever costs
    a skipped observation, while non-finite values and persistent
    divergence still quarantine. HOROVOD_INTEGRITY_SPIKE_SIGMA
    overrides the sigma here too."""
    from horovod_tpu.integrity.guards import HOROVOD_INTEGRITY_SPIKE_SIGMA

    return StepGuard(sigma=_get_float(HOROVOD_INTEGRITY_SPIKE_SIGMA, 12.0),
                     warmup=32, decay=0.98, name=f"serve_r{rank}")


# live handles, for serve_state() / the /serve route
_state_lock = witness.make_lock("serve_api._state_lock")
_handles: List["ServeHandle"] = []   # guarded-by: _state_lock


class ServeHandle:
    """A running in-process replica set + its shared queue."""

    def __init__(self, replicas: List[Replica], queue: RequestQueue,
                 policy: ServePolicy, tokenizer=None):
        self._replicas = replicas
        self._queue = queue
        self._policy = policy
        self._tokenizer = tokenizer
        self._max_seq = min((r.engine.max_seq for r in replicas),
                            default=0)
        self._threads: List[threading.Thread] = []
        self._closed = False
        self.started_s = time.monotonic()
        # /healthz flips to "serving": not ready again until a replica
        # loop (or KV heartbeat) proves the fleet actually came up
        tracing.note_serve_started()
        for replica in replicas:
            t = threading.Thread(target=replica.run, daemon=True,
                                 name=replica.name)
            self._threads.append(t)
            t.start()
        with _state_lock:
            _handles.append(self)
        # flight-recorder "serve" provider: every postmortem dump now
        # carries the serving snapshot — replica/queue state and, under
        # HOROVOD_SERVE_PAGED, pool occupancy at death
        flight_recorder.set_state_provider("serve", serve_state)

    # -- request API -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None) -> str:
        """Enqueue a prompt (token-id list, or text when a tokenizer was
        given); returns the request id.

        Raises :class:`ValueError` for a prompt the replicas could
        never serve (empty, or longer than the model's ``max_seq``) —
        admission would otherwise fail deep inside a replica thread and
        the caller would hang in :meth:`result` until timeout. A prompt
        that FITS but whose ``prompt + max_new_tokens`` overruns the KV
        cache is accepted and truncated (``finish="cache_limit"`` on the
        completion)."""
        if self._closed:
            raise RuntimeError(
                "serve handle is closed; nothing would ever complete "
                "this request")
        if self._tokenizer is not None and isinstance(prompt, str):
            prompt = list(self._tokenizer.encode(prompt))
        prompt = list(prompt)
        if not prompt:
            raise ValueError("serve: empty prompt")
        if self._max_seq and len(prompt) > self._max_seq:
            raise ValueError(
                f"serve: prompt length {len(prompt)} exceeds the "
                f"model's max_seq ({self._max_seq})")
        # the trace context is minted HERE, at the public API edge —
        # every span and serve-path flight event downstream carries it
        trace_id = tracing.new_trace_id()
        uid = self._queue.submit(
            prompt,
            max_new_tokens=(self._policy.max_new_tokens
                            if max_new_tokens is None else max_new_tokens),
            trace_id=trace_id)
        flight_recorder.emit("serve_submit", uid=uid, trace_id=trace_id,
                             prompt_len=len(prompt))
        return uid

    def result(self, uid: str, timeout: Optional[float] = None
               ) -> Completion:
        return self._queue.result(uid, timeout=timeout)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = 60.0) -> Completion:
        return self.result(self.submit(prompt, max_new_tokens),
                           timeout=timeout)

    # -- introspection -----------------------------------------------------
    @property
    def policy(self) -> ServePolicy:
        return self._policy

    def queue_depth(self) -> int:
        return self._queue.depth()

    def compiles_total(self) -> int:
        return sum(r.engine.compiles_total() for r in self._replicas)

    def stats(self) -> dict:
        return {
            "policy": dataclasses.asdict(self._policy),
            "uptime_s": round(time.monotonic() - self.started_s, 3),
            "queue": self._queue.stats(),
            "replicas": [r.stats() for r in self._replicas],
        }

    def close(self, timeout: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        for replica in self._replicas:
            replica.stop()
        for t in self._threads:
            t.join(timeout=timeout)
        with _state_lock:
            if self in _handles:
                _handles.remove(self)

    def __enter__(self) -> "ServeHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve(model, params, tokenizer=None, *, replicas: int = 1,
          policy: Optional[ServePolicy] = None, **overrides) -> ServeHandle:
    """Start an in-process continuous-batching replica set over
    ``model``/``params`` and return its :class:`ServeHandle`.

    ``model`` must be a causal :class:`~horovod_tpu.models.transformer.
    Transformer` (or clone-compatible); ``params`` its trained params
    pytree. ``**overrides`` are :class:`ServePolicy` fields; anything
    not overridden comes from ``HOROVOD_SERVE_*`` env knobs.
    """
    if policy is None:
        policy = ServePolicy.from_env(**overrides)
    elif overrides:
        policy = dataclasses.replace(policy, **overrides)
    queue = RequestQueue(capacity=policy.queue_capacity)
    fleet: List[Replica] = []
    for rank in range(replicas):
        if policy.paged:
            engine = PagedDecodeEngine(
                model, params, num_slots=policy.slots, name=f"r{rank}",
                page_tokens=policy.page_tokens,
                pool_pages=policy.page_pool,
                prefix_entries=policy.prefix_cache)
        else:
            engine = DecodeEngine(model, params, num_slots=policy.slots,
                                  name=f"r{rank}")
        guard = _serve_guard(rank) if policy.quarantine else None
        fleet.append(Replica(engine, _LocalTransport(queue, rank), policy,
                             rank=rank, guard=guard))
    return ServeHandle(fleet, queue, policy, tokenizer=tokenizer)


def serve_state() -> dict:
    """JSON-ready snapshot of every live handle — the ``/serve`` route
    on the metrics server (docs/metrics.md)."""
    with _state_lock:
        handles = list(_handles)
    return {"handles": [h.stats() for h in handles],
            "count": len(handles)}
