"""Iteration-level continuous batcher (Orca-style scheduling).

One instance per replica, owned by the replica loop thread — all state
below is ``# guarded-by: <replica-thread>``. The batcher is pure
scheduling: it never touches jax, so the admission policy is unit-
testable with a fake clock (tests/test_serve.py's policy matrix).

Admission policy, in priority order:

1. **Token budget is a hard cap.** A candidate is admitted only if the
   committed token total — every active slot's ``prompt_len +
   max_tokens`` plus the candidate's — stays within
   ``HOROVOD_SERVE_MAX_BATCH_TOKENS``. Committed (worst-case) rather
   than current lengths, so an admitted request can never be evicted
   mid-generation by later admissions. The admission deadline never
   overrides the budget. ``max_tokens`` is ``max_new_tokens`` capped at
   admission so no KV write can land past the cache length (the request
   then finishes with ``finish="cache_limit"``).
2. **Slots.** At most ``HOROVOD_SERVE_SLOTS`` concurrent requests (one
   KV-cache row each).
3. **Deadline beats the decode block.** Between admission checks the
   replica decodes ``HOROVOD_SERVE_DECODE_BLOCK`` uninterrupted steps
   (admission means a prefill, i.e. a latency bubble for running
   requests — batching those bubbles amortizes them). But a waiting
   request older than ``HOROVOD_SERVE_ADMISSION_MS`` pulls the check
   forward to the next step boundary: the block length bounds decode
   batching, the deadline bounds queueing delay, and the deadline wins.

FIFO order: requests are admitted in arrival order, and a budget-blocked
head does not let younger requests jump it (head-of-line blocking is the
price of no-starvation; the budget check is against the queue head).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu.serve.queue import Request


@dataclasses.dataclass
class ActiveRequest:
    """One occupied KV-cache slot. ``max_tokens`` is the EFFECTIVE
    generation length: the request's ``max_new_tokens``, capped at
    admission so every KV write stays inside the cache
    (``prompt_len + max_tokens - 1 <= max_seq`` — the last generated
    token is returned, never written). Without the cap, positions past
    ``max_seq`` would silently clamp onto the last cache row and the
    request would complete with garbage tokens."""

    slot: int
    request: Request
    prompt_len: int
    position: int            # absolute index the NEXT token writes at
    max_tokens: int = 0      # 0 → request.max_new_tokens (uncapped)
    page_cost: int = 0       # committed KV pages charged at admission
    #                          (paged engines only; 0 under dense)
    admit_seq: int = 0       # admission order — preemption takes newest
    generated: List[int] = dataclasses.field(default_factory=list)
    first_token_s: float = 0.0
    admitted_s: float = 0.0
    # span bookkeeping (tracing.py; written by the replica loop): phase
    # durations for the slow-request exemplar, plus the open decode-block
    # span — block_t0 is EPOCH seconds (the trace clock), block_steps
    # counts decode steps since the block opened
    queue_wait_s: float = 0.0
    prefill_s: float = 0.0
    block_t0: float = 0.0
    block_steps: int = 0
    blocks: int = 0

    def __post_init__(self):
        if self.max_tokens <= 0:
            self.max_tokens = self.request.max_new_tokens

    @property
    def capped(self) -> bool:
        return self.max_tokens < self.request.max_new_tokens

    @property
    def committed_tokens(self) -> int:
        return self.prompt_len + self.max_tokens

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.max_tokens


class ContinuousBatcher:
    """Slot assignment + admission timing for one replica."""

    def __init__(self, num_slots: int, max_batch_tokens: int,
                 admission_ms: float, decode_block: int,
                 max_seq: Optional[int] = None,
                 page_tokens: Optional[int] = None,
                 pool_pages: Optional[int] = None,
                 prefix_probe=None):
        self.num_slots = num_slots
        self.max_batch_tokens = max_batch_tokens
        self.admission_s = admission_ms / 1000.0
        self.decode_block = max(1, decode_block)
        self.max_seq = max_seq   # cache length; None → no generation cap
        # paged admission (serve/paging.py): when page_tokens is set the
        # pool — not dense slot rows — is the capacity being committed.
        # prefix_probe(prompt) -> currently-cached full-block pages, the
        # admission discount (optimistic: a later eviction shows up as a
        # PagePoolExhausted the replica answers with preemption).
        self.page_tokens = page_tokens
        self.pool_pages = pool_pages
        self.prefix_probe = prefix_probe
        # guarded-by: <replica-thread>
        self._waiting: deque = deque()   # (Request, offered_monotonic)
        self._active: Dict[int, ActiveRequest] = {}
        self._free: List[int] = sorted(range(num_slots), reverse=True)
        self._steps_since_admission = 0
        self._admission_seq = 0   # monotonic admission order (preemption)
        self.preemptions = 0

    # -- introspection -----------------------------------------------------
    def waiting(self) -> int:
        return len(self._waiting)

    def active(self) -> List[ActiveRequest]:
        return list(self._active.values())

    def occupancy(self) -> int:
        return len(self._active)

    def committed_tokens(self) -> int:
        return sum(a.committed_tokens for a in self._active.values())

    def committed_pages(self) -> int:
        return sum(a.page_cost for a in self._active.values())

    def oldest_wait_s(self, now: Optional[float] = None) -> float:
        if not self._waiting:
            return 0.0
        now = time.monotonic() if now is None else now
        return now - self._waiting[0][1]

    # -- scheduling --------------------------------------------------------
    def offer(self, request: Request, now: Optional[float] = None) -> None:
        self._waiting.append((request,
                              time.monotonic() if now is None else now))

    def note_step(self) -> None:
        self._steps_since_admission += 1

    def admission_due(self, now: Optional[float] = None) -> bool:
        """Check admission this iteration? True at every decode-block
        boundary, immediately when the replica is idle, and early when
        the queue head has waited past the admission deadline."""
        if not self._waiting:
            return False
        if not self._active:
            return True
        if self._steps_since_admission >= self.decode_block:
            return True
        return self.oldest_wait_s(now) >= self.admission_s

    def admit(self, now: Optional[float] = None) -> List[ActiveRequest]:
        """Admit FIFO from the waiting line while slots and the token
        budget allow; resets the decode-block counter."""
        now = time.monotonic() if now is None else now
        admitted: List[ActiveRequest] = []
        budget = self.committed_tokens()
        pages = self.committed_pages()
        while self._waiting and self._free:
            req, _ = self._waiting[0]
            max_tokens = req.max_new_tokens
            if self.max_seq is not None:
                # last generated token is returned, never written, so
                # prompt_len + max_tokens - 1 must fit the cache
                max_tokens = max(
                    1, min(max_tokens, self.max_seq - len(req.prompt) + 1))
            page_cost = 0
            if self.page_tokens and self.pool_pages:
                # a single request must fit the whole pool — the paged
                # analogue of the max_seq cap, same cache_limit finish
                cap = self.pool_pages * self.page_tokens \
                    - len(req.prompt) + 1
                max_tokens = max(1, min(max_tokens, cap))
                # committed pages: worst-case written positions
                # (prompt + generated - 1), discounted by the prefix
                # pages currently shared in the engine's cache
                written = len(req.prompt) + max_tokens - 1
                discount = (self.prefix_probe(req.prompt)
                            if self.prefix_probe is not None else 0)
                page_cost = max(
                    1, -(-written // self.page_tokens) - discount)
                if pages + page_cost > self.pool_pages:
                    break   # pool committed — wait for retires
            cost = len(req.prompt) + max_tokens
            if budget + cost > self.max_batch_tokens:
                break   # hard cap — the deadline never overrides it
            self._waiting.popleft()
            slot = self._free.pop()
            self._admission_seq += 1
            active = ActiveRequest(slot=slot, request=req,
                                   prompt_len=len(req.prompt),
                                   position=len(req.prompt),
                                   max_tokens=max_tokens,
                                   page_cost=page_cost,
                                   admit_seq=self._admission_seq,
                                   admitted_s=now)
            self._active[slot] = active
            admitted.append(active)
            budget += cost
            pages += page_cost
        self._steps_since_admission = 0
        return admitted

    def retire_done(self) -> List[ActiveRequest]:
        """Free the slots of finished requests (iteration-level retire:
        called after every decode step, not at batch boundaries)."""
        done = [a for a in self._active.values() if a.done]
        for a in done:
            del self._active[a.slot]
            self._free.append(a.slot)
        self._free.sort(reverse=True)
        return done

    def preempt_slot(self, slot: int,
                     now: Optional[float] = None) -> Optional[ActiveRequest]:
        """Pool-exhaustion path (paged engines): push ``slot``'s request
        back to the FRONT of the waiting line — it is older than
        anything queued behind it, so FIFO fairness holds — free its
        slot, and count the requeue. Pages are the ENGINE's to reclaim
        (``release_slot``); the batcher only schedules. The generated
        prefix is dropped: greedy decoding regenerates it
        deterministically on resume, so nothing is lost — the same
        invariant the quarantine requeue rides."""
        active = self._active.pop(slot, None)
        if active is None:
            return None
        self._free.append(slot)
        self._free.sort(reverse=True)
        active.request.requeues += 1
        self._waiting.appendleft(
            (active.request, time.monotonic() if now is None else now))
        self.preemptions += 1
        return active

    def preempt_newest(self, exclude_slot: Optional[int] = None,
                       now: Optional[float] = None
                       ) -> Optional[ActiveRequest]:
        """Pick the NEWEST-admitted active request (it has done the
        least work and, having been admitted last, is the fairest to
        defer) and preempt it. ``exclude_slot`` protects the request
        the caller is currently operating on (e.g. mid-prefill)."""
        candidates = [a for a in self._active.values()
                      if a.slot != exclude_slot]
        if not candidates:
            return None
        victim = max(candidates, key=lambda a: a.admit_seq)
        return self.preempt_slot(victim.slot, now=now)

    def evict_all(self) -> List[Request]:
        """Drop every active request (quarantine / worker-loss path) and
        return them for requeueing — nothing is lost, the generated
        prefix is (tokens are regenerated deterministically on replay)."""
        evicted = [a.request for a in
                   sorted(self._active.values(), key=lambda a: a.slot)]
        self._active.clear()
        self._free = sorted(range(self.num_slots), reverse=True)
        return evicted

    def drain_waiting(self) -> List[Request]:
        out = [req for req, _ in self._waiting]
        self._waiting.clear()
        return out

    def batch_rows(self) -> Tuple[List[int], List[int], List[int]]:
        """(slots, token_ids, positions) for the next decode step: each
        active row's last generated token (or last prompt token right
        after prefill) at its current position."""
        slots, tokens, positions = [], [], []
        for a in sorted(self._active.values(), key=lambda a: a.slot):
            if a.done:
                continue
            tokens.append(a.generated[-1] if a.generated
                          else a.request.prompt[-1])
            positions.append(a.position)
            slots.append(a.slot)
        return slots, tokens, positions
