"""Per-slot KV-cache management + the serving program caches.

:class:`DecodeEngine` owns everything jax about one replica:

* the decode clone of the user's model (``model.clone(decode=True)`` —
  same params, plus a ``cache`` variable collection of
  ``(slots, max_seq, heads, head_dim)`` key/value tensors per layer);
* ONE jitted decode program over ALL slots every step — the shape never
  changes (inactive rows run masked garbage at position 0, overwritten
  by the next prefill), so steady-state decode never recompiles;
* one jitted prefill program PER PROMPT-LENGTH BUCKET, batch 1, which
  writes the prompt's KV into a fresh single-row cache and scatters it
  into the requested slot at a traced index. Bucketing reuses the
  runtime's size-bucket policy (``fusion_buffer.bucket_elems``: identity
  up to the quantum, then power-of-two multiples), floored at the
  quantum so short prompts share one program — the bucket set is
  O(log(max_seq)) and after one request per bucket the program cache is
  warm: zero steady-state compiles.

Prefill padding is safe without length bookkeeping: padded positions'
garbage KV sits at positions ``>= prompt_len``, which
``models.transformer.cached_attention`` masks for every query that has
not reached them — and decode overwrites each one before its query
arrives. Slot reuse is safe the same way (stale rows of the previous
occupant are never attendable); tests/test_serve.py pins both down
against the uncached ``apply``.

Sampling is greedy (argmax in-graph; only the winning token ids leave
the device each step, plus one max-|logit| scalar per slot for the
integrity guard).
"""

from __future__ import annotations

import time
import weakref
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.runtime.fusion_buffer import bucket_elems

# prompt-length bucket quantum (tokens). Not a knob: the policy is the
# runtime's, only the unit differs (tokens, not bytes).
PREFILL_BUCKET_QUANTUM = 16

_COMPILES = _metrics().counter(
    "horovod_serve_compiles_total",
    "Serving programs compiled, by kind (steady state adds none).",
    labelnames=("program",))
_KV_BYTES = _metrics().gauge(
    "horovod_serve_kv_cache_bytes",
    "KV-cache bytes resident per decode engine (replica).",
    labelnames=("replica",))

# every live engine, so the memory tracker's "serve_kv" subsystem can sum
# resident cache bytes without the serve plane pushing on its hot path
_engines_lock = witness.make_lock("kv_cache._engines_lock")
_engines: "weakref.WeakSet" = weakref.WeakSet()  # guarded-by: _engines_lock


def total_cache_bytes() -> int:
    """Resident KV-cache bytes across every live engine on this process —
    the memory tracker's pull source for the ``serve_kv`` subsystem."""
    with _engines_lock:
        engines = list(_engines)
    return sum(e.cache_bytes() for e in engines)


def prompt_bucket(prompt_len: int, max_seq: int,
                  quantum: int = PREFILL_BUCKET_QUANTUM) -> int:
    """Padded prompt length: the fusion-buffer size-bucket policy in
    token units, floored at the quantum (identity below the quantum
    would mean one compile per distinct short-prompt length — right for
    fusion cache keys, wrong for programs)."""
    return min(max_seq, bucket_elems(max(prompt_len, quantum), 1, quantum))


class DecodeEngine:
    """Model programs + the slot cache for one replica."""

    def __init__(self, model, params, num_slots: int, name: str = "r0"):
        if not getattr(model, "causal", True):
            raise ValueError("hvd.serve() needs a causal (decoder) model")
        self.name = name
        self.num_slots = int(num_slots)
        self.max_seq = int(model.max_seq)
        self.vocab_size = int(model.vocab_size)
        self._params = params
        self._model = model.clone(decode=True, remat=False,
                                  attention_fn=None)
        self._cache = self._allocate_cache()
        self._prefill_fns: Dict[int, object] = {}  # guarded-by: <replica-thread>
        self._decode_fn = jax.jit(self._decode_impl)
        self._decode_compiled = False
        self._lock = witness.make_lock("DecodeEngine._lock")
        self._compiles: Dict[str, int] = {}      # guarded-by: _lock
        self.decode_steps = 0
        self.step_ms_ewma = 0.0
        with _engines_lock:
            _engines.add(self)
        _KV_BYTES.labels(replica=self.name).set(self.cache_bytes())

    # -- cache -------------------------------------------------------------
    def _allocate_cache(self):
        """Zero cache pytree with the decode program's shapes — derived
        via ``eval_shape`` so allocation itself compiles nothing."""
        tokens = jnp.zeros((self.num_slots, 1), jnp.int32)
        pos = jnp.zeros((self.num_slots,), jnp.int32)
        _, shapes = jax.eval_shape(
            lambda p, t, q: self._model.apply(
                {"params": p}, t, positions=q, train=False,
                mutable=["cache"]),
            self._params, tokens, pos)
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            shapes["cache"])

    def cache_bytes(self) -> int:
        return sum(int(np.prod(x.shape)) * x.dtype.itemsize
                   for x in jax.tree.leaves(self._cache))

    # -- programs ----------------------------------------------------------
    def _note_compile(self, program: str) -> None:
        _COMPILES.labels(program=program).inc()
        with self._lock:
            self._compiles[program] = self._compiles.get(program, 0) + 1

    def compiles_total(self) -> int:
        with self._lock:
            return sum(self._compiles.values())

    def _prefill_impl(self, params, cache, tokens, prompt_len, slot):
        # batch-1 run over the padded prompt builds a fresh (1, max_seq)
        # cache (flax creates the zero cache inside the traced apply)...
        logits, mutated = self._model.apply(
            {"params": params}, tokens,
            positions=jnp.zeros((1,), jnp.int32), train=False,
            mutable=["cache"])
        # ...scattered into the slot row at a traced index, so every
        # prompt of this bucket reuses one program regardless of slot
        cache = jax.tree.map(
            lambda big, one: jax.lax.dynamic_update_index_in_dim(
                big, one[0], slot, axis=0), cache, mutated["cache"])
        last = jax.lax.dynamic_index_in_dim(
            logits[0], prompt_len - 1, axis=0, keepdims=False)
        return cache, jnp.argmax(last).astype(jnp.int32), \
            jnp.max(jnp.abs(last))

    def _decode_impl(self, params, cache, tokens, positions):
        logits, mutated = self._model.apply(
            {"params": params, "cache": cache}, tokens,
            positions=positions, train=False, mutable=["cache"])
        step_logits = logits[:, 0, :]
        return (mutated["cache"],
                jnp.argmax(step_logits, axis=-1).astype(jnp.int32),
                jnp.max(jnp.abs(step_logits), axis=-1))

    # -- serving ops -------------------------------------------------------
    def prefill(self, slot: int, prompt: List[int]) -> Tuple[int, float]:
        """Run the prompt through the bucketed prefill program, filling
        ``slot``'s cache rows. Returns (first generated token id,
        max |logit|) — the first token comes from prefill itself."""
        if not 0 < len(prompt) <= self.max_seq:
            # callers (ServeHandle.submit, Replica._reject) screen this
            # out; fail loudly rather than let the padded copy below
            # raise an opaque broadcast error inside a replica thread
            raise ValueError(
                f"prefill: prompt length {len(prompt)} outside "
                f"(0, max_seq={self.max_seq}]")
        bucket = prompt_bucket(len(prompt), self.max_seq)
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = jax.jit(self._prefill_impl)
            self._prefill_fns[bucket] = fn
            self._note_compile(f"prefill_{bucket}")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(prompt)] = prompt
        self._cache, token, max_abs = fn(
            self._params, self._cache, jnp.asarray(padded),
            jnp.int32(len(prompt)), jnp.int32(slot))
        return int(token), float(max_abs)

    def decode(self, slots: List[int], tokens: List[int],
               positions: List[int]) -> Tuple[List[int], List[float]]:
        """One decode step over ALL cache rows (fixed shape — the one
        compiled decode program). Active rows get their real token and
        position; inactive rows run token 0 at position 0, whose cache
        write lands where the next prefill overwrites it."""
        if not self._decode_compiled:
            self._decode_compiled = True
            self._note_compile("decode")
        step_tokens = np.zeros((self.num_slots, 1), np.int32)
        step_pos = np.zeros((self.num_slots,), np.int32)
        for s, t, p in zip(slots, tokens, positions):
            if p >= self.max_seq:
                # admission caps max_tokens so no write lands past the
                # cache (batcher.ActiveRequest); overrunning silently
                # would overwrite the last KV row and serve garbage
                raise ValueError(
                    f"decode: slot {s} position {p} >= max_seq "
                    f"{self.max_seq} (admission cap violated)")
            step_tokens[s, 0] = t
            step_pos[s] = p
        start = time.monotonic()
        self._cache, ids, max_abs = self._decode_fn(
            self._params, self._cache, jnp.asarray(step_tokens),
            jnp.asarray(step_pos))
        ids = np.asarray(ids)
        max_abs = np.asarray(max_abs)
        ms = (time.monotonic() - start) * 1000.0
        self.decode_steps += 1
        self.step_ms_ewma = (ms if self.decode_steps == 1
                             else 0.9 * self.step_ms_ewma + 0.1 * ms)
        return ([int(ids[s]) for s in slots],
                [float(max_abs[s]) for s in slots])

    def stats(self) -> dict:
        with self._lock:
            compiles = dict(self._compiles)
        return {"compiles": compiles,
                "compiles_total": sum(compiles.values()),
                "decode_steps": self.decode_steps,
                "decode_step_ms_ewma": round(self.step_ms_ewma, 3),
                "cache_bytes": self.cache_bytes(),
                "slots": self.num_slots}
