"""Paged KV-cache subsystem: block allocator + prefix reuse for serving.

The dense :class:`~horovod_tpu.serve.kv_cache.DecodeEngine` reserves
``max_seq`` cache rows per slot, so ``slots x max_seq`` bounds HBM no
matter how short requests actually run — vLLM's PagedAttention
observation is that most of that is never reached. This module replaces
the per-slot rows with a shared pool of fixed-size pages
(``HOROVOD_SERVE_PAGE_TOKENS`` tokens each, power of two):

* :class:`PagePool` — refcounted free-list allocator over
  ``HOROVOD_SERVE_PAGE_POOL`` physical pages. Page 0 is the reserved
  SCRATCH page: it is never allocated, pads every request's page table
  past its last real block, and absorbs the padded-prefill garbage
  writes — garbage in scratch is unattendable for the same reason stale
  dense rows are (``cached_attention`` masks ``key_pos <= q_pos``).
* :class:`PrefixCache` — rolling-hash chain over FULL prompt blocks
  plus exact-whole-prompt entries, mapping shared prefixes (system
  prompts) to refcounted pages. N requests sharing a prefill pay for it
  once; an exact repeat does ZERO prefill compute (the cached first
  token and max-|logit| replay). Divergence is copy-on-write: the first
  write into a page with refcount > 1 copies it (one jitted page-copy
  program, warmed at engine init).
* :class:`PagedDecodeEngine` — the drop-in engine behind
  ``HOROVOD_SERVE_PAGED=1``. Reads and writes go through gather/scatter
  at TRACED int32 page-table indices inside the one fixed-shape decode
  program, so growing a request appends a page id to a host-side table
  — zero steady-state compiles, token-for-token against the dense path
  (tests/test_paging.py pins parity across prompt buckets).

Admission moves from dense slots to free-page accounting in
``batcher.ContinuousBatcher`` (admit while the pool covers committed
``prompt+max_new`` pages, discounted by the candidate's current prefix
hits); on exhaustion the replica preempts the newest-admitted request
back to the queue FRONT with its pages reclaimed — the zero-lost
requeue invariant holds, and greedy decoding regenerates the dropped
prefix deterministically on resume.

Threading: a pool is touched by its replica thread, the memory
tracker's pull (``total_pool_bytes``) and ``/serve`` snapshots, so all
pool state is behind ``PagePool._lock``. Engine-level structures
(tables, prefix cache, program caches) are owned by the replica loop
thread, like the dense engine's.
"""

from __future__ import annotations

import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.serve import kv_cache as _kv
from horovod_tpu.serve.kv_cache import prompt_bucket
from horovod_tpu.utils.env import _get_int

HOROVOD_SERVE_PAGED = "HOROVOD_SERVE_PAGED"
HOROVOD_SERVE_PAGE_TOKENS = "HOROVOD_SERVE_PAGE_TOKENS"
HOROVOD_SERVE_PAGE_POOL = "HOROVOD_SERVE_PAGE_POOL"
HOROVOD_SERVE_PREFIX_CACHE = "HOROVOD_SERVE_PREFIX_CACHE"

DEFAULT_PAGE_TOKENS = 16
DEFAULT_PREFIX_ENTRIES = 256

_PAGE_POOL = _metrics().gauge(
    "horovod_serve_page_pool_pages",
    "Allocatable KV pages in the pool (scratch page excluded).",
    labelnames=("replica",))
_PAGE_FREE = _metrics().gauge(
    "horovod_serve_page_free_pages",
    "KV pages currently on the free list.",
    labelnames=("replica",))
_COW = _metrics().counter(
    "horovod_serve_page_cow_copies_total",
    "Copy-on-write page copies (first divergent write to a shared page).",
    labelnames=("replica",))
_PREFIX_HITS = _metrics().counter(
    "horovod_serve_page_prefix_hits_total",
    "Prefills that reused at least one cached prefix page.",
    labelnames=("replica",))
_PREFIX_TOKENS = _metrics().counter(
    "horovod_serve_page_prefix_tokens_total",
    "Prefill prompt tokens, by source (reused from cache / computed).",
    labelnames=("replica", "source"))
_PREEMPTIONS = _metrics().counter(
    "horovod_serve_page_preemptions_total",
    "Requests preempted back to the queue front on pool exhaustion.",
    labelnames=("replica",))

# every live paged engine, so the memory tracker's "kv_pages" subsystem
# can sum resident pool bytes without the serve plane pushing
_pools_lock = witness.make_lock("paging._pools_lock")
_pools: "weakref.WeakSet" = weakref.WeakSet()  # guarded-by: _pools_lock


def total_pool_bytes() -> int:
    """Resident page-pool bytes across every live paged engine on this
    process — the memory tracker's pull source for ``kv_pages``."""
    with _pools_lock:
        engines = list(_pools)
    return sum(e.cache_bytes() for e in engines)


class PagePoolExhausted(RuntimeError):
    """No free page and nothing reclaimable — the caller preempts."""


class PagePool:
    """Refcounted free-list allocator over fixed-size KV pages.

    ``pages`` counts PHYSICAL pages including the reserved scratch page
    0, which is never handed out — page ids returned by :meth:`alloc`
    are in ``[1, pages)``. A page is freed when its refcount reaches
    zero (requests, prefix-cache entries and exact entries each hold
    one ref per page). When the free list is empty, ``alloc`` invokes
    the reclaim hook (prefix-cache LRU eviction) until a page frees or
    nothing is left to evict.
    """

    def __init__(self, pages: int, page_tokens: int, name: str = "pool"):
        if pages < 2:
            raise ValueError(f"page pool needs >= 2 pages (1 scratch + "
                             f"1 allocatable), got {pages}")
        self.pages = int(pages)
        self.page_tokens = int(page_tokens)
        self.name = name
        self._lock = witness.make_lock("PagePool._lock")
        # highest page first so allocation order is deterministic
        self._free: List[int] = list(range(self.pages - 1, 0, -1))  # guarded-by: _lock
        self._refs: Dict[int, int] = {}        # guarded-by: _lock
        self._reclaim = None   # set once by the owning engine, pre-serving
        self.allocs = 0                        # guarded-by: _lock
        self.reclaims = 0                      # guarded-by: _lock

    @property
    def allocatable(self) -> int:
        return self.pages - 1

    def set_reclaim_hook(self, fn) -> None:
        self._reclaim = fn

    def alloc(self) -> int:
        """Take a free page at refcount 1; tries the reclaim hook before
        giving up. Raises :class:`PagePoolExhausted` when every page is
        pinned by a live request."""
        while True:
            with self._lock:
                if self._free:
                    page = self._free.pop()
                    self._refs[page] = 1
                    self.allocs += 1
                    return page
            # the hook evicts cache entries, which re-enters unref() —
            # so it must run outside _lock
            if self._reclaim is None or not self._reclaim():
                raise PagePoolExhausted(
                    f"{self.name}: all {self.allocatable} pages pinned")
            with self._lock:
                self.reclaims += 1

    def ref(self, page: int) -> None:
        with self._lock:
            if page not in self._refs:
                raise ValueError(f"ref of unallocated page {page}")
            self._refs[page] += 1

    def unref(self, page: int) -> bool:
        """Drop one ref; returns True when the page was freed."""
        with self._lock:
            count = self._refs.get(page)
            if count is None:
                raise ValueError(f"unref of unallocated page {page}")
            if count > 1:
                self._refs[page] = count - 1
                return False
            del self._refs[page]
            self._free.append(page)
            return True

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def used_count(self) -> int:
        with self._lock:
            return len(self._refs)

    def stats(self) -> dict:
        with self._lock:
            return {"pages": self.allocatable,
                    "page_tokens": self.page_tokens,
                    "free": len(self._free),
                    "used": len(self._refs),
                    "allocs": self.allocs,
                    "reclaims": self.reclaims}


class PrefixCache:
    """Token-prefix → page mapping for prefill reuse.

    Two entry kinds share one LRU order (single ``OrderedDict``):

    * BLOCK entries, keyed by ``(depth, rolling_hash)`` where the hash
      chains over full ``page_tokens`` blocks — a depth-``d`` hit is
      only reachable through hits at every shallower depth, so a match
      (verified against the stored block tokens, hash collisions are a
      miss) proves the whole prefix. The entry maps one FULL block to
      one refcounted page.
    * EXACT entries, keyed by the whole prompt tuple: all of the
      prompt's pages (partial tail page included) plus the prefill's
      first generated token and max-|logit| — a repeat prompt replays
      them with zero prefill compute. The tail page is shared, so the
      repeat's first decode write copy-on-writes it.

    Owned by the replica loop thread; page refcounts go through the
    (locked) pool. Evicting an entry drops its page refs — pages still
    referenced by live requests survive, the cache just forgets them.
    """

    def __init__(self, pool: PagePool, capacity: int):
        self.pool = pool
        self.capacity = int(capacity)
        self._entries: "OrderedDict" = OrderedDict()  # guarded-by: <replica-thread>
        self.hits = 0
        self.lookups = 0
        self.inserts = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _walk(self, prompt: List[int]):
        """Yield ``(depth, hash, block)`` for every FULL block; the hash
        chains so equal (depth, hash, block) implies equal prefix."""
        T = self.pool.page_tokens
        h = 0
        for depth in range(len(prompt) // T):
            block = tuple(prompt[depth * T:(depth + 1) * T])
            h = hash((h, block))
            yield depth, h, block

    def lookup(self, prompt: List[int]
               ) -> Tuple[List[int], Optional[Tuple[Tuple[int, ...], int, float]]]:
        """(longest-prefix hit pages, exact entry or None). Does NOT
        take refs — the caller refs what it keeps."""
        self.lookups += 1
        exact = self._entries.get(("x", tuple(prompt)))
        if exact is not None:
            self._entries.move_to_end(("x", tuple(prompt)))
            self.hits += 1
            return list(exact[0]), (exact[0], exact[1], exact[2])
        pages: List[int] = []
        for depth, h, block in self._walk(prompt):
            entry = self._entries.get(("b", depth, h))
            if entry is None or entry[1] != block:
                break
            self._entries.move_to_end(("b", depth, h))
            pages.append(entry[0])
        if pages:
            self.hits += 1
        return pages, None

    def probe(self, prompt: List[int]) -> int:
        """Full-block hit count WITHOUT touching LRU order or counters —
        the admission-time page-cost discount."""
        n = 0
        for depth, h, block in self._walk(prompt):
            entry = self._entries.get(("b", depth, h))
            if entry is None or entry[1] != block:
                break
            n += 1
        return n

    def insert(self, prompt: List[int], pages: List[int],
               first_token: int, max_abs: float) -> None:
        """Cache a finished prefill's pages (one ref per entry-page)."""
        if self.capacity <= 0:
            return
        for depth, h, block in self._walk(prompt):
            key = ("b", depth, h)
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            self.pool.ref(pages[depth])
            self._entries[key] = (pages[depth], block)
        key = ("x", tuple(prompt))
        if key in self._entries:
            self._entries.move_to_end(key)
        else:
            for p in pages:
                self.pool.ref(p)
            self._entries[key] = (tuple(pages), int(first_token),
                                  float(max_abs))
        self.inserts += 1
        while len(self._entries) > self.capacity:
            self._evict_lru()

    def _evict_lru(self) -> None:
        key, entry = self._entries.popitem(last=False)
        if key[0] == "b":
            self.pool.unref(entry[0])
        else:
            for p in entry[0]:
                self.pool.unref(p)
        self.evictions += 1

    def reclaim_one(self) -> bool:
        """Pool reclaim hook: evict LRU entries until one page actually
        frees (entries whose pages are still shared free nothing).
        Returns False once the cache is empty."""
        while self._entries:
            key, entry = self._entries.popitem(last=False)
            self.evictions += 1
            pages = (entry[0],) if key[0] == "b" else entry[0]
            freed = False
            for p in pages:
                freed |= self.pool.unref(p)
            if freed:
                return True
        return False

    def held_pages(self) -> set:
        held = set()
        for key, entry in self._entries.items():
            if key[0] == "b":
                held.add(entry[0])
            else:
                held.update(entry[0])
        return held

    def release_all(self) -> None:
        while self._entries:
            self._evict_lru()

    def stats(self) -> dict:
        return {"entries": len(self._entries), "capacity": self.capacity,
                "lookups": self.lookups, "hits": self.hits,
                "inserts": self.inserts, "evictions": self.evictions}


def auto_pool_pages(num_slots: int, max_seq: int, page_tokens: int) -> int:
    """Default pool size (physical pages, scratch included): half the
    dense engine's ``slots x max_seq`` token capacity — the paged bench
    must show >= 2x lower KV bytes at equal occupancy — floored so one
    worst-case request (``max_seq`` tokens) always fits."""
    max_blocks = -(-max_seq // page_tokens)
    return max(max_blocks + 1, num_slots * max_seq // (2 * page_tokens))


class PagedDecodeEngine:
    """Pool-paged drop-in for :class:`~horovod_tpu.serve.kv_cache.
    DecodeEngine` (``HOROVOD_SERVE_PAGED=1``).

    Same program discipline as dense — ONE fixed-shape decode program
    over all slots, one prefill program per suffix-length bucket, plus
    one page-copy program (COW), warmed at init — but the cache is
    ``(pool_pages, page_tokens, heads, head_dim)`` per layer and every
    read/write indirects through per-slot int32 page tables passed as
    traced arguments. Page tables live host-side (``_tables``) and as a
    ``(slots, max_blocks+1)`` array whose padding entries point at
    scratch page 0.

    The replica loop calls :meth:`prepare_step` before each decode step
    to grow tables across block boundaries and copy-on-write shared
    pages; both can raise :class:`PagePoolExhausted`, which the replica
    answers by preempting the newest-admitted request. ``decode`` also
    calls it internally so direct callers (bench warmup, tests) can
    never corrupt a shared page.
    """

    paged = True

    def __init__(self, model, params, num_slots: int, name: str = "r0",
                 page_tokens: Optional[int] = None,
                 pool_pages: Optional[int] = None,
                 prefix_entries: Optional[int] = None):
        if not getattr(model, "causal", True):
            raise ValueError("hvd.serve() needs a causal (decoder) model")
        self.name = name
        self.num_slots = int(num_slots)
        self.max_seq = int(model.max_seq)
        self.vocab_size = int(model.vocab_size)
        T = int(_get_int(HOROVOD_SERVE_PAGE_TOKENS, DEFAULT_PAGE_TOKENS)
                if page_tokens is None else page_tokens)
        if T < 1 or (T & (T - 1)):
            raise ValueError(
                f"{HOROVOD_SERVE_PAGE_TOKENS} must be a power of two, "
                f"got {T}")
        self.page_tokens = T
        self.max_blocks = -(-self.max_seq // T)
        self.table_width = self.max_blocks + 1   # last entry: scratch pad
        pages = int(_get_int(HOROVOD_SERVE_PAGE_POOL, 0)
                    if pool_pages is None else pool_pages)
        if pages <= 0:
            pages = auto_pool_pages(self.num_slots, self.max_seq, T)
        if pages - 1 < self.max_blocks:
            raise ValueError(
                f"{HOROVOD_SERVE_PAGE_POOL}={pages} cannot hold one "
                f"max_seq={self.max_seq} request "
                f"({self.max_blocks} pages of {T} tokens + scratch)")
        self.pool = PagePool(pages, T, name=f"{name}.pool")
        entries = int(_get_int(HOROVOD_SERVE_PREFIX_CACHE,
                               DEFAULT_PREFIX_ENTRIES)
                      if prefix_entries is None else prefix_entries)
        self.prefix = PrefixCache(self.pool, entries) if entries > 0 else None
        if self.prefix is not None:
            self.pool.set_reclaim_hook(self.prefix.reclaim_one)

        self._params = params
        self._model = model.clone(decode=True, paged=True,
                                  num_pages=pages, page_tokens=T,
                                  remat=False, attention_fn=None)
        self._cache = self._allocate_cache()
        self._prefill_fns: Dict[int, object] = {}  # guarded-by: <replica-thread>
        self._decode_fn = jax.jit(self._decode_impl)
        self._decode_compiled = False
        self._copy_fn = jax.jit(self._copy_impl)
        self._lock = witness.make_lock("PagedDecodeEngine._lock")
        self._compiles: Dict[str, int] = {}      # guarded-by: _lock
        # per-slot page tables + token high-water marks (replica thread)
        self._tables: List[List[int]] = [[] for _ in range(self.num_slots)]
        self._table_arr = np.zeros((self.num_slots, self.table_width),
                                   np.int32)
        self._lengths = [0] * self.num_slots
        self.decode_steps = 0
        self.step_ms_ewma = 0.0
        self.cow_copies = 0
        self.preemptions = 0
        self.exact_hits = 0
        self.reused_tokens = 0
        self.computed_tokens = 0
        # warm the COW program now (a self-copy of scratch is a no-op)
        # so the first real divergence never compiles mid-steady-state
        self._cache = self._copy_fn(self._cache, jnp.int32(0), jnp.int32(0))
        self._note_compile("page_copy")
        with _pools_lock:
            _pools.add(self)
        _PAGE_POOL.labels(replica=self.name).set(self.pool.allocatable)
        _PAGE_FREE.labels(replica=self.name).set(self.pool.free_count())

    # -- cache -------------------------------------------------------------
    def _allocate_cache(self):
        tokens = jnp.zeros((self.num_slots, 1), jnp.int32)
        pos = jnp.zeros((self.num_slots,), jnp.int32)
        table = jnp.zeros((self.num_slots, self.table_width), jnp.int32)
        _, shapes = jax.eval_shape(
            lambda p, t, q, pt: self._model.apply(
                {"params": p}, t, positions=q, page_table=pt,
                train=False, mutable=["cache"]),
            self._params, tokens, pos, table)
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            shapes["cache"])

    def cache_bytes(self) -> int:
        return sum(int(np.prod(x.shape)) * x.dtype.itemsize
                   for x in jax.tree.leaves(self._cache))

    # -- programs ----------------------------------------------------------
    def _note_compile(self, program: str) -> None:
        _kv._COMPILES.labels(program=program).inc()
        with self._lock:
            self._compiles[program] = self._compiles.get(program, 0) + 1

    def compiles_total(self) -> int:
        with self._lock:
            return sum(self._compiles.values())

    def _copy_impl(self, cache, src, dst):
        return jax.tree.map(lambda a: a.at[dst].set(a[src]), cache)

    def _prefill_impl(self, params, cache, tokens, start, rel_last, table):
        # the suffix runs through the SAME paged path as decode, just
        # with new_tokens > 1 and batch 1: scatter into this request's
        # pages at traced table indices, attend the whole mapped prefix
        logits, mutated = self._model.apply(
            {"params": params, "cache": cache}, tokens,
            positions=jnp.reshape(start, (1,)), page_table=table,
            train=False, mutable=["cache"])
        last = jax.lax.dynamic_index_in_dim(
            logits[0], rel_last, axis=0, keepdims=False)
        return mutated["cache"], jnp.argmax(last).astype(jnp.int32), \
            jnp.max(jnp.abs(last))

    def _decode_impl(self, params, cache, tokens, positions, table):
        logits, mutated = self._model.apply(
            {"params": params, "cache": cache}, tokens,
            positions=positions, page_table=table, train=False,
            mutable=["cache"])
        step_logits = logits[:, 0, :]
        return (mutated["cache"],
                jnp.argmax(step_logits, axis=-1).astype(jnp.int32),
                jnp.max(jnp.abs(step_logits), axis=-1))

    # -- page bookkeeping --------------------------------------------------
    def _set_table(self, slot: int, pages: List[int], length: int) -> None:
        self._tables[slot] = list(pages)
        row = self._table_arr[slot]
        row[:] = 0
        row[:len(pages)] = pages
        self._lengths[slot] = length

    def release_slot(self, slot: int) -> None:
        """Drop the slot's page refs (retire/preempt/re-prefill). Pages
        shared with the prefix cache survive under the cache's refs."""
        for page in self._tables[slot]:
            self.pool.unref(page)
        self._tables[slot] = []
        self._table_arr[slot, :] = 0
        self._lengths[slot] = 0
        _PAGE_FREE.labels(replica=self.name).set(self.pool.free_count())

    def release_all(self) -> None:
        """Quarantine/eviction path: every request-held page goes back.
        The chaos cell (tests/test_paging.py) pins request_held == 0
        after this, the pool-leak analogue of ``leases == 0``."""
        for slot in range(self.num_slots):
            if self._tables[slot]:
                self.release_slot(slot)

    def probe_prefix(self, prompt: List[int]) -> int:
        """Admission-time page discount: FULL blocks currently cached
        for this prompt. Capped so the recompute-last-block rule (see
        :meth:`prefill`) never discounts a page prefill must allocate."""
        if self.prefix is None:
            return 0
        cap = (len(prompt) - 1) // self.page_tokens
        return min(self.prefix.probe(prompt), cap)

    def prepare_step(self, slots: List[int], positions: List[int]) -> None:
        """Make every row's next write position ownable: grow the table
        across a block boundary (alloc+append) and copy-on-write shared
        pages. Idempotent — a retry after preemption re-checks cheaply.
        Raises :class:`PagePoolExhausted` when the pool cannot cover
        it; partial allocations stay (they are this request's pages and
        survive to the retry)."""
        T = self.page_tokens
        for slot, pos in zip(slots, positions):
            if pos >= self.max_seq:
                continue   # decode() raises the admission-cap error
            blk = pos // T
            table = self._tables[slot]
            while blk >= len(table):
                page = self.pool.alloc()   # may raise: caller preempts
                table.append(page)
                self._table_arr[slot, len(table) - 1] = page
            page = table[blk]
            if self.pool.refcount(page) > 1:
                fresh = self.pool.alloc()  # may raise: caller preempts
                self._cache = self._copy_fn(self._cache, jnp.int32(page),
                                            jnp.int32(fresh))
                self.pool.unref(page)
                table[blk] = fresh
                self._table_arr[slot, blk] = fresh
                self.cow_copies += 1
                _COW.labels(replica=self.name).inc()
        _PAGE_FREE.labels(replica=self.name).set(self.pool.free_count())

    def note_preemption(self) -> None:
        self.preemptions += 1
        _PREEMPTIONS.labels(replica=self.name).inc()

    # -- serving ops -------------------------------------------------------
    def prefill(self, slot: int, prompt: List[int]) -> Tuple[int, float]:
        """Paged prefill: reuse every cached full-prefix block, compute
        only the suffix (bucketed program, batch 1, traced start), and
        cache the result for the next sharer. An exact repeat replays
        the cached first token with zero prefill compute."""
        if not 0 < len(prompt) <= self.max_seq:
            raise ValueError(
                f"prefill: prompt length {len(prompt)} outside "
                f"(0, max_seq={self.max_seq}]")
        T = self.page_tokens
        self.release_slot(slot)   # re-prefill frees the previous occupant
        if self.prefix is not None:
            hit_pages, exact = self.prefix.lookup(prompt)
        else:
            hit_pages, exact = [], None
        if exact is not None:
            pages, token, max_abs = exact
            for p in pages:
                self.pool.ref(p)
            self._set_table(slot, list(pages), len(prompt))
            self.exact_hits += 1
            self.reused_tokens += len(prompt)
            _PREFIX_HITS.labels(replica=self.name).inc()
            _PREFIX_TOKENS.labels(replica=self.name,
                                  source="reused").inc(len(prompt))
            return int(token), float(max_abs)

        # at least the LAST prompt token must be recomputed (its logits
        # produce the first generated token), and the suffix prefill
        # writes its blocks — so a full-block hit covering the whole
        # prompt drops its last block and recomputes it into a fresh
        # page (identical values: greedy + same prefix)
        hit_tokens = min(len(hit_pages) * T, ((len(prompt) - 1) // T) * T)
        hit_pages = hit_pages[:hit_tokens // T]
        needed = -(-len(prompt) // T)
        taken: List[int] = []
        try:
            for p in hit_pages:
                self.pool.ref(p)
                taken.append(p)
            while len(taken) < needed:
                taken.append(self.pool.alloc())
        except PagePoolExhausted:
            for p in taken:     # roll back — admission retries after
                self.pool.unref(p)   # the replica preempts a victim
            raise
        suffix = prompt[hit_tokens:]
        bucket = prompt_bucket(len(suffix), self.max_seq)
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = jax.jit(self._prefill_impl)
            self._prefill_fns[bucket] = fn
            self._note_compile(f"prefill_{bucket}")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(suffix)] = suffix
        row = np.zeros((1, self.table_width), np.int32)
        row[0, :needed] = taken
        self._cache, token, max_abs = fn(
            self._params, self._cache, jnp.asarray(padded),
            jnp.int32(hit_tokens), jnp.int32(len(suffix) - 1),
            jnp.asarray(row))
        self._set_table(slot, taken, len(prompt))
        if hit_pages:
            _PREFIX_HITS.labels(replica=self.name).inc()
        self.reused_tokens += hit_tokens
        self.computed_tokens += len(suffix)
        _PREFIX_TOKENS.labels(replica=self.name,
                              source="reused").inc(hit_tokens)
        _PREFIX_TOKENS.labels(replica=self.name,
                              source="computed").inc(len(suffix))
        if self.prefix is not None:
            self.prefix.insert(prompt, taken, int(token), float(max_abs))
        _PAGE_FREE.labels(replica=self.name).set(self.pool.free_count())
        return int(token), float(max_abs)

    def decode(self, slots: List[int], tokens: List[int],
               positions: List[int]) -> Tuple[List[int], List[float]]:
        """One decode step over ALL slots through the one paged program.
        Runs :meth:`prepare_step` first so every write position owns
        its page — direct callers get the same COW safety the replica
        loop's explicit prepare/preempt cycle provides."""
        self.prepare_step(slots, positions)
        if not self._decode_compiled:
            self._decode_compiled = True
            self._note_compile("decode")
        step_tokens = np.zeros((self.num_slots, 1), np.int32)
        step_pos = np.zeros((self.num_slots,), np.int32)
        # inactive rows still run (fixed shape) and write garbage KV at
        # position 0 — in the dense engine that lands in the slot's own
        # row, but here a mapped table would scribble on its block-0
        # page, which may be SHARED with the prefix cache or another
        # request. Zeroed rows route the write to the scratch page,
        # which is only ever gathered at masked key positions.
        step_table = np.zeros_like(self._table_arr)
        for s, t, p in zip(slots, tokens, positions):
            if p >= self.max_seq:
                raise ValueError(
                    f"decode: slot {s} position {p} >= max_seq "
                    f"{self.max_seq} (admission cap violated)")
            step_tokens[s, 0] = t
            step_pos[s] = p
            step_table[s] = self._table_arr[s]
        start = time.monotonic()
        self._cache, ids, max_abs = self._decode_fn(
            self._params, self._cache, jnp.asarray(step_tokens),
            jnp.asarray(step_pos), jnp.asarray(step_table))
        ids = np.asarray(ids)
        max_abs = np.asarray(max_abs)
        ms = (time.monotonic() - start) * 1000.0
        self.decode_steps += 1
        self.step_ms_ewma = (ms if self.decode_steps == 1
                             else 0.9 * self.step_ms_ewma + 0.1 * ms)
        for s, p in zip(slots, positions):
            self._lengths[s] = max(self._lengths[s], p + 1)
        return ([int(ids[s]) for s in slots],
                [float(max_abs[s]) for s in slots])

    # -- introspection -----------------------------------------------------
    def page_stats(self) -> dict:
        """Pool occupancy split by holder, utilization and (internal)
        fragmentation — the ``/serve`` page-pool fields and the flight
        recorder's postmortem view of the pool at death."""
        request_held = set()
        held_tokens = 0
        for slot in range(self.num_slots):
            request_held.update(self._tables[slot])
            held_tokens += self._lengths[slot]
        prefix_held = (self.prefix.held_pages()
                       if self.prefix is not None else set())
        pool = self.pool.stats()
        T = self.page_tokens
        req_pages = len(request_held)
        # internal fragmentation: allocated token rows the requests
        # mapping them have not (yet) filled
        frag = (1.0 - held_tokens / (req_pages * T)) if req_pages else 0.0
        return {
            **pool,
            "utilization": round(pool["used"] / max(pool["pages"], 1), 3),
            "fragmentation": round(max(frag, 0.0), 3),
            "request_held": req_pages,
            "prefix_held": len(prefix_held),
            "shared": len(request_held & prefix_held),
            "cow_copies": self.cow_copies,
            "preemptions": self.preemptions,
            "exact_hits": self.exact_hits,
            "reused_tokens": self.reused_tokens,
            "computed_tokens": self.computed_tokens,
            "prefix_hit_rate": self.prefix_hit_rate(),
            "prefix": (self.prefix.stats()
                       if self.prefix is not None else None),
        }

    def prefix_hit_rate(self) -> float:
        """Token-weighted prefill reuse: cached tokens / prompt tokens."""
        total = self.reused_tokens + self.computed_tokens
        return round(self.reused_tokens / total, 4) if total else 0.0

    def stats(self) -> dict:
        with self._lock:
            compiles = dict(self._compiles)
        return {"compiles": compiles,
                "compiles_total": sum(compiles.values()),
                "decode_steps": self.decode_steps,
                "decode_step_ms_ewma": round(self.step_ms_ewma, 3),
                "cache_bytes": self.cache_bytes(),
                "slots": self.num_slots,
                "pages": self.page_stats()}
