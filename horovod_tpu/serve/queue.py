"""Shared request queue for the serving plane.

Two transports behind one contract:

* :class:`RequestQueue` — in-memory, single-controller. ``hvd.serve()``
  threads (replicas) and caller threads (submitters) share it inside one
  process; it is also the reference semantics the unit tests pin down.
* :class:`KVQueueFrontend` / :class:`KVQueueReplica` — the cross-process
  transport over the rendezvous HTTP KV store (run/rendezvous.py), used
  by ``tpurun --serve`` worker fleets and the chaos matrix. The store
  has no atomic claim op, so the frontend is the single dispatcher: it
  round-robins requests into per-rank scopes, watches per-rank
  heartbeat keys, and re-dispatches the un-answered requests of a dead
  replica to survivors (responses are deduplicated by request id, so a
  reply that raced the death detection is harmless).

The zero-lost-requests invariant both transports uphold: a request
leaves the system only by completing. Pulling moves it to an in-flight
set tagged with the puller's rank; worker loss moves that rank's
in-flight requests back to the FRONT of the waiting line
(:meth:`RequestQueue.requeue_worker`), oldest first, so a re-dispatched
request does not also lose its queue position.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu import flight_recorder, tracing
from horovod_tpu.analysis import witness
from horovod_tpu.utils.env import _get_float

# rendezvous scopes of the cross-process transport
REQ_SCOPE = "serve.req.{rank}"   # per-replica inbox: key=uid, val=request
RESP_SCOPE = "serve.resp"        # key=uid, val=completion
HB_SCOPE = "serve.hb"            # key=str(rank), TTL-listed for liveness
CTL_SCOPE = "serve.ctl"          # "stop" key drains the fleet

# a replica heartbeats ~4x faster than the frontend declares it dead.
# Replicas beat from a dedicated thread (replica._KVTransport), NOT the
# serve loop, so a multi-second blocking step (first-request XLA
# compiles, large prefills) cannot lapse a healthy replica's liveness.
HEARTBEAT_SECONDS = 0.5
STALE_SECONDS = 2.0

# completed results are held for late readers, then evicted — a serving
# process must not leak memory proportional to total requests served
HOROVOD_SERVE_RESULT_TTL_S = "HOROVOD_SERVE_RESULT_TTL_S"
RESULT_TTL_SECONDS = 600.0


class QueueFull(RuntimeError):
    """Admission refused: the queue is at HOROVOD_SERVE_QUEUE_CAPACITY."""


@dataclasses.dataclass
class Request:
    """One generation request. ``submitted_s`` is the submitter's local
    monotonic clock (latency accounting happens where the clock lives).
    ``trace_id`` is the distributed trace context (tracing.py): minted
    once at submit, it rides the wire format through every transport hop
    so spans on the frontend and on whichever replica(s) serve the
    request join into one Perfetto flow. ``requeues`` counts how many
    times worker loss bounced the request back into the waiting line."""

    uid: str
    prompt: List[int]
    max_new_tokens: int
    submitted_s: float = 0.0
    trace_id: str = ""
    requeues: int = 0

    def to_json(self) -> bytes:
        return json.dumps({"uid": self.uid, "prompt": list(self.prompt),
                           "max_new_tokens": self.max_new_tokens,
                           "trace_id": self.trace_id,
                           "requeues": self.requeues}).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Request":
        d = json.loads(raw)
        return cls(uid=d["uid"], prompt=[int(t) for t in d["prompt"]],
                   max_new_tokens=int(d["max_new_tokens"]),
                   trace_id=d.get("trace_id", ""),
                   requeues=int(d.get("requeues", 0)))


@dataclasses.dataclass
class Completion:
    """A finished request: generated ids + where/how it ran."""

    uid: str
    tokens: List[int]
    prompt_len: int
    rank: int
    ttft_s: float = 0.0      # submit -> first generated token
    latency_s: float = 0.0   # submit -> completion
    finish: str = "length"
    trace_id: str = ""       # trace context echoed back to the submitter
    requeues: int = 0

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Completion":
        d = json.loads(raw)
        return cls(uid=d["uid"], tokens=[int(t) for t in d["tokens"]],
                   prompt_len=int(d["prompt_len"]), rank=int(d["rank"]),
                   ttft_s=float(d.get("ttft_s", 0.0)),
                   latency_s=float(d.get("latency_s", 0.0)),
                   finish=d.get("finish", "length"),
                   trace_id=d.get("trace_id", ""),
                   requeues=int(d.get("requeues", 0)))


class RequestQueue:
    """In-process shared queue: waiting deque + per-rank in-flight map +
    completed results, one lock. No call blocks under the lock — waiters
    poll (:meth:`result`) with short sleeps outside it."""

    def __init__(self, capacity: int = 1024,
                 result_ttl: Optional[float] = None):
        self._lock = witness.make_lock("RequestQueue._lock")
        self._capacity = capacity
        self._result_ttl = (
            _get_float(HOROVOD_SERVE_RESULT_TTL_S, RESULT_TTL_SECONDS)
            if result_ttl is None else result_ttl)
        self._waiting: deque = deque()           # guarded-by: _lock
        self._inflight: Dict[str, Tuple[int, Request]] = {}  # guarded-by: _lock
        self._results: Dict[str, Completion] = {}  # guarded-by: _lock
        self._expiry: deque = deque()            # (deadline, uid); guarded-by: _lock
        self._submitted = 0                      # guarded-by: _lock
        self._completed = 0                      # guarded-by: _lock
        self._requeued = 0                       # guarded-by: _lock

    def submit(self, prompt: List[int], max_new_tokens: int,
               uid: Optional[str] = None, trace_id: str = "") -> str:
        t0 = time.time()
        req = Request(uid=uid or uuid.uuid4().hex, prompt=list(prompt),
                      max_new_tokens=int(max_new_tokens),
                      submitted_s=time.monotonic(),
                      trace_id=trace_id or tracing.new_trace_id())
        with self._lock:
            if len(self._waiting) >= self._capacity:
                raise QueueFull(
                    f"serve queue at capacity ({self._capacity})")
            self._waiting.append(req)
            self._submitted += 1
        tracing.record("request.submit", t0, time.time() - t0,
                       trace_id=req.trace_id, uid=req.uid,
                       prompt_len=len(req.prompt))
        return req.uid

    def pull(self, rank: int, max_n: int) -> List[Request]:
        """Hand up to ``max_n`` waiting requests to replica ``rank``;
        they stay in-flight (charged to that rank) until completed or
        requeued."""
        out: List[Request] = []
        with self._lock:
            while self._waiting and len(out) < max_n:
                req = self._waiting.popleft()
                self._inflight[req.uid] = (rank, req)
                out.append(req)
        return out

    def complete(self, completion: Completion) -> None:
        t0 = time.time()
        now = time.monotonic()
        with self._lock:
            self._inflight.pop(completion.uid, None)
            # first writer wins: a requeued duplicate that also finished
            # must not overwrite the reply the caller already saw
            if completion.uid not in self._results:
                self._results[completion.uid] = completion
                self._expiry.append((now + self._result_ttl,
                                     completion.uid))
                self._completed += 1
            # evict results older than the TTL (amortized on the write
            # path) — without this a long-running serving process leaks
            # one Completion per request ever served
            while self._expiry and self._expiry[0][0] <= now:
                _, uid = self._expiry.popleft()
                self._results.pop(uid, None)
        tracing.record("request.response", t0, time.time() - t0,
                       trace_id=completion.trace_id, uid=completion.uid,
                       finish=completion.finish)

    def requeue_worker(self, rank: int) -> int:
        """Return every request in-flight on ``rank`` to the FRONT of
        the waiting line (oldest first). The no-request-lost half of
        worker loss; called by the serve loop on ``WorkersDownError``,
        quarantine, or replica death."""
        with self._lock:
            stranded = [(uid, req) for uid, (r, req)
                        in self._inflight.items() if r == rank]
            for uid, req in sorted(stranded,
                                   key=lambda kv: kv[1].submitted_s,
                                   reverse=True):
                del self._inflight[uid]
                req.requeues += 1
                self._waiting.appendleft(req)
            self._requeued += len(stranded)
            return len(stranded)

    def result(self, uid: str, timeout: Optional[float] = None
               ) -> Completion:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                done = self._results.get(uid)
            if done is not None:
                return done
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"request {uid} not completed "
                                   f"within {timeout}s")
            time.sleep(0.002)

    def try_result(self, uid: str) -> Optional[Completion]:
        with self._lock:
            return self._results.get(uid)

    def depth(self) -> int:
        with self._lock:
            return len(self._waiting)

    def stats(self) -> dict:
        with self._lock:
            return {"waiting": len(self._waiting),
                    "inflight": len(self._inflight),
                    "completed": self._completed,
                    "results_held": len(self._results),
                    "submitted": self._submitted,
                    "requeued": self._requeued}


class KVQueueReplica:
    """Replica-side view of the KV transport: poll the per-rank inbox,
    publish completions, heartbeat, honor the stop key. Single-owner
    (the replica loop thread) — no lock needed."""

    def __init__(self, client, rank: int):
        self._client = client            # KVStoreClient, any scope
        self._rank = rank
        self._scope = REQ_SCOPE.format(rank=rank)
        self._taken: set = set()         # guarded-by: <replica-thread>

    def heartbeat(self) -> None:
        self._client.set(str(self._rank), b"1", scope=HB_SCOPE)

    def poll(self, max_n: int) -> List[Request]:
        out: List[Request] = []
        try:
            keys = self._client.keys(scope=self._scope)
        except Exception:
            return out
        # taken keys leave the inbox listing when complete() finishes
        # them — prune the memo so it tracks the inbox, not all history
        self._taken.intersection_update(keys)
        for key in keys:
            if key in self._taken or len(out) >= max_n:
                continue
            try:
                raw = self._client.get(key, scope=self._scope, wait=False)
            except KeyError:
                continue
            self._taken.add(key)
            req = Request.from_json(raw)
            req.submitted_s = time.monotonic()  # replica-local clock
            out.append(req)
        return out

    def complete(self, completion: Completion) -> None:
        self._client.set(completion.uid, completion.to_json(),
                         scope=RESP_SCOPE)
        try:  # shrink the inbox listing; liveness only, never correctness
            self._client.finish(completion.uid, scope=self._scope)
        except Exception:
            pass

    def stopped(self) -> bool:
        try:
            self._client.get("stop", scope=CTL_SCOPE, wait=False)
            return True
        except Exception:
            return False


class KVQueueFrontend:
    """Dispatcher side of the KV transport (runs in the load generator /
    ``hvd.serve`` controller process). Single-owner thread."""

    # dedup memory for late zombie replies: completions already consumed
    # and finished server-side; bounded so a long-running frontend does
    # not leak one Completion per request ever served
    _DONE_MAX = 65536

    def __init__(self, client, stale_seconds: float = STALE_SECONDS):
        self._client = client
        self._stale = stale_seconds
        self._rr = itertools.count()
        # guarded-by: <frontend-thread>
        self._assigned: Dict[str, Tuple[int, Request]] = {}
        self._done: Dict[str, Completion] = {}
        self._done_order: deque = deque()
        self.requeued = 0
        self.dead_ranks: set = set()

    def live_replicas(self) -> List[int]:
        try:
            keys = self._client.keys(scope=HB_SCOPE, ttl=self._stale)
        except Exception:
            return []
        return sorted(int(k) for k in keys if k.isdigit())

    def wait_for_replicas(self, n: int, timeout: float = 60.0) -> List[int]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            live = self.live_replicas()
            if len(live) >= n:
                return live
            time.sleep(0.1)
        raise TimeoutError(f"{n} serve replicas not up within {timeout}s")

    def submit(self, request: Request,
               rank: Optional[int] = None) -> int:
        """Dispatch to ``rank`` (or round-robin over live replicas).
        Mints the trace context if the caller didn't — the span covers
        the KV put, i.e. the frontend→replica wire hop."""
        if not request.trace_id:
            request.trace_id = tracing.new_trace_id()
        t0 = time.time()
        if rank is None:
            live = self.live_replicas()
            if not live:
                raise RuntimeError("no live serve replicas")
            rank = live[next(self._rr) % len(live)]
        self._client.set(request.uid, request.to_json(),
                         scope=REQ_SCOPE.format(rank=rank))
        self._assigned[request.uid] = (rank, request)
        tracing.record("request.submit", t0, time.time() - t0,
                       trace_id=request.trace_id, uid=request.uid,
                       prompt_len=len(request.prompt), to_rank=rank)
        return rank

    def _redispatch_dead(self) -> None:
        live = set(self.live_replicas())
        if not live:
            return
        # _assigned holds only unanswered requests (poll_responses drops
        # an entry the moment its completion is consumed)
        for uid, (rank, req) in list(self._assigned.items()):
            if rank in live:
                continue
            self.dead_ranks.add(rank)
            self.requeued += 1
            req.requeues += 1
            new_rank = self.submit(req)
            flight_recorder.emit(
                "serve_redispatch", uid=uid, trace_id=req.trace_id,
                dead_rank=rank, new_rank=new_rank,
                requeues=req.requeues)

    def poll_responses(self) -> List[Completion]:
        """Drain newly-published completions; re-dispatches the pending
        requests of any replica whose heartbeat went stale."""
        fresh: List[Completion] = []
        try:
            keys = self._client.keys(scope=RESP_SCOPE)
        except Exception:
            keys = []
        for key in keys:
            if key in self._done:
                continue
            t0 = time.time()
            try:
                raw = self._client.get(key, scope=RESP_SCOPE, wait=False)
            except KeyError:
                continue
            done = Completion.from_json(raw)
            self._done[key] = done   # dedup: first reply wins
            self._done_order.append(key)
            self._assigned.pop(key, None)
            fresh.append(done)
            tracing.record("request.response", t0, time.time() - t0,
                           trace_id=done.trace_id, uid=done.uid,
                           from_rank=done.rank, finish=done.finish)
            try:  # shrink the response listing; liveness only
                self._client.finish(key, scope=RESP_SCOPE)
            except Exception:
                pass
        while len(self._done) > self._DONE_MAX:
            self._done.pop(self._done_order.popleft(), None)
        self._redispatch_dead()
        return fresh

    def pending(self) -> int:
        return len(self._assigned)

    def stop_fleet(self) -> None:
        self._client.set("stop", b"1", scope=CTL_SCOPE)
