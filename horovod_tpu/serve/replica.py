"""The per-replica serving loop: pull → admit/prefill → decode → retire.

One :class:`Replica` drives one :class:`~horovod_tpu.serve.kv_cache.
DecodeEngine` and one :class:`~horovod_tpu.serve.batcher.
ContinuousBatcher` on a single thread. The loop each iteration:

1. pulls new requests from the shared queue (in-process or KV-backed,
   behind a small transport adapter) into the batcher's waiting line;
2. when admission is due (decode-block boundary, idle replica, or the
   admission deadline — batcher.py has the policy), prefills admitted
   prompts through the bucketed prefill programs; the first generated
   token falls out of prefill, so TTFT is measured here;
3. runs ONE fixed-shape decode step over all slots and retires finished
   rows iteration-level (a retiring request frees its slot for the very
   next admission check, not a batch boundary).

Reliability wiring (the serve plane rides the existing stack):

* ``fault_inject.maybe_inject`` fires per DECODE step (the serving
  analogue of the training step counter), so the chaos matrix can kill
  a replica mid-generation;
* a PR-10 :class:`~horovod_tpu.integrity.guards.StepGuard` watches the
  per-step max-|logit|; a non-finite value (or an exhausted guard)
  QUARANTINES the replica — it returns every pulled request to the
  queue, stops heartbeating so the dispatcher reassigns, and parks,
  rather than serving garbage;
* a :class:`~horovod_tpu.exceptions.WorkersDownError` escaping the step
  (a model whose forward uses collectives under elastic) requeues the
  in-flight work the same way before re-raising to the elastic driver.
"""

from __future__ import annotations

import math
import threading
import time
from typing import List, Optional

from horovod_tpu import flight_recorder, goodput, tracing
from horovod_tpu.elastic import fault_inject
from horovod_tpu.exceptions import NumericalError, WorkersDownError
from horovod_tpu.metrics import COUNT_BUCKETS, registry as _metrics
from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.kv_cache import DecodeEngine
from horovod_tpu.serve.paging import PagePoolExhausted
from horovod_tpu.serve.queue import (Completion, KVQueueReplica,
                                     RequestQueue, HEARTBEAT_SECONDS)
from horovod_tpu.utils import logging as log

_IDLE_SLEEP_SECONDS = 0.002

_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

_REQUESTS = _metrics().counter(
    "horovod_serve_requests_total",
    "Serving requests, by outcome (completed/requeued).",
    labelnames=("outcome",))
_TOKENS = _metrics().counter(
    "horovod_serve_tokens_total",
    "Tokens processed by the serving plane, by kind (prefill/decode).",
    labelnames=("kind",))
_OCCUPANCY = _metrics().gauge(
    "horovod_serve_batch_occupancy",
    "Active requests in the continuous batch, per replica.",
    labelnames=("replica",))
_QUEUE_DEPTH = _metrics().gauge(
    "horovod_serve_queue_depth",
    "Requests waiting for admission (queue + batcher), per replica.",
    labelnames=("replica",))
_OCCUPANCY_HIST = _metrics().histogram(
    "horovod_serve_batch_occupancy_steps",
    "Batch occupancy observed at each decode step.",
    buckets=COUNT_BUCKETS)
_LATENCY = _metrics().histogram(
    "horovod_serve_latency_seconds",
    "Request latency by phase: ttft (submit to first token) and total.",
    buckets=_LATENCY_BUCKETS, labelnames=("phase",))
_QUARANTINED = _metrics().counter(
    "horovod_serve_quarantined_total",
    "Replicas quarantined by the serving integrity guard.")


class _LocalTransport:
    """In-process adapter over the shared :class:`RequestQueue`."""

    def __init__(self, queue: RequestQueue, rank: int):
        self._queue = queue
        self._rank = rank

    def pull(self, max_n):
        return self._queue.pull(self._rank, max_n)

    def complete(self, completion):
        self._queue.complete(completion)

    def requeue_all(self) -> int:
        return self._queue.requeue_worker(self._rank)

    def heartbeat(self):
        pass

    def stopped(self) -> bool:
        return False

    def depth(self) -> int:
        return self._queue.depth()


class _KVTransport:
    """Cross-process adapter over the rendezvous-KV queue. Requeueing is
    the DISPATCHER's job in this transport (it owns assignment): on
    quarantine the replica just goes silent — its heartbeat lapses and
    the frontend redistributes everything unanswered.

    Heartbeats come from a dedicated daemon thread, NOT the serve loop:
    a blocking step longer than STALE_SECONDS (first-request XLA
    prefill/decode compiles routinely take many seconds) must not make
    the frontend declare a healthy replica dead and re-dispatch its
    pending work. The thread only writes one KV key; ``silent`` and the
    stop event are its whole shared state (single-word flags, read-only
    here, set by the replica thread).

    The serve loop spins at millisecond cadence; every KV op is an HTTP
    round trip, so the inbox poll and the stop-key check are throttled —
    an idle replica costs the rendezvous server ~60 requests/s, not
    ~1500."""

    _POLL_SECONDS = 0.02
    _STOP_CHECK_SECONDS = 0.25

    def __init__(self, kv: KVQueueReplica):
        self._kv = kv
        self._last_poll = 0.0
        self._last_stop_check = 0.0
        self._stopped = False
        self.silent = False          # set by replica thread on quarantine
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="serve-heartbeat")
        self._hb_thread.start()

    def _heartbeat_loop(self) -> None:
        while True:
            if not self.silent:
                try:
                    self._kv.heartbeat()
                    tracing.note_replica_heartbeat()
                except Exception as exc:
                    log.warning("serve: heartbeat failed: %s", exc)
            if self._hb_stop.wait(HEARTBEAT_SECONDS):
                return

    def pull(self, max_n):
        now = time.monotonic()
        if now - self._last_poll < self._POLL_SECONDS:
            return []
        self._last_poll = now
        return self._kv.poll(max_n)

    def complete(self, completion):
        self._kv.complete(completion)

    def requeue_all(self) -> int:
        self.silent = True
        return 0

    def heartbeat(self):
        pass   # the dedicated thread owns liveness

    def shutdown(self) -> None:
        """Stop heartbeating for good (replica drained or crashed) so
        the frontend does not keep dispatching to a gone replica."""
        self._hb_stop.set()
        self._hb_thread.join(timeout=2 * HEARTBEAT_SECONDS)

    def stopped(self) -> bool:
        if self._stopped:
            return True
        now = time.monotonic()
        if now - self._last_stop_check < self._STOP_CHECK_SECONDS:
            return False
        self._last_stop_check = now
        self._stopped = self._kv.stopped()
        return self._stopped

    def depth(self) -> int:
        return 0


class Replica:
    """One serving replica; ``run()`` is the loop, single thread."""

    def __init__(self, engine: DecodeEngine, transport, policy, rank: int = 0,
                 name: Optional[str] = None, guard=None):
        self.engine = engine
        self.transport = transport
        self.policy = policy
        self.rank = rank
        self.name = name or f"serve-r{rank}"
        # paged engines (serve/paging.py) switch admission from dense
        # slot rows to free-page accounting: the batcher commits pool
        # pages, discounted by the candidate's current prefix hits
        self.paged = bool(getattr(engine, "paged", False))
        self.batcher = ContinuousBatcher(
            num_slots=engine.num_slots,
            max_batch_tokens=policy.max_batch_tokens,
            admission_ms=policy.admission_ms,
            decode_block=policy.decode_block,
            max_seq=engine.max_seq,
            page_tokens=engine.page_tokens if self.paged else None,
            pool_pages=engine.pool.allocatable if self.paged else None,
            prefix_probe=engine.probe_prefix if self.paged else None)
        self.guard = guard
        self.quarantined = False
        self.completed = 0
        self.decode_iterations = 0
        self.occupancy_sum = 0
        self.page_used_sum = 0   # pool pages in use, summed per step
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def stop(self) -> None:
        self._stop.set()

    def _finish(self, active, now: float) -> None:
        req = active.request
        epoch_now = time.time()
        if active.block_steps > 0:   # close the trailing decode block
            tracing.record(
                "request.decode_block", active.block_t0,
                max(epoch_now - active.block_t0, 0.0),
                trace_id=req.trace_id, uid=req.uid, slot=active.slot,
                block=active.blocks, tokens=active.block_steps)
        # "cache_limit" (not "length") when the KV cache, not the
        # request, bounded the generation — callers must be able to
        # tell a fulfilled budget from a truncated one
        completion = Completion(
            uid=req.uid, tokens=list(active.generated),
            prompt_len=active.prompt_len, rank=self.rank,
            ttft_s=active.first_token_s - req.submitted_s,
            latency_s=now - req.submitted_s,
            finish="cache_limit" if active.capped else "length",
            trace_id=req.trace_id, requeues=req.requeues)
        self.transport.complete(completion)
        self.completed += 1
        _REQUESTS.labels(outcome="completed").inc()
        _LATENCY.labels(phase="total").observe(completion.latency_s)
        serve_dur = max(now - active.admitted_s, 0.0)
        tracing.record(
            "request.serve", epoch_now - serve_dur, serve_dur,
            trace_id=req.trace_id, uid=req.uid, slot=active.slot,
            finish=completion.finish, requeues=req.requeues,
            tokens=len(active.generated),
            ttft_ms=round(completion.ttft_s * 1000.0, 3),
            latency_ms=round(completion.latency_s * 1000.0, 3))
        tracing.slo().record_request(
            completion.ttft_s, completion.latency_s, ok=True,
            trace_id=req.trace_id, rank=self.rank, requeues=req.requeues,
            phases={"queue_wait": active.queue_wait_s,
                    "prefill": active.prefill_s,
                    "decode": max(now - active.first_token_s, 0.0)})

    def _reject(self, req, reason: str) -> None:
        """Complete an unservable request (empty, or prompt longer than
        the KV cache) with ``finish="rejected"`` instead of crashing the
        loop on it or stranding its caller in ``result()``."""
        self.transport.complete(Completion(
            uid=req.uid, tokens=[], prompt_len=len(req.prompt),
            rank=self.rank, finish="rejected",
            trace_id=req.trace_id, requeues=req.requeues))
        _REQUESTS.labels(outcome="rejected").inc()
        # an unserved request is an availability bad event — it has no
        # meaningful TTFT, so the latency objectives are not scored
        tracing.slo().record_request(
            0.0, 0.0, ok=False, trace_id=req.trace_id, rank=self.rank,
            requeues=req.requeues)
        log.warning("serve: replica %s rejected request %s (%s)",
                    self.name, req.uid, reason)

    def _quarantine(self, reason: str) -> None:
        """Integrity trip: never serve garbage. Active + waiting work
        goes back to the queue (in-process) or to the dispatcher's
        death-detection (KV: the heartbeat just stops); the replica
        parks until the fleet is stopped."""
        self.quarantined = True
        _QUARANTINED.inc()
        victims = self.batcher.evict_all()
        victims += self.batcher.drain_waiting()
        if self.paged:
            # a dead replica must not pin pool pages: every request-held
            # page goes back (the chaos cell pins request_held == 0)
            self.engine.release_all()
        evicted = len(victims)
        requeued = self.transport.requeue_all()
        _REQUESTS.labels(outcome="requeued").inc(max(evicted, requeued))
        flight_recorder.emit("serve_quarantine", replica=self.name,
                             rank=self.rank, reason=reason,
                             evicted=evicted,
                             trace_ids=[r.trace_id for r in victims])
        log.error("serve: replica %s QUARANTINED (%s); %d request(s) "
                  "returned for redistribution", self.name, reason,
                  max(evicted, requeued))

    def _preempt_for_pages(self, exclude_slot=None) -> bool:
        """Page-pool exhaustion (paged engines): bounce the newest-
        admitted request back to the queue FRONT and reclaim its pages.
        Returns False when there is no other victim to take."""
        victim = self.batcher.preempt_newest(exclude_slot=exclude_slot)
        if victim is None:
            return False
        self.engine.release_slot(victim.slot)
        self.engine.note_preemption()
        _REQUESTS.labels(outcome="preempted").inc()
        # goodput ledger: the victim's decoded-so-far tokens are work the
        # preemption threw away — re-attributed from productive to
        # serve_preempted badput at the EWMA per-token decode cost
        goodput.note_serve_preempted(len(victim.generated))
        flight_recorder.emit(
            "serve_preempt", replica=self.name, rank=self.rank,
            uid=victim.request.uid, slot=victim.slot,
            trace_id=victim.request.trace_id,
            generated=len(victim.generated),
            requeues=victim.request.requeues)
        log.warning("serve: replica %s preempted request %s (pool "
                    "exhausted); requeued at front", self.name,
                    victim.request.uid)
        return True

    def _guard_ok(self, max_abs: float) -> bool:
        """Non-finite logits always quarantine; the spike guard's EWMA
        feeds the same decision once its skip budget is spent."""
        if not math.isfinite(max_abs):
            return False
        if self.guard is not None:
            try:
                self.guard.observe(max_abs)
            except NumericalError:
                return False
        return True

    # -- the loop ----------------------------------------------------------
    def run(self) -> None:
        flight_recorder.emit("serve_replica_start", replica=self.name,
                             rank=self.rank, slots=self.engine.num_slots)
        # a running loop IS the liveness signal for in-process serving
        # (the KV transport's heartbeat thread also notes it) — flips
        # the /healthz readiness gate
        tracing.note_replica_heartbeat()
        while not self._stop.is_set():
            self.transport.heartbeat()
            if self.transport.stopped():
                break
            if self.quarantined:
                time.sleep(0.05)
                continue
            try:
                self._iterate()
            except WorkersDownError:
                # elastic membership change mid-step: nothing is lost —
                # the pulled work returns to the queue before the
                # elastic driver re-forms us
                victims = self.batcher.evict_all()
                victims += self.batcher.drain_waiting()
                if self.paged:
                    self.engine.release_all()
                requeued = self.transport.requeue_all()
                requeued += len(victims)
                flight_recorder.emit(
                    "serve_requeue", replica=self.name, rank=self.rank,
                    requeued=requeued,
                    trace_ids=[r.trace_id for r in victims])
                raise
            except Exception as exc:
                # anything else must not silently kill the loop thread
                # and strand its in-flight callers — quarantine instead
                # (which requeues active + waiting work for the other
                # replicas / the dispatcher first)
                log.error("serve: replica %s loop error: %r",
                          self.name, exc)
                self._quarantine(f"loop error: {exc!r}")
        flight_recorder.emit("serve_replica_stop", replica=self.name,
                             rank=self.rank, completed=self.completed)

    def _iterate(self) -> None:
        now = time.monotonic()
        free = self.engine.num_slots - self.batcher.occupancy()
        if free > 0 or self.batcher.waiting() == 0:
            for req in self.transport.pull(max(free, 1)):
                # unservable prompts answer immediately — an oversized
                # prompt must never reach prefill (where it would blow
                # up the padded copy) or circulate in requeue forever
                if not req.prompt:
                    self._reject(req, "empty prompt")
                elif len(req.prompt) > self.engine.max_seq:
                    self._reject(
                        req, f"prompt length {len(req.prompt)} > "
                             f"max_seq {self.engine.max_seq}")
                else:
                    self.batcher.offer(req, now)
        _QUEUE_DEPTH.labels(replica=self.name).set(
            self.batcher.waiting() + self.transport.depth())

        if self.batcher.admission_due(now):
            for active in self.batcher.admit(now):
                req = active.request
                # queue-wait span: submitted -> admitted. submitted_s is
                # a LOCAL monotonic stamp; map it onto the epoch trace
                # clock by anchoring "now" and subtracting the wait.
                p0 = time.time()
                active.queue_wait_s = max(
                    active.admitted_s - req.submitted_s, 0.0)
                tracing.record(
                    "request.queue_wait", p0 - active.queue_wait_s,
                    active.queue_wait_s, trace_id=req.trace_id,
                    uid=req.uid, requeues=req.requeues)
                token = None
                while True:
                    try:
                        token, max_abs = self.engine.prefill(
                            active.slot, req.prompt)
                        break
                    except PagePoolExhausted:
                        # prefill rolled its partial allocations back;
                        # preempt the newest OTHER request and retry.
                        # With nothing left to preempt, the admission
                        # itself bounces back to the queue front (its
                        # prefix-hit discount was optimistic)
                        if not self._preempt_for_pages(
                                exclude_slot=active.slot):
                            self.batcher.preempt_slot(active.slot)
                            self.engine.note_preemption()
                            _REQUESTS.labels(outcome="preempted").inc()
                            break
                if token is None:
                    continue
                if not self._guard_ok(max_abs):
                    self._quarantine("non-finite prefill logits")
                    return
                active.generated.append(token)
                active.first_token_s = time.monotonic()
                active.prefill_s = time.time() - p0
                tracing.record(
                    "request.prefill", p0, active.prefill_s,
                    trace_id=req.trace_id, uid=req.uid,
                    slot=active.slot, prompt_len=active.prompt_len)
                # prefill is productive serve time too (tokens=0: the
                # preemption exchange rate stays a pure decode cost)
                goodput.record_serve_step(active.prefill_s)
                # open the first decode-block span
                active.block_t0 = p0 + active.prefill_s
                _TOKENS.labels(kind="prefill").inc(active.prompt_len)
                _LATENCY.labels(phase="ttft").observe(
                    active.first_token_s - active.request.submitted_s)
            for done in self.batcher.retire_done():  # max_new_tokens == 1
                if self.paged:
                    self.engine.release_slot(done.slot)
                self._finish(done, time.monotonic())

        slots, tokens, positions = self.batcher.batch_rows()
        if not slots:
            _OCCUPANCY.labels(replica=self.name).set(0)
            time.sleep(_IDLE_SLEEP_SECONDS)
            # goodput ledger: an empty loop iteration is queue-idle badput
            goodput.record_span("serve_queue_idle", _IDLE_SLEEP_SECONDS)
            return

        if self.paged:
            # grow tables across block boundaries / COW shared pages
            # BEFORE the step; exhaustion preempts newest-admitted until
            # the survivors fit (admission guarantees a sole request
            # always does)
            while True:
                try:
                    self.engine.prepare_step(slots, positions)
                    break
                except PagePoolExhausted:
                    if not self._preempt_for_pages():
                        raise   # nothing left to shed: quarantine path
                    slots, tokens, positions = self.batcher.batch_rows()
                    if not slots:
                        _OCCUPANCY.labels(replica=self.name).set(0)
                        return

        # the serving step counter: chaos kills aim at decode step N
        self.decode_iterations += 1
        fault_inject.maybe_inject(self.decode_iterations)
        t_decode0 = time.monotonic()
        ids, max_abs = self.engine.decode(slots, tokens, positions)
        # no short-circuit: the guard's EWMA/skip-budget state must see
        # EVERY slot's observation, not a prefix that stops at the
        # first failing slot
        verdicts = [self._guard_ok(m) for m in max_abs]
        if not all(verdicts):
            self._quarantine("non-finite decode logits")
            return
        by_slot = {a.slot: a for a in self.batcher.active()}
        for slot, token in zip(slots, ids):
            active = by_slot[slot]
            active.generated.append(token)
            active.position += 1
            active.block_steps += 1
            if active.block_steps >= self.policy.decode_block:
                # decode-block boundary: close this request's span and
                # open the next (one time.time() per block, not per step)
                t1 = time.time()
                tracing.record(
                    "request.decode_block", active.block_t0,
                    max(t1 - active.block_t0, 0.0),
                    trace_id=active.request.trace_id,
                    uid=active.request.uid, slot=slot,
                    block=active.blocks, tokens=active.block_steps)
                active.blocks += 1
                active.block_t0 = t1
                active.block_steps = 0
        occupancy = len(slots)
        self.occupancy_sum += occupancy
        if self.paged:
            self.page_used_sum += self.engine.pool.used_count()
        _TOKENS.labels(kind="decode").inc(occupancy)
        _OCCUPANCY.labels(replica=self.name).set(occupancy)
        _OCCUPANCY_HIST.observe(occupancy)
        self.batcher.note_step()
        now = time.monotonic()
        # goodput ledger: one decoded token per occupied slot is the
        # serve plane's productive unit; the step wall also refreshes the
        # EWMA per-token cost that prices preempted work
        goodput.record_serve_step(now - t_decode0, tokens=occupancy)
        for done in self.batcher.retire_done():
            if self.paged:
                self.engine.release_slot(done.slot)
            self._finish(done, now)

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        steps = max(self.engine.decode_steps, 1)
        out = {"name": self.name, "rank": self.rank,
               "quarantined": self.quarantined,
               "completed": self.completed,
               "active": self.batcher.occupancy(),
               "waiting": self.batcher.waiting(),
               "decode_steps": self.engine.decode_steps,
               "avg_occupancy": round(self.occupancy_sum / steps, 3),
               # memory plane: resident KV bytes + the slot-occupancy-
               # weighted share of the cache that did useful work
               "kv_cache_bytes": self.engine.cache_bytes(),
               "kv_utilization": round(
                   self.occupancy_sum
                   / (steps * max(self.engine.num_slots, 1)), 3),
               "engine": self.engine.stats()}
        if self.paged:
            # pool view for /serve and hvd_top's pages row: live pool
            # stats plus the per-decode-step average occupancy
            out["pages"] = self.engine.page_stats()
            out["page_utilization"] = round(
                self.page_used_sum
                / (steps * max(self.engine.pool.allocatable, 1)), 3)
            out["prefix_hit_rate"] = self.engine.prefix_hit_rate()
            out["preemptions"] = self.engine.preemptions
        return out


def run_kv_replica(model, params, policy, rank: int, addr: str, port: int,
                   guard=None) -> Replica:
    """Blocking entrypoint for a cross-process replica (``tpurun
    --serve`` workers, the chaos matrix): serve from the rendezvous KV
    queue until the frontend publishes the stop key."""
    from horovod_tpu.run.rendezvous import KVStoreClient

    client = KVStoreClient(addr, port, scope="serve", timeout=10.0)
    if getattr(policy, "paged", False):
        from horovod_tpu.serve.paging import PagedDecodeEngine

        engine = PagedDecodeEngine(
            model, params, num_slots=policy.slots, name=f"r{rank}",
            page_tokens=policy.page_tokens, pool_pages=policy.page_pool,
            prefix_entries=policy.prefix_cache)
    else:
        engine = DecodeEngine(model, params, num_slots=policy.slots,
                              name=f"r{rank}")
    # the transport's heartbeat thread starts beating here, BEFORE the
    # first (slow, compiling) prefill can run — registration is not
    # gated on the serve loop being responsive
    transport = _KVTransport(KVQueueReplica(client, rank))
    replica = Replica(engine, transport, policy, rank=rank, guard=guard)
    try:
        replica.run()
    finally:
        # stop advertising liveness once we are no longer serving
        transport.shutdown()
    return replica
