"""Spark integration: run a training function on Spark executors.

TPU-native rebuild of the reference's ``horovod.spark.run`` (reference:
horovod/spark/__init__.py:100): one Spark task per rank; tasks register
with a driver TCP service (HMAC-keyed Wire protocol, reference:
run/common/util/network.py:50-84), the driver computes the
rank/local/cross allocation from registered host hashes — barrel-shifted
so rank 0 lands on the first host (reference: spark/__init__.py:180-188) —
and hands each task its worker environment; tasks run ``fn`` under that
environment and ship results back, which are returned ordered by rank
(reference: spark/__init__.py:226-233).

Where the reference tunnels ``mpirun``/orted through Spark task services
(reference: spark/driver/mpirun_rsh.py), the TPU build needs no process
tree: Spark's python workers *are* the ranks, and the collectives ride the
framework's socket controller + XLA data plane directly.

Requires pyspark (an optional dependency — importing this module without it
raises only when ``run`` is called).
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from horovod_tpu.run import hosts as hosts_mod
from horovod_tpu.run import util
from horovod_tpu.run.rendezvous import RendezvousServer
from horovod_tpu.run.service import (
    BasicService,
    ErrorResponse,
    OkResponse,
    ServiceClient,
)

_POLL_S = 0.5


@dataclasses.dataclass
class RegisterSparkTaskRequest:
    index: int
    host_hash: str
    ip: str
    # a free TCP port probed on the TASK's host — the coordinator must
    # bind on rank 0's machine, so the driver cannot probe it
    coord_port: int = 0


@dataclasses.dataclass
class SparkTaskInfoRequest:
    index: int


@dataclasses.dataclass
class SparkTaskInfoResponse:
    env: Optional[Dict[str, str]]  # None until all tasks registered


@dataclasses.dataclass
class SparkResultRequest:
    index: int
    ok: bool
    payload: str  # base64 cloudpickle of result or exception text


class SparkDriverService(BasicService):
    """Driver-side registry: task registration, slot allocation, results
    (reference: spark/driver/driver_service.py)."""

    def __init__(self, key: bytes, num_proc: int):
        super().__init__(key)
        self._num_proc = num_proc
        # idx -> (host_hash, ip, coord_port)
        self._registered: Dict[int, Tuple[str, str, int]] = {}
        self._task_env: Dict[int, Dict[str, str]] = {}
        self._results: Dict[int, Tuple[bool, str]] = {}
        self._frozen = False  # set once ranks are allocated
        self._lock = threading.Lock()
        self.all_registered = threading.Event()
        self.all_results = threading.Event()

    def _handle(self, req):
        if isinstance(req, RegisterSparkTaskRequest):
            with self._lock:
                if self._frozen:
                    # A Spark task retry (speculation / executor loss)
                    # arriving after allocation would silently join with a
                    # stale environment and corrupt the rank layout —
                    # fail it (and thereby the job) loudly instead.
                    return ErrorResponse(
                        f"task index {req.index} re-registered after the "
                        "rank allocation was fixed; Spark retried a "
                        "failed task — the whole job must be restarted")
                # before allocation a retry may harmlessly re-register
                # (last registration wins — its host is the real one)
                self._registered[req.index] = (req.host_hash, req.ip,
                                               req.coord_port)
                if len(self._registered) == self._num_proc:
                    self.all_registered.set()
            return OkResponse()
        if isinstance(req, SparkTaskInfoRequest):
            with self._lock:
                return SparkTaskInfoResponse(self._task_env.get(req.index))
        if isinstance(req, SparkResultRequest):
            with self._lock:
                self._results[req.index] = (req.ok, req.payload)
                if len(self._results) == self._num_proc:
                    self.all_results.set()
            return OkResponse()
        return super()._handle(req)

    # -- allocation ------------------------------------------------------

    def allocate(self, extra_env: Dict[str, str]) -> Dict[int, int]:
        """Assign ranks to registered tasks; fill ``_task_env``; return
        index→rank. Hosts are ordered with the first-registered host first
        so rank 0 lands there (the reference's barrel shift,
        spark/__init__.py:180-188)."""
        with self._lock:
            registered = dict(self._registered)
            self._frozen = True

        by_host: Dict[str, List[int]] = {}
        host_order: List[str] = []
        for index in sorted(registered):
            h, _, _ = registered[index]
            if h not in by_host:
                by_host[h] = []
                host_order.append(h)
            by_host[h].append(index)

        infos = [hosts_mod.HostInfo(h, len(by_host[h])) for h in host_order]
        slots = hosts_mod.allocate(infos, sum(i.slots for i in infos))

        # rank 0's routable IP hosts the socket coordinator, on a port the
        # rank-0 TASK probed free on its own machine
        first_host = slots[0].hostname
        rank0_index = by_host[first_host][0]
        coord_ip = registered[rank0_index][1]
        coord_port = registered[rank0_index][2] or _free_port_hint()

        index_to_rank: Dict[int, int] = {}
        taken: Dict[str, int] = {h: 0 for h in by_host}
        for slot in slots:
            index = by_host[slot.hostname][taken[slot.hostname]]
            taken[slot.hostname] += 1
            index_to_rank[index] = slot.rank
            env = dict(extra_env)
            env.update(slot.to_env())
            env["HOROVOD_HOSTNAME"] = slot.hostname
            env.update({
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_CPU_OPERATIONS": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": coord_ip,
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(coord_port),
            })
            with self._lock:
                self._task_env[index] = env
        return index_to_rank

    def results(self) -> Dict[int, Tuple[bool, str]]:
        with self._lock:
            return dict(self._results)


def _free_port_hint() -> int:
    """A currently-free TCP port number (best effort — rank 0 binds it on
    its own host moments later)."""
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _my_ip(driver_addr: Tuple[str, int]) -> str:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((driver_addr[0], driver_addr[1] or 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def _make_mapper(driver_addrs, key, fn, args, kwargs, start_timeout):
    """The function each Spark task runs (reference:
    spark/__init__.py:35-75 _task_fn)."""

    def _mapper(index, _iterator):
        client = ServiceClient(driver_addrs[0], key)
        client.call(RegisterSparkTaskRequest(
            index, util.host_hash(), _my_ip(driver_addrs[0]),
            _free_port_hint()))
        timeout = util.Timeout(start_timeout,
                               "spark task waiting for allocation")
        while True:
            info = client.call(SparkTaskInfoRequest(index))
            if info.env is not None:
                break
            timeout.check()
            time.sleep(_POLL_S)

        os.environ.update(info.env)
        try:
            result = fn(*args, **(kwargs or {}))
            client.call(SparkResultRequest(
                index, True, util.dumps_base64(result)))
        except BaseException as e:  # report, then re-raise into Spark
            client.call(SparkResultRequest(index, False, repr(e)))
            raise
        yield 0

    return _mapper


def run(fn, args=(), kwargs=None, num_proc: Optional[int] = None,
        start_timeout: float = 600.0, extra_env: Optional[Dict] = None,
        verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks as one training job; returns
    the per-rank results ordered by rank (reference:
    horovod/spark/__init__.py:100-233).

    ``fn`` runs inside each Spark python worker with the framework's
    launcher environment set; it typically calls ``hvd.init()`` and trains.
    """
    try:
        import pyspark  # noqa: F401
        from pyspark import SparkContext
    except ImportError as e:
        raise RuntimeError(
            "horovod_tpu.spark.run requires pyspark "
            "(pip install pyspark)") from e

    sc = SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create a SparkSession "
                           "before calling horovod_tpu.spark.run")
    if num_proc is None:
        num_proc = sc.defaultParallelism
    if verbose:
        print(f"Running {num_proc} processes...")

    key = util.make_secret_key()
    driver = SparkDriverService(key, num_proc)
    rendezvous = RendezvousServer()
    http_port = rendezvous.start()
    driver_ip = _driver_ip(sc)
    driver_addrs = [(driver_ip, driver.port)]

    base_env = dict(extra_env or {})
    base_env.update({
        "HOROVOD_RENDEZVOUS_HTTP_ADDR": driver_ip,
        "HOROVOD_RENDEZVOUS_HTTP_PORT": str(http_port),
        "HOROVOD_NP": str(num_proc),
    })

    mapper = _make_mapper(driver_addrs, key, fn, args, kwargs, start_timeout)
    result_holder: Dict[str, Any] = {}

    def _submit():
        try:
            sc.parallelize(range(num_proc), num_proc) \
                .mapPartitionsWithIndex(mapper).collect()
        except BaseException as e:
            result_holder["error"] = e

    job = threading.Thread(target=_submit, daemon=True)
    job.start()
    try:
        timeout = util.Timeout(
            start_timeout,
            f"waiting for {num_proc} Spark tasks to register. Check that "
            f"the cluster has at least {num_proc} task slots")
        while not driver.all_registered.is_set():
            if "error" in result_holder:
                raise result_holder["error"]
            timeout.check()
            driver.all_registered.wait(_POLL_S)

        index_to_rank = driver.allocate(base_env)
        while not driver.all_results.is_set():
            if "error" in result_holder:
                raise result_holder["error"]
            driver.all_results.wait(_POLL_S)
        job.join(timeout=60)

        results = driver.results()
        failures = {i: p for i, (ok, p) in results.items() if not ok}
        if failures:
            raise RuntimeError(
                "spark tasks failed: "
                + "; ".join(f"rank {index_to_rank[i]}: {p}"
                            for i, p in sorted(failures.items())))
        ordered = sorted(results, key=lambda i: index_to_rank[i])
        return [util.loads_base64(results[i][1]) for i in ordered]
    finally:
        rendezvous.stop()
        driver.shutdown()


def _driver_ip(sc) -> str:
    host = sc.getConf().get("spark.driver.host", None)
    if host and host not in ("localhost", "127.0.0.1"):
        return host
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
