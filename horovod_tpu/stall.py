"""Stall inspector: detect workers that stopped submitting tensors.

TPU-native analogue of the reference's ``StallInspector`` (reference:
horovod/common/stall_inspector.cc/.h): on the coordinator, periodically
scan the negotiation table for tensors announced by some-but-not-all
workers; log a WARNING naming the ready and missing ranks
(stall_inspector.cc:26-110); if a tensor stays stalled longer than the
shutdown threshold, trigger a global shutdown so the job fails fast instead
of hanging (wired into the controller cycle as in controller.cc:98-107).

Knobs (reference: common.h:78-80): ``HOROVOD_STALL_CHECK_DISABLE``,
``HOROVOD_STALL_CHECK_TIME_SECONDS`` (default 60),
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS`` (default 0 = never shut down).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from horovod_tpu.utils import logging as log


class StallInspector:
    def __init__(self, warning_time_seconds: float = 60.0,
                 shutdown_time_seconds: float = 0.0,
                 enabled: bool = True):
        self.warning_time = warning_time_seconds
        self.shutdown_time = shutdown_time_seconds
        self.enabled = enabled
        self._last_check = time.monotonic()
        # tensor name -> first time observed incomplete
        self._first_seen: Dict[str, float] = {}

    def check(self, message_table, cache=None, world: Optional[int] = None
              ) -> bool:
        """Scan for stalled tensors; returns True if a stall exceeded the
        shutdown threshold (reference: CheckForStalledTensors,
        stall_inspector.cc:26-110)."""
        if not self.enabled:
            return False
        now = time.monotonic()
        if now - self._last_check < self.warning_time:
            return False
        self._last_check = now

        pending = message_table.pending()
        stalled_msgs = []
        shutdown = False
        seen_names = set()
        for name, requests in pending.items():
            seen_names.add(name)
            first = self._first_seen.setdefault(name, now)
            age = now - first
            if age < self.warning_time:
                continue
            ready = sorted(r.rank for r in requests)
            missing = ([] if world is None else
                       sorted(set(range(world)) - set(ready)))
            stalled_msgs.append(
                f"{name} [ready ranks: {ready}"
                + (f", missing ranks: {missing}]" if missing else "]"))
            # NOTE: stalled *cached* tensors re-enter negotiation through
            # the controller's synchronized STALE_HIT invalidation protocol
            # (controller.py) — invalidating the coordinator's cache here
            # directly would desynchronize cache bits across workers.
            if self.shutdown_time > 0 and age > self.shutdown_time:
                shutdown = True

        # forget tensors that completed since last scan
        self._first_seen = {k: v for k, v in self._first_seen.items()
                            if k in seen_names}

        if stalled_msgs:
            log.warning(
                "One or more tensors were submitted to be reduced, gathered "
                "or broadcasted by subset of ranks and are waiting for "
                "remainder of ranks for more than %.0f seconds. This may "
                "indicate that different ranks are trying to submit "
                "different tensors or that only subset of ranks is "
                "submitting tensors. Stalled ops: %s",
                self.warning_time, "; ".join(stalled_msgs))
        if shutdown:
            log.error(
                "Stalled tensors exceeded HOROVOD_STALL_SHUTDOWN_TIME_"
                "SECONDS (%.0fs); shutting down.", self.shutdown_time)
        return shutdown
