"""Stall inspector: detect workers that stopped submitting tensors.

TPU-native analogue of the reference's ``StallInspector`` (reference:
horovod/common/stall_inspector.cc/.h): on the coordinator, periodically
scan the negotiation table for tensors announced by some-but-not-all
workers; log a WARNING naming the ready and missing ranks
(stall_inspector.cc:26-110); if a tensor stays stalled longer than the
shutdown threshold, trigger a global shutdown so the job fails fast instead
of hanging (wired into the controller cycle as in controller.cc:98-107).

Knobs (reference: common.h:78-80): ``HOROVOD_STALL_CHECK_DISABLE``,
``HOROVOD_STALL_CHECK_TIME_SECONDS`` (default 60),
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS`` (default 0 = never shut down).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu import flight_recorder
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log

_STALL_WARNINGS = _metrics().counter(
    "horovod_stall_warnings_total",
    "Tensors reported stalled by the stall inspector (one per tensor per "
    "warning scan).")
_STALL_SHUTDOWNS = _metrics().counter(
    "horovod_stall_shutdowns_total",
    "Stall scans that exceeded HOROVOD_STALL_SHUTDOWN_TIME_SECONDS and "
    "triggered a global shutdown.")
_STRAGGLER_LAG = _metrics().gauge(
    "horovod_straggler_lag_seconds",
    "Per-rank negotiation lateness EWMA on the coordinator: how long "
    "after the first announcing rank this rank's request arrives, "
    "smoothed across negotiations.", labelnames=("rank",))
_NEGOTIATE_SKEW = _metrics().histogram(
    "horovod_negotiate_skew_seconds",
    "Cross-rank arrival skew (last minus first announcement) per "
    "completed negotiation on the coordinator.")


class StragglerTracker:
    """Coordinator-side straggler attribution from per-rank arrival
    timestamps carried by the negotiation message table.

    Every completed negotiation yields one arrival map
    ``{rank: monotonic_time}``; from it the tracker feeds the cross-rank
    skew histogram, a per-rank lateness EWMA gauge
    (``horovod_straggler_lag_seconds{rank=...}``), and a periodic log
    report naming the consistently-last ranks — the live half of the
    attribution whose postmortem half is the flight recorder. Arrival
    resolution is one controller cycle (a fast rank and a slightly-fast
    rank that announce in the same cycle read as simultaneous); a real
    straggler lags by many cycles and dominates the EWMA."""

    def __init__(self, world: int, alpha: float = 0.2,
                 report_seconds: float = 60.0):
        self.world = world
        self.alpha = alpha
        self.report_seconds = report_seconds
        self.lag_ewma: Dict[int, float] = {}
        self.last_counts: Dict[int, int] = {}
        self.samples = 0
        self._last_report = time.monotonic()

    def observe(self, name: str, arrivals: Dict[int, float]) -> None:
        if not arrivals:
            return
        t_first = min(arrivals.values())
        skew = max(arrivals.values()) - t_first
        _NEGOTIATE_SKEW.observe(skew)
        if skew > 0:
            try:
                # goodput ledger: the arrival skew is how long the
                # fastest rank's tensor sat waiting for the last one —
                # straggler badput on the coordinator's ledger
                from horovod_tpu import goodput

                goodput.record_span("straggler_wait", skew)
            except Exception:
                pass
        for rank, t in arrivals.items():
            lag = t - t_first
            prev = self.lag_ewma.get(rank)
            ewma = lag if prev is None else prev + self.alpha * (lag - prev)
            self.lag_ewma[rank] = ewma
            _STRAGGLER_LAG.labels(rank=rank).set(ewma)
        if skew > 0:
            last_rank = max(arrivals, key=lambda r: arrivals[r])
            self.last_counts[last_rank] = \
                self.last_counts.get(last_rank, 0) + 1
        self.samples += 1
        self.maybe_report()

    def ranking(self) -> List[Tuple[int, float]]:
        return sorted(self.lag_ewma.items(), key=lambda kv: -kv[1])

    def lag_summary(self, ranks=None) -> str:
        items = self.ranking()
        if ranks:
            wanted = [kv for kv in items if kv[0] in set(ranks)]
            items = wanted or items
        return ", ".join("rank %d=%.3fs" % kv for kv in items[:8])

    def maybe_report(self) -> None:
        if self.report_seconds <= 0 or not self.samples:
            return
        now = time.monotonic()
        if now - self._last_report < self.report_seconds:
            return
        self._last_report = now
        leader, lag = self.ranking()[0]
        last_frac = self.last_counts.get(leader, 0) / self.samples
        log.info(
            "straggler report: over %d negotiations the lateness EWMA is "
            "%s; rank %d arrived last in %.0f%% of them",
            self.samples, self.lag_summary(), leader, 100.0 * last_frac)
        flight_recorder.emit("straggler_report", leader=leader,
                             lag=round(lag, 6), samples=self.samples)


class StallInspector:
    def __init__(self, warning_time_seconds: float = 60.0,
                 shutdown_time_seconds: float = 0.0,
                 enabled: bool = True, elastic: bool = False):
        self.warning_time = warning_time_seconds
        self.shutdown_time = shutdown_time_seconds
        self.enabled = enabled
        # elastic mode: a shutdown-threshold stall raises a catchable
        # WorkerStallError (naming the missing ranks) instead of only
        # returning True — the elastic runner evicts the stalled workers
        # and re-forms rather than failing the whole job
        self.elastic = elastic
        self._last_check = time.monotonic()
        # tensor name -> first time observed incomplete. Fallback baseline
        # only: the message table's arrival stamp is preferred (see check),
        # so age is measured from the actual announcement, not from the
        # first scan that happened to notice it (which under-ages stalls
        # by up to one warning interval — ~2x delay before the warning).
        self._first_seen: Dict[str, float] = {}

    def check(self, message_table, cache=None, world: Optional[int] = None,
              straggler: "Optional[StragglerTracker]" = None) -> bool:
        """Scan for stalled tensors; returns True if a stall exceeded the
        shutdown threshold (reference: CheckForStalledTensors,
        stall_inspector.cc:26-110)."""
        if not self.enabled:
            return False
        now = time.monotonic()
        if now - self._last_check < self.warning_time:
            return False
        self._last_check = now

        pending = message_table.pending()
        stalled_msgs = []
        shutdown = False
        missing_ranks: set = set()
        warn_missing: set = set()
        seen_names = set()
        arrival_time = getattr(message_table, "first_request_time", None)
        for name, requests in pending.items():
            seen_names.add(name)
            # age from the request's arrival stamp carried in the message
            # table (reference: stall_inspector.cc keeps the timestamp with
            # the table entry); scan-time baseline only for tables that do
            # not carry one
            first = arrival_time(name) if arrival_time is not None else None
            if first is None:
                first = self._first_seen.setdefault(name, now)
            age = now - first
            if age < self.warning_time:
                continue
            ready = sorted(r.rank for r in requests)
            missing = ([] if world is None else
                       sorted(set(range(world)) - set(ready)))
            stalled_msgs.append(
                f"{name} [ready ranks: {ready}"
                + (f", missing ranks: {missing}]" if missing else "]"))
            warn_missing.update(missing)
            # NOTE: stalled *cached* tensors re-enter negotiation through
            # the controller's synchronized STALE_HIT invalidation protocol
            # (controller.py) — invalidating the coordinator's cache here
            # directly would desynchronize cache bits across workers.
            if self.shutdown_time > 0 and age > self.shutdown_time:
                shutdown = True
                missing_ranks.update(missing)

        # forget tensors that completed since last scan
        self._first_seen = {k: v for k, v in self._first_seen.items()
                            if k in seen_names}

        if stalled_msgs:
            _STALL_WARNINGS.inc(len(stalled_msgs))
            lag_note = ""
            if straggler is not None:
                summary = straggler.lag_summary(warn_missing or None)
                if summary:
                    lag_note = (" Straggler lag EWMA (seconds since first "
                                "announcing rank): %s." % summary)
            log.warning(
                "One or more tensors were submitted to be reduced, gathered "
                "or broadcasted by subset of ranks and are waiting for "
                "remainder of ranks for more than %.0f seconds. This may "
                "indicate that different ranks are trying to submit "
                "different tensors or that only subset of ranks is "
                "submitting tensors. Stalled ops: %s%s",
                self.warning_time, "; ".join(stalled_msgs), lag_note)
            flight_recorder.emit("stall_warning",
                                 tensors=len(stalled_msgs),
                                 missing=sorted(warn_missing))
        if shutdown:
            _STALL_SHUTDOWNS.inc()
            log.error(
                "Stalled tensors exceeded "
                "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS (%.0fs); "
                "shutting down.", self.shutdown_time)
            flight_recorder.emit("stall_shutdown",
                                 ranks=sorted(missing_ranks))
            flight_recorder.dump_on_failure("stall_shutdown")
            if self.elastic:
                from horovod_tpu.exceptions import WorkerStallError

                raise WorkerStallError(
                    f"stalled ranks exceeded the shutdown threshold "
                    f"({self.shutdown_time:.0f}s): "
                    f"{'; '.join(stalled_msgs)}",
                    ranks=sorted(missing_ranks))
        return shutdown
