"""horovod_tpu.tensorflow — TensorFlow binding for the TPU-native framework.

Rebuild of the reference's TF API (reference:
horovod/tensorflow/__init__.py:26-376): ``import horovod_tpu.tensorflow
as hvd`` gives ``hvd.init()``, differentiable ``allreduce`` /
``allgather`` / ``broadcast`` (IndexedSlices handled via the gather
path), ``broadcast_variables`` for the checkpoint-on-rank-0 convention,
``DistributedGradientTape`` averaging gradients across ranks, and
``DistributedOptimizer`` for both legacy ``tf.compat.v1`` optimizers
(compute_gradients override) and Keras optimizers (apply_gradients
override).

TensorFlow executes on CPU; the collectives run through the dynamic
enqueue runtime (negotiation, response cache, tensor fusion) on the XLA
data plane or the multi-process wire — the same path as the torch
binding.
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.tensorflow import mpi_ops
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401
    grouped_allreduce,
    Average,
    Max,
    Min,
    Product,
    Sum,
    _allreduce,
    allgather,
    alltoall,
    broadcast,
    reducescatter,
    cross_rank,
    cross_size,
    ddl_built,
    gloo_built,
    gloo_enabled,
    init,
    is_initialized,
    local_rank,
    local_size,
    mlsl_built,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
    xla_built,
)
from horovod_tpu.tensorflow.util import (_cache, _executing_eagerly,
                                         _make_subgraph,
                                         optimizer_variables)  # noqa: F401


def allreduce(tensor, average=True, device_dense="", device_sparse="",
              compression=Compression.none, name=None):
    """Average (or sum) a tensor over all ranks (reference:
    horovod/tensorflow/__init__.py:38-83). ``tf.IndexedSlices`` inputs
    take the gather path — values and indices are allgathered, which is
    an allreduce of the represented sparse tensor without densifying it.
    ``device_dense`` / ``device_sparse`` are accepted for API
    compatibility; placement on the TPU data plane is the runtime's job,
    not the op's. ``name`` keys the wire negotiation — a stable name
    makes the response cache and tensor fusion effective across steps."""
    if isinstance(tensor, tf.IndexedSlices):
        if average and not tensor.values.dtype.is_floating:
            raise ValueError(
                "average is not supported for integer IndexedSlices; "
                "use average=False")
        horovod_size = tf.cast(size(), tensor.values.dtype)
        values = allgather(tensor.values,
                           name=f"{name}.values" if name else None)
        indices = allgather(tensor.indices,
                            name=f"{name}.indices" if name else None)
        new_values = (values / horovod_size) if average else values
        return tf.IndexedSlices(new_values, indices,
                                dense_shape=tensor.dense_shape)
    if average and not (tensor.dtype.is_floating or tensor.dtype.is_complex):
        # int / size would silently promote to float64 (the reference
        # rejects integer averaging the same way)
        raise ValueError(
            "average is not supported for integer tensors; use "
            "average=False")
    horovod_size = tf.cast(size(), tensor.dtype)
    compressed, ctx = compression.compress(tensor)
    summed = _allreduce(compressed, name=name)
    summed = compression.decompress(summed, ctx)
    return summed / horovod_size if average else summed


def _broadcast_arrays_burst(arrays, root_rank, name_prefix):
    """Broadcast a list of numpy arrays from ``root_rank``: ALL enqueued
    async first, synchronized after, so the runtime negotiates and fuses
    them in few cycles instead of one round trip per array. 64-bit
    payloads would be silently narrowed on the x32 JAX data plane (int64
    step counters wrap, float64 loses precision); they ride as int32
    bit-pairs — broadcast moves bits, not numbers, so the reassembled
    value is exact."""
    from horovod_tpu.ops import collectives as _c

    handles = []
    for i, arr in enumerate(arrays):
        arr = np.ascontiguousarray(arr)
        orig_dtype = arr.dtype
        if orig_dtype in (np.int64, np.uint64, np.float64):
            arr = arr.reshape(-1).view(np.int32)
        handles.append((orig_dtype, arr.shape, _c.broadcast_async(
            arr, root_rank, name=f"{name_prefix}.{i}")))
    out = []
    for orig_dtype, _, handle in handles:
        value = np.asarray(_c.synchronize(handle))
        if value.dtype != orig_dtype:
            value = np.ascontiguousarray(value).reshape(-1) \
                .view(orig_dtype)
        out.append(value)
    return out


def broadcast_variables(variables, root_rank):
    """Broadcast variables from ``root_rank`` to all ranks — consistent
    init / resume-from-checkpoint (reference: __init__.py:86-113).

    Eager: reads ``var.numpy()``, bursts the broadcasts through the
    runtime (the reference wraps a tf.function for the same concurrency;
    an eager enqueue burst is the equivalent here and also works with
    Keras 3's backend Variables, which do not survive tf.function
    argument passing), and assigns in place.

    Graph mode (tf.compat.v1 / inside tf.function): returns a single op
    that performs the same burst at session-run time — ONE
    ``tf.py_function`` carries every variable, so the enqueue order
    cannot deadlock across ranks the way per-variable py_functions
    scheduled in different orders could (each would block in
    synchronize() holding an executor thread)."""
    variables = list(variables)
    if not variables:
        return tf.no_op() if not _executing_eagerly() else None
    if _executing_eagerly():
        if size() == 1:
            return None
        values = _broadcast_arrays_burst(
            [v.numpy() for v in variables], root_rank,
            "broadcast_variables")
        for var, value in zip(variables, values):
            var.assign(value.reshape(var.shape))
        return None
    return _graph_broadcast_variables_op(variables, root_rank)


def _graph_broadcast_variables_op(variables, root_rank):
    """Graph-mode assign op for :func:`broadcast_variables` (VERDICT r3
    ask 4: the former shim crashed on ``var.numpy()``). The py_function
    body executes at session-run time with eager tensors, bridging the
    graph world into the same numpy burst the eager path uses."""
    if size() == 1:
        return tf.no_op()

    def bridge(*tensors):
        values = _broadcast_arrays_burst(
            [t.numpy() for t in tensors], root_rank,
            "broadcast_variables.graph")
        return [tf.convert_to_tensor(v) for v in values]

    values = tf.py_function(
        bridge, [v.read_value() if hasattr(v, "read_value") else v
                 for v in variables],
        Tout=[v.dtype.base_dtype for v in variables])
    assigns = []
    for var, value in zip(variables, values):
        assigns.append(tf.compat.v1.assign(
            var, tf.reshape(value, tf.shape(var))))
    return tf.group(*assigns, name="horovod_broadcast_variables")


def broadcast_global_variables(root_rank):
    """Op broadcasting ALL global variables from ``root_rank`` — the TF1
    graph-mode initialization convention (reference: __init__.py:125-140).
    Eager callers must pass variables explicitly (the reference raises
    the same way: global collections do not exist in TF2 eager)."""
    if _executing_eagerly():
        raise RuntimeError(
            "hvd.broadcast_global_variables() does not support eager "
            "execution. Please use `hvd.broadcast_variables(<model/"
            "optimizer variables>)` instead.")
    return broadcast_variables(tf.compat.v1.global_variables(), root_rank)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """SessionRunHook broadcasting all global variables from root_rank
    once the session is created — rank-0-checkpoint-restore and random
    init both end up consistent under ``MonitoredTrainingSession`` /
    estimator-style loops (reference: __init__.py:158-192, the one named
    class of the reference's TF1 surface; same two-phase shape: build
    the op in ``begin``, run it in ``after_create_session``).

    ``device`` is accepted for API compatibility; placement on the TPU
    data plane is the runtime's job."""

    def __init__(self, root_rank, device=""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device

    def begin(self):
        if (self.bcast_op is None
                or self.bcast_op.graph != tf.compat.v1.get_default_graph()):
            self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object (epochs, RNG state in
    resume flows) — same convenience as the torch binding
    (torch/__init__.py broadcast_object)."""
    import pickle

    name = name or "broadcast_object"
    if size() == 1:
        return obj
    if rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        sz = tf.constant([len(payload)], dtype=tf.int64)
    else:
        payload = np.zeros(0, np.uint8)
        sz = tf.constant([0], dtype=tf.int64)
    sz = broadcast(sz, root_rank, name=f"{name}.size")
    if rank() != root_rank:
        payload = np.zeros(int(sz.numpy()[0]), np.uint8)
    buf = broadcast(tf.constant(payload, dtype=tf.uint8), root_rank,
                    name=f"{name}.bytes")
    if rank() == root_rank:
        return obj
    return pickle.loads(buf.numpy().tobytes())


@_cache
def _make_allreduce_grads_fn(name, device_dense, device_sparse,
                             compression, sparse_as_dense):
    """Closure that allreduces a gradient list (reference:
    __init__.py:195-215). Each gradient gets a STABLE wire name
    (``<name>.sig<k>.grad.<i>``) so the response cache hits and the
    runtime can fuse across steps — fresh auto-names would churn the
    cache and re-negotiate every step. The ``@_cache`` matters for the
    same reason: users re-wrap the tape every training step, and the
    cache hands every same-config wrapper the same closure (and thus the
    same wire names).

    ``sig<k>`` distinguishes distinct gradient SIGNATURES (the
    shapes/dtypes list) sharing one closure — without it, two
    same-config wrappers over different models (a GAN's generator and
    discriminator tapes) would alternate different shapes under the same
    wire names and renegotiate every step. Signature indices are
    assigned at trace time in first-seen order, which all ranks share
    under the same program-order assumption as auto-named ops.

    In eager mode the closure is compiled into one tf.function so the
    per-gradient collectives overlap instead of serializing (reference:
    __init__.py:212-215)."""
    signature_ids = {}

    def allreduce_grads(grads):
        if sparse_as_dense:
            grads = [tf.convert_to_tensor(g)
                     if g is not None and isinstance(g, tf.IndexedSlices)
                     else g for g in grads]
        # runs at trace time (shape changes retrace), so the dict stays
        # tiny: one entry per distinct model/signature
        sig = tuple((tuple(g.shape), str(g.dtype)) if g is not None
                    else None for g in grads)
        prefix = f"{name}.sig{signature_ids.setdefault(sig, len(signature_ids))}"
        # Dense gradients ride ONE grouped burst (a single py_function
        # that enqueues everything async before awaiting anything) — the
        # per-gradient path serializes into one negotiation round trip
        # per gradient when TF's inter-op pool is small, defeating
        # fusion entirely (measured 48/48 unfused cycles; the grouped
        # path hits 2). IndexedSlices keep the per-gradient gather path.
        dense_idx = []
        for i, g in enumerate(grads):
            if g is None or isinstance(g, tf.IndexedSlices):
                continue
            if not (g.dtype.is_floating or g.dtype.is_complex):
                # same guard as allreduce(): int / size would silently
                # promote to float64
                raise ValueError(
                    "average is not supported for integer tensors; "
                    "integer gradients cannot flow through "
                    "DistributedGradientTape averaging")
            dense_idx.append(i)
        out = list(grads)
        if dense_idx:
            compressed, ctxs = zip(*(compression.compress(grads[i])
                                     for i in dense_idx))
            summed = mpi_ops.grouped_allreduce(
                list(compressed), name=f"{prefix}.grads")
            for i, s, ctx in zip(dense_idx, summed, ctxs):
                s = compression.decompress(s, ctx)
                out[i] = s / tf.cast(size(), s.dtype)
        for i, g in enumerate(grads):
            if g is not None and isinstance(g, tf.IndexedSlices):
                out[i] = allreduce(g, device_dense=device_dense,
                                   device_sparse=device_sparse,
                                   compression=compression,
                                   name=f"{prefix}.grad.{i}")
        return out

    if _executing_eagerly():
        return _make_subgraph(allreduce_grads)
    return allreduce_grads


_LegacyOptimizer = getattr(tf.compat.v1.train, "Optimizer", None)

if _LegacyOptimizer is not None:
    class _DistributedOptimizer(_LegacyOptimizer):
        """Legacy (tf.compat.v1) optimizer wrapper: compute_gradients
        also allreduces (reference: __init__.py:230-275)."""

        def __init__(self, optimizer, name=None, use_locking=False,
                     device_dense="", device_sparse="",
                     compression=Compression.none, sparse_as_dense=False):
            if name is None:
                name = f"Distributed{type(optimizer).__name__}"
            super().__init__(name=name, use_locking=use_locking)
            self._optimizer = optimizer
            self._allreduce_grads = _make_allreduce_grads_fn(
                name, device_dense, device_sparse, compression,
                sparse_as_dense)

        def compute_gradients(self, *args, **kwargs):
            gradients = self._optimizer.compute_gradients(*args, **kwargs)
            if size() > 1:
                grads, variables = zip(*gradients)
                avg_grads = self._allreduce_grads(grads)
                return list(zip(avg_grads, variables))
            return gradients

        def apply_gradients(self, *args, **kwargs):
            return self._optimizer.apply_gradients(*args, **kwargs)

        def get_slot(self, *args, **kwargs):
            return self._optimizer.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._optimizer.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._optimizer.variables(*args, **kwargs)


def _wrap_keras_optimizer_class(base_cls, name=None, device_dense="",
                                device_sparse="",
                                compression=Compression.none,
                                sparse_as_dense=False):
    """Dynamic ``Distributed<Base>`` Keras optimizer class:
    apply_gradients averages the incoming gradients across ranks first —
    the TF2-idiomatic placement of the reference's compute_gradients
    override (reference: __init__.py:245-259; Keras 3 optimizers have no
    compute_gradients).

    A REAL subclass of the optimizer's own class (the reference
    re-parents the same way, __init__.py:368-369): it passes Keras'
    isinstance checks (``model.compile`` accepts it) and attribute
    writes like ``opt.learning_rate = ...`` hit real optimizer state —
    a delegating proxy would take the write on the proxy and silently
    leave the inner optimizer untouched."""
    allreduce_grads = _make_allreduce_grads_fn(
        name or f"Distributed{base_cls.__name__}", device_dense,
        device_sparse, compression, sparse_as_dense)

    class DistributedKerasOptimizer(base_cls):
        _hvd_allreduce_grads = staticmethod(allreduce_grads)

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            if size() > 1:
                grads, variables = zip(*grads_and_vars)
                grads = self._hvd_allreduce_grads(tuple(grads))
                grads_and_vars = list(zip(grads, variables))
            return super().apply_gradients(grads_and_vars, *args,
                                           **kwargs)

    DistributedKerasOptimizer.__name__ = f"Distributed{base_cls.__name__}"
    return DistributedKerasOptimizer


def _make_keras_optimizer(optimizer, name, device_dense, device_sparse,
                          compression, sparse_as_dense):
    cls = _wrap_keras_optimizer_class(
        optimizer.__class__, name, device_dense, device_sparse,
        compression, sparse_as_dense)
    return cls.from_config(optimizer.get_config())


def __getattr__(attr):
    """Resolve ``Distributed<Opt>`` classes for Keras deserialization: a
    model saved with a wrapped optimizer records class_name
    'DistributedSGD' (etc.) against this module, and loading rebuilds
    the same wrapper around the stock Keras class (the reference solves
    this with a custom_objects registry in load_model,
    keras/__init__.py:123-157; a module __getattr__ covers every
    optimizer without enumeration)."""
    prefix = "Distributed"
    if attr.startswith(prefix) and hasattr(tf.keras.optimizers,
                                           attr[len(prefix):]):
        return _wrap_keras_optimizer_class(
            getattr(tf.keras.optimizers, attr[len(prefix):]))
    raise AttributeError(
        f"module {__name__!r} has no attribute {attr!r}")


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False):
    """Wrap an optimizer so gradients are averaged across ranks before
    the update (reference: __init__.py:278-320). Accepts legacy
    ``tf.compat.v1.train.Optimizer`` instances (compute_gradients
    override) and Keras optimizers (apply_gradients override)."""
    if _LegacyOptimizer is not None and isinstance(optimizer,
                                                   _LegacyOptimizer):
        return _DistributedOptimizer(optimizer, name, use_locking,
                                     device_dense, device_sparse,
                                     compression, sparse_as_dense)
    if hasattr(optimizer, "apply_gradients"):
        return _make_keras_optimizer(optimizer, name, device_dense,
                                     device_sparse, compression,
                                     sparse_as_dense)
    raise ValueError(
        "Provided optimizer doesn't inherit from either legacy "
        "TensorFlow or Keras optimizer: %s" % optimizer)


class _DistributedGradientTape:
    """Delegating tape wrapper: ``gradient()`` averages across ranks
    (reference: __init__.py:323-342 — the reference re-parents the
    tape's class at runtime; a delegating wrapper gives the same surface
    without depending on GradientTape internals)."""

    def __init__(self, tape, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False):
        self._tape = tape
        self._allreduce_grads = _make_allreduce_grads_fn(
            "DistributedGradientTape", device_dense, device_sparse,
            compression, sparse_as_dense)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        gradients = self._tape.gradient(target, sources, output_gradients)
        if size() > 1:
            structure = tf.nest.flatten(gradients)
            reduced = self._allreduce_grads(tuple(structure))
            return tf.nest.pack_sequence_as(gradients, list(reduced))
        return gradients

    def __getattr__(self, item):
        return getattr(self._tape, item)


def DistributedGradientTape(gradtape, device_dense="", device_sparse="",
                            compression=Compression.none,
                            sparse_as_dense=False):
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns
    rank-averaged gradients (reference: __init__.py:345-376)."""
    return _DistributedGradientTape(gradtape, device_dense, device_sparse,
                                    compression, sparse_as_dense)
