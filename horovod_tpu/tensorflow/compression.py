"""Gradient compression for the TensorFlow binding.

Rebuild of the reference's TF compression (reference:
horovod/tensorflow/compression.py:23-78): compress to fp16 on the wire,
decompress back to the original dtype after the collective. Non-float
tensors pass through untouched.
"""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    """Interface: compress before the collective, decompress after."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: compression.py:34-44)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Halve wire bytes for float tensors (reference:
    compression.py:47-69). Integer tensors pass through — casting them
    would corrupt the values."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating and tensor.dtype != tf.float16:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is None:
            return tensor
        return tf.cast(tensor, ctx)


class Compression:
    """Namespace matching the reference's selection surface
    (compression.py:72-78)."""

    none = NoneCompressor
    fp16 = FP16Compressor
