"""horovod_tpu.tensorflow.keras — tf.keras surface over the TF binding.

Rebuild of the reference's TF-Keras binding (reference:
horovod/tensorflow/keras/__init__.py:41-157 and the shared
implementations in horovod/_keras/callbacks.py:20-185): a Keras-native
``DistributedOptimizer``, value-level collective helpers, the canonical
callback trio (broadcast-on-start, metric averaging, LR warmup /
schedule), and ``load_model`` that rewraps the deserialized optimizer.

All collectives ride the same enqueue runtime as
``horovod_tpu.tensorflow`` — this module only adapts the surface to the
tf.keras training loop.
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as _hvd_tf
from horovod_tpu.tensorflow import (  # noqa: F401 — re-exported lifecycle
    Compression,
    broadcast_variables,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from horovod_tpu.tensorflow.keras import callbacks  # noqa: F401


def DistributedOptimizer(optimizer, name=None, device_dense="",
                         device_sparse="", compression=Compression.none,
                         sparse_as_dense=False):
    """Keras optimizer whose apply_gradients averages gradients across
    ranks first (reference: keras/__init__.py:41-67 — there via a
    get_gradients override; Keras 3 optimizers apply, not get)."""
    return _hvd_tf.DistributedOptimizer(
        optimizer, name=name, device_dense=device_dense,
        device_sparse=device_sparse, compression=compression,
        sparse_as_dense=sparse_as_dense)


def broadcast_global_variables(root_rank):
    """reference: keras/__init__.py:70-77 — eager Keras has no globals
    collection; broadcast a model/optimizer's variables explicitly."""
    return _hvd_tf.broadcast_global_variables(root_rank)


def allreduce(value, name=None, average=True):
    """Average a value (tensor or numpy) over all ranks (reference:
    keras/__init__.py:80-91)."""
    tensor = tf.convert_to_tensor(value)
    out = _hvd_tf.allreduce(tensor, average=average, name=name)
    return out.numpy() if isinstance(value, (np.ndarray, float, int)) \
        else out


def allgather(value, name=None):
    """reference: keras/__init__.py:94-106."""
    tensor = tf.convert_to_tensor(value)
    out = _hvd_tf.allgather(tensor, name=name)
    return out.numpy() if isinstance(value, np.ndarray) else out


def broadcast(value, root_rank, name=None):
    """reference: keras/__init__.py:109-120."""
    tensor = tf.convert_to_tensor(value)
    out = _hvd_tf.broadcast(tensor, root_rank, name=name)
    return out.numpy() if isinstance(value, np.ndarray) else out


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model saved with a wrapped optimizer: the
    ``Distributed<Opt>`` classes are provided as custom objects for
    every stock Keras optimizer (plus any ``custom_optimizers``), so
    the restored model resumes distributed without re-wrapping
    (reference: keras/__init__.py:123-157, same wrap_optimizer registry
    idea)."""
    from horovod_tpu.tensorflow import _wrap_keras_optimizer_class

    objects = {}
    base_classes = [getattr(tf.keras.optimizers, attr)
                    for attr in dir(tf.keras.optimizers)]
    base_classes = [cls for cls in base_classes
                    if isinstance(cls, type)
                    and issubclass(cls, tf.keras.optimizers.Optimizer)]
    for cls in base_classes + list(custom_optimizers or []):
        wrapped = _wrap_keras_optimizer_class(cls,
                                              compression=compression)
        objects[wrapped.__name__] = wrapped
    objects.update(custom_objects or {})
    return tf.keras.models.load_model(filepath, custom_objects=objects)
