"""tf.keras callbacks for distributed training.

Rebuild of the reference's shared Keras callback implementations
(reference: horovod/_keras/callbacks.py:20-185, surfaced via
horovod/tensorflow/keras/callbacks.py): broadcast-on-start, cross-rank
metric averaging, and the LR schedule/warmup pair that scales the
learning rate with world size — the canonical distributed-Keras recipe
(reference: docs and examples/keras_mnist_advanced.py).
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd



class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast model + optimizer state from ``root_rank`` at the end
    of the FIRST batch, so random inits / restored checkpoints agree
    across ranks (reference: _keras/callbacks.py:20-43, same hook
    point: optimizer slot variables only exist after the first
    apply_gradients, and the batch-0 broadcast overwrites whatever that
    one divergent step produced)."""

    def __init__(self, root_rank=0, device=""):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        variables = list(self.model.variables)
        if self.model.optimizer is not None:
            variables += hvd.optimizer_variables(self.model.optimizer)
        hvd.broadcast_variables(variables, self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics over ranks before other callbacks (early
    stopping, checkpointing, LR plateaus) read them (reference:
    _keras/callbacks.py:46-85)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            for name in sorted(logs):
                value = logs[name]
                if isinstance(value, (int, float, np.floating, np.integer)):
                    logs[name] = float(hvd.allreduce(
                        tf.constant(float(value)),
                        average=True, name=f"metric.{name}").numpy())


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Multiply the base LR by ``multiplier(epoch)`` within
    [start_epoch, end_epoch) (reference: _keras/callbacks.py:87-163;
    ``staircase`` applies per-epoch, otherwise per-batch with
    fractional epochs)."""

    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = None
        self.current_epoch = 0
        self._restore_momentum = None
        if not callable(multiplier):
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _in_range(self, epoch):
        return (epoch >= self.start_epoch
                and (self.end_epoch is None or epoch < self.end_epoch))

    def _set_lr(self, epoch):
        if not self._in_range(epoch):
            return
        opt = self.model.optimizer
        old_lr = float(tf.keras.backend.get_value(opt.learning_rate))
        new_lr = self.initial_lr * self.multiplier(epoch)
        opt.learning_rate = new_lr
        if self.momentum_correction and hasattr(opt, "momentum") \
                and old_lr > 0:
            # scale the accumulated momentum by the lr ratio for the
            # step the new lr first applies to, then restore (Goyal et
            # al. 2017; reference: _keras/callbacks.py:120-134)
            self._restore_momentum = float(
                tf.keras.backend.get_value(opt.momentum))
            opt.momentum = self._restore_momentum * new_lr / old_lr

    def _restore_momentum_if_needed(self):
        if self._restore_momentum is not None:
            self.model.optimizer.momentum = self._restore_momentum
            self._restore_momentum = None

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = float(
                tf.keras.backend.get_value(
                    self.model.optimizer.learning_rate))

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._set_lr(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase:
            if self.steps_per_epoch is None:
                raise ValueError(
                    "steps_per_epoch is required for non-staircase "
                    "schedules (the reference autodetects it from the "
                    "TF1 params dict, which eager Keras no longer "
                    "carries)")
            self._set_lr(self.current_epoch + batch / self.steps_per_epoch)

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        self._restore_momentum_if_needed()
        if logs is not None:
            logs["lr"] = float(tf.keras.backend.get_value(
                self.model.optimizer.learning_rate))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Ramp the LR from 1x to size()x over ``warmup_epochs`` — the
    gradual-warmup recipe for large effective batches (reference:
    _keras/callbacks.py:166-185, after Goyal et al. 2017)."""

    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            # epoch may be fractional (per-batch ramp)
            return 1.0 / hvd.size() * (
                epoch * (hvd.size() - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.warmup_epochs - 1 and self.verbose \
                and hvd.rank() == 0:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self.initial_lr}.")
