"""TensorFlow collective ops backed by the TPU-native runtime.

Rebuild of the reference's TF op layer (reference:
horovod/tensorflow/mpi_ops.py:33-180 and the kernels in
horovod/tensorflow/mpi_ops.cc:276-440): ``_allreduce`` / ``allgather`` /
``broadcast`` with registered gradients so the ops are differentiable
under ``tf.GradientTape`` and inside ``tf.function`` graphs.

Where the reference loads a compiled ``mpi_lib`` op library whose
AsyncOpKernels enqueue into the Horovod runtime, this binding reaches the
same dynamic enqueue runtime (negotiation, response cache, tensor fusion
— SURVEY.md §2.1) through the named-async numpy API, bridged into the TF
graph with ``tf.py_function`` and differentiated with
``tf.custom_gradient`` — the TF2-idiomatic equivalents of a custom op
with a ``RegisterGradient`` entry. TF tensors cross as numpy arrays
(bfloat16 included — TF's ``.numpy()`` yields ``ml_dtypes.bfloat16``,
which the collective layer handles natively); the collective itself runs
on the XLA data plane or the multi-process wire exactly as for the torch
binding.
"""

from __future__ import annotations

import re
import threading

import numpy as np
import tensorflow as tf

from horovod_tpu.core.basics import (  # noqa: F401 — re-exported lifecycle
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    mpi_threads_supported,
    mpi_enabled,
    mpi_built,
    gloo_built,
    nccl_built,
    ddl_built,
    mlsl_built,
    xla_built,
)
from horovod_tpu.ops import collectives as _c

# the reference exposes gloo_enabled alongside gloo_built
# (mpi_ops.py:61-62); in this runtime the wire transport is active
# whenever it is built
gloo_enabled = gloo_built

Average = _c.Average
Sum = _c.Sum
Min = _c.Min
Max = _c.Max
Product = _c.Product

# Per-process op counters for auto-generated names, shared convention
# with the torch binding (torch/mpi_ops.py:33-43): all ranks must issue
# unnamed ops in the same order (and trace tf.functions in the same
# order) — the reference's graph-mode naming has the same property.
_op_counters = {}
_counter_lock = threading.Lock()


def _op_name(op_kind, name):
    if name is not None:
        return _normalize_name(name)
    with _counter_lock:
        n = _op_counters.get(op_kind, 0)
        _op_counters[op_kind] = n + 1
    return f"{op_kind}.noname.{n}"


def _normalize_name(name):
    """Normalize an op name to TF charset rules (reference:
    mpi_ops.py:68-70) — also keeps wire names printable."""
    return re.sub("[^a-zA-Z0-9_./]", "_", name)


def _run_collective(launch, tensor, out_dtype, out_shape):
    """Run a numpy-level collective inside the TF graph.

    ``launch(np_array) -> np_array`` is executed via ``tf.py_function``
    so the same code path serves eager execution and traced
    ``tf.function`` graphs (the reference's AsyncOpKernel serves both the
    same way). ``out_shape`` restores the static shape py_function
    erases; pass None when the output shape depends on other ranks
    (allgather's dim 0)."""

    def bridge(t):
        return launch(t.numpy())

    out = tf.py_function(bridge, [tensor], Tout=out_dtype)
    if out_shape is not None:
        out.set_shape(out_shape)
    else:
        shape = tensor.shape.as_list() if tensor.shape.rank is not None \
            else None
        if shape is not None:
            shape[0] = None
            out.set_shape(shape)
    return out


def _allreduce(tensor, name=None, op=Sum):
    """Sum (by default) a tensor over all processes, keyed by name; the
    op completes only after every rank contributed (reference:
    mpi_ops.py:73-86). Differentiable: grad(allreduce) = allreduce(grad)
    (reference: mpi_ops.py:89-100)."""
    tensor = tf.convert_to_tensor(tensor)
    if size() == 1:
        return tf.identity(tensor)
    wire_name = _op_name("allreduce", name)

    @tf.custom_gradient
    def fn(t):
        def launch(arr):
            return np.asarray(_c.synchronize(
                _c.allreduce_async(arr, op=op, name=wire_name)))

        result = _run_collective(launch, t, t.dtype, t.shape)

        def grad(dy):
            return _allreduce(dy, name=f"{wire_name}.grad", op=op)

        return result, grad

    return fn(tensor)


def grouped_allreduce(tensors, name=None, op=Sum):
    """Sum a LIST of tensors over all processes in one burst (reference:
    mpi_ops.py grouped_allreduce / grouped_allreduce_async_): every
    tensor is enqueued async inside a SINGLE ``tf.py_function`` before
    any is awaited, so the runtime negotiates and bin-packs the whole
    burst into few fused cycles.

    This is load-bearing, not sugar: TF's executor gives each
    ``py_function`` body an inter-op thread, and a body that blocks in
    ``synchronize()`` holds it — on small thread pools per-tensor
    collectives serialize into one negotiation round trip per tensor
    (measured: a 48-gradient tape burst cost 48 unfused cycles through
    the per-tensor path, 2 through this one). Differentiable the same
    way as ``_allreduce``: grad(grouped) = grouped(grads)."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    if not tensors:
        return []
    if size() == 1:
        return [tf.identity(t) for t in tensors]
    prefix = _op_name("grouped_allreduce", name)

    @tf.custom_gradient
    def fn(*ts):
        def bridge(*arrs):
            handles = [
                _c.allreduce_async(a.numpy(), op=op, name=f"{prefix}.{i}")
                for i, a in enumerate(arrs)]
            return [np.asarray(_c.synchronize(h)) for h in handles]

        outs = tf.py_function(bridge, list(ts),
                              Tout=[t.dtype for t in ts])
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        outs = list(outs)
        for o, t in zip(outs, ts):
            o.set_shape(t.shape)

        def grad(*dys):
            return grouped_allreduce(list(dys), name=f"{prefix}.grad",
                                     op=op)

        return outs, grad

    out = fn(*tensors)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def allgather(tensor, name=None):
    """Concatenate each rank's tensor along dim 0; ranks may differ in
    dim 0 (reference: mpi_ops.py:103-119). Differentiable: the gradient
    is this rank's slice of the summed gradient (reference:
    mpi_ops.py:122-145)."""
    tensor = tf.convert_to_tensor(tensor)
    if tensor.shape.rank == 0:
        raise ValueError(
            "allgather requires a tensor of rank >= 1 (the concatenation "
            "axis); reshape the scalar to [1] first")
    if size() == 1:
        return tf.identity(tensor)
    wire_name = _op_name("allgather", name)

    @tf.custom_gradient
    def fn(t):
        def launch(arr):
            return np.asarray(_c.synchronize(
                _c.allgather_async(arr, name=wire_name)))

        result = _run_collective(launch, t, t.dtype, None)

        def grad(dy):
            # sizes travel as one more allgather so ragged dim 0 splits
            # correctly (reference does the same with a [d0] gather)
            d0 = tf.shape(t)[0]
            sizes = allgather(tf.reshape(d0, [1]),
                              name=f"{wire_name}.sizes")
            summed = _allreduce(dy, name=f"{wire_name}.grad")
            offset = tf.reduce_sum(sizes[:rank()])
            begin = tf.concat(
                [tf.reshape(offset, [1]),
                 tf.zeros([tf.rank(t) - 1], dtype=tf.int32)], axis=0)
            extent = tf.concat(
                [tf.reshape(d0, [1]), tf.shape(t)[1:]], axis=0)
            return tf.slice(summed, begin, extent)

        return result, grad

    return fn(tensor)


def broadcast(tensor, root_rank, name=None):
    """Broadcast the root rank's value to every process, keyed by name
    (reference: mpi_ops.py:148-162). Differentiable: the gradient is the
    summed gradient on the root and zero elsewhere (reference:
    mpi_ops.py:165-180)."""
    tensor = tf.convert_to_tensor(tensor)
    if size() == 1:
        return tf.identity(tensor)
    wire_name = _op_name("broadcast", name)

    @tf.custom_gradient
    def fn(t):
        def launch(arr):
            return np.asarray(_c.synchronize(
                _c.broadcast_async(arr, root_rank, name=wire_name)))

        result = _run_collective(launch, t, t.dtype, t.shape)

        def grad(dy):
            summed = _allreduce(dy, name=f"{wire_name}.grad")
            if rank() != root_rank:
                return tf.zeros_like(summed)
            return summed

        return result, grad

    return fn(tensor)


def _rs_a2a_launch(kind, wire_name, red_op=None):
    """numpy-level launch for reducescatter/alltoall: the enqueue runtime
    in a multi-process world, the replicated single-controller emulation
    otherwise (same split as the torch binding — the core eager RS/A2A
    accept only stacked per-worker input)."""
    from horovod_tpu.core import basics

    def launch(arr):
        st = basics._ensure_init()
        if _c._multiprocess_world(st) and _c._runtime_capable(st):
            from horovod_tpu.runtime.runtime import get_runtime

            if kind == "reducescatter":
                h = get_runtime().enqueue_reducescatter(
                    wire_name, _c._to_plane(arr),
                    reduce_op=_c._OP_NAMES[red_op])
            else:
                h = get_runtime().enqueue_alltoall(
                    wire_name, _c._to_plane(arr))
            return np.asarray(_c.synchronize(h))
        return np.asarray(_c._replicated_rs_a2a(
            kind, np.asarray(arr), st.size, red_op))

    return launch


def reducescatter(tensor, name=None, op=Average):
    """Reduce across ranks and keep this rank's shard of dim 0 (TPU
    extension mirroring the core API; role reference:
    ops/nccl_operations.cc:150-346). ``op`` defaults to Average — the
    same omitted-op default as the core API (``_resolve_op``) and the
    torch binding. dim 0 must divide evenly by the world size.
    Differentiable for Sum/Average: grad(reducescatter) =
    allgather(grad) (each rank's input slice j contributed to shard j's
    reduction on its owner)."""
    tensor = tf.convert_to_tensor(tensor)
    if tensor.shape.rank == 0:
        raise ValueError("reducescatter requires a tensor of rank >= 1")
    if tensor.shape[0] is not None and tensor.shape[0] % size():
        raise ValueError(
            f"reducescatter dim 0 ({tensor.shape[0]}) must divide evenly "
            f"by size ({size()})")
    if size() == 1:
        return tf.identity(tensor)
    wire_name = _op_name("reducescatter", name)
    out_shape = tf.TensorShape(
        [None if tensor.shape[0] is None else tensor.shape[0] // size()]
        + tensor.shape.as_list()[1:])

    @tf.custom_gradient
    def fn(t):
        result = _run_collective(
            _rs_a2a_launch("reducescatter", wire_name, red_op=op),
            t, t.dtype, out_shape)

        def grad(dy):
            if op not in (Sum, Average):
                # the allgather adjoint is only correct for the linear
                # ops; Min/Max/Product would need argmax routing — fail
                # loud rather than train on silently wrong gradients
                raise NotImplementedError(
                    "reducescatter gradient is defined for Sum/Average "
                    "only")
            g = allgather(dy, name=f"{wire_name}.grad")
            if op == Average:
                g = g / tf.cast(size(), g.dtype)
            return g

        return result, grad

    return fn(tensor)


def alltoall(tensor, name=None):
    """Split dim 0 into ``size()`` chunks, send chunk j to rank j,
    receive one chunk from every rank (TPU extension mirroring the core
    API). dim 0 must divide evenly by the world size. Differentiable:
    the exchange is its own adjoint, so grad(alltoall) =
    alltoall(grad)."""
    tensor = tf.convert_to_tensor(tensor)
    if tensor.shape.rank == 0:
        raise ValueError("alltoall requires a tensor of rank >= 1")
    if tensor.shape[0] is not None and tensor.shape[0] % size():
        raise ValueError(
            f"alltoall dim 0 ({tensor.shape[0]}) must divide evenly by "
            f"size ({size()})")
    if size() == 1:
        return tf.identity(tensor)
    wire_name = _op_name("alltoall", name)

    @tf.custom_gradient
    def fn(t):
        result = _run_collective(
            _rs_a2a_launch("alltoall", wire_name),
            t, t.dtype, t.shape)

        def grad(dy):
            return alltoall(dy, name=f"{wire_name}.grad")

        return result, grad

    return fn(tensor)
