"""Small TF-side helpers for the TensorFlow binding.

TPU-native analogue of the reference's helper module (reference:
horovod/tensorflow/util.py:21-55 — ``_executing_eagerly``,
``_make_subgraph``, ``_cache``): eager detection, tf.function wrapping,
and a per-argument cache used to build the grads-allreduce closure once.
"""

from __future__ import annotations

import functools

import tensorflow as tf


def _executing_eagerly() -> bool:
    """True when TF is executing eagerly (TF2 default)."""
    return tf.executing_eagerly()


def _make_subgraph(fn):
    """Compile ``fn`` into a single TF graph so independent ops inside it
    (e.g. the per-variable broadcasts of ``broadcast_variables``) run
    concurrently instead of serializing through the eager executor."""
    return tf.function(fn)


def optimizer_variables(optimizer) -> list:
    """Optimizer state variables across Keras generations: Keras 3
    exposes ``optimizer.variables`` as a property (a list), Keras 2
    (TF<=2.15) as a bound method — calling ``list(...)`` on the latter
    raises ``TypeError: 'method' object is not iterable``."""
    v = optimizer.variables
    return list(v() if callable(v) else v)


def _cache(fn):
    """Memoize on hashable positional args (the reference caches its
    closure factories the same way so tf.function tracing happens once
    per configuration, not once per call)."""
    cache = {}

    @functools.wraps(fn)
    def wrapper(*args):
        key = (args, tf.executing_eagerly())
        if key not in cache:
            cache[key] = fn(*args)
        return cache[key]

    return wrapper
