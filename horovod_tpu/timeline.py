"""Chrome-trace timeline: per-tensor negotiation/operation tracing.

TPU-native equivalent of the reference's Horovod Timeline (reference:
horovod/common/timeline.cc/.h, docs/timeline.rst:6-21): a JSON trace in the
Chrome ``chrome://tracing`` "JSON Array" format recording, per named tensor,
the NEGOTIATE phase (when each worker announced readiness), the top-level
operation, and nested activities (fusion memcpys, the XLA collective, ...).

Mechanics mirror the reference: the hot path never blocks on file I/O —
events go into a queue drained by a dedicated writer thread (reference:
timeline.h:66-75 uses a boost lock-free SPSC queue + writer thread; here a
``queue.SimpleQueue`` + daemon thread). Each tensor follows the state
machine UNKNOWN → NEGOTIATING → TOP_LEVEL → ACTIVITY (reference:
timeline.h:77).

Enable with ``HOROVOD_TIMELINE=/path/to/trace.json``; optional per-cycle
markers with ``HOROVOD_TIMELINE_MARK_CYCLES`` (reference:
operations.cc:363-375).

Timestamps are **epoch microseconds** (one clock domain across ranks and
across trace producers), so per-rank timelines and device-side traces
exported as Chrome JSON (e.g. ``jax.profiler.trace`` via TensorBoard's
profile plugin) compose into ONE merged view with
``tpurun --merge-trace out.json rank0.json rank1.json device.json.gz``
(:func:`merge_traces`) — the analogue of the reference's single
host+device Chrome trace (reference: timeline.cc,
cuda_operations.cc:69-93 event timestamps).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Optional

from horovod_tpu.metrics import registry as _metrics

_TL_DROPPED = _metrics().counter(
    "horovod_timeline_dropped_events_total",
    "Timeline events discarded after the writer became unhealthy or its "
    "ring overflowed.")

# Activity names (reference: horovod/common/common.h:31-58)
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
XLA_COLLECTIVE = "XLA_COLLECTIVE"
QUEUE = "QUEUE"


class _NativeWriter:
    """Writer backed by the C++ SPSC ring + writer thread (cpp/timeline.cc
    — the direct analogue of the reference's boost spsc_queue +
    TimelineWriter, reference: timeline.h:66-75)."""

    def __init__(self, path: str):
        from horovod_tpu.runtime import native

        self._lib = native.load_library()
        self._handle = self._lib.hvd_tl_open(path.encode())
        if not self._handle:
            raise OSError(f"could not open timeline file {path!r}")

    def emit(self, ph: str, pid: int, ts_us: float,
             name: Optional[str] = None, args: Optional[dict] = None,
             s: Optional[str] = None) -> None:
        if not self._handle:  # closed — drop rather than use-after-free
            _TL_DROPPED.inc()
            return
        if self._lib.hvd_tl_emit(
                self._handle, ph.encode(), pid, ts_us,
                name.encode() if name else None,
                json.dumps(args).encode() if args else None,
                s.encode() if s else None):
            # nonzero return: ring overflow or oversize event (timeline.cc)
            _TL_DROPPED.inc()

    def close(self) -> None:
        if self._handle:
            handle, self._handle = self._handle, None
            self._lib.hvd_tl_close(handle)


class _Writer:
    """Pure-Python fallback: background thread draining an event queue
    (reference: TimelineWriter, timeline.cc:28-127)."""

    _CLOSE = object()

    def __init__(self, path: str):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._path = path
        self._file = open(path, "w")
        self._file.write("[\n")
        self._healthy = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-timeline-writer")
        self._thread.start()

    def emit(self, ph: str, pid: int, ts_us: float,
             name: Optional[str] = None, args: Optional[dict] = None,
             s: Optional[str] = None) -> None:
        if not self._healthy:
            _TL_DROPPED.inc()
            return
        event = {"ph": ph, "pid": pid, "ts": ts_us}
        if name:
            event["name"] = name
        if args:
            event["args"] = args
        if s:
            event["s"] = s
        self._q.put(event)

    def close(self) -> None:
        self._q.put(self._CLOSE)
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        try:
            while True:
                try:
                    item = self._q.get(timeout=1.0)
                except queue.Empty:
                    # periodic flush: a killed process leaves a readable
                    # (truncated-array) trace instead of a buffered void —
                    # merge_traces tolerates the truncation
                    self._flush()
                    continue
                if item is self._CLOSE:
                    break
                if not self._healthy:
                    _TL_DROPPED.inc()
                    continue
                try:
                    self._file.write(json.dumps(item) + ",\n")
                    if self._q.empty():
                        self._flush()
                except (OSError, ValueError):
                    self._healthy = False
                    _TL_DROPPED.inc()
        finally:
            # Chrome tracing tolerates a trailing comma with no closing
            # bracket, but we close the array properly.
            try:
                self._file.write("{}]\n")
                self._file.close()
            except (OSError, ValueError):
                pass
            self._healthy = False

    def _flush(self) -> None:
        if not self._healthy:
            return
        try:
            self._file.flush()
        except (OSError, ValueError):
            self._healthy = False


def _make_writer(path: str):
    try:
        return _NativeWriter(path)
    except Exception:
        return _Writer(path)


class Timeline:
    """Per-tensor tracing state machine (reference: timeline.h:77-131).

    Thread-safe: enqueue-side state is mutex-guarded; file I/O happens on
    the writer thread only.
    """

    def __init__(self, path: str, mark_cycles: bool = False):
        self._writer = _make_writer(path)
        self._mark_cycles = mark_cycles
        self._lock = threading.Lock()
        self._tensor_pids: dict[str, int] = {}
        self._next_pid = 1
        self._cycle = 0

    # -- helpers -----------------------------------------------------------
    def _ts_us(self) -> float:
        # epoch domain so traces from different ranks/producers align
        # (double keeps microsecond precision: 2^53 us >> epoch us)
        return time.time_ns() / 1e3

    def _pid(self, tensor_name: str) -> int:
        pid = self._tensor_pids.get(tensor_name)
        if pid is None:
            pid = self._next_pid
            self._next_pid += 1
            self._tensor_pids[tensor_name] = pid
            self._writer.emit("M", pid, self._ts_us(), name="process_name",
                              args={"name": tensor_name})
        return pid

    def _emit(self, tensor_name: str, ph: str, name: Optional[str] = None,
              **args) -> None:
        with self._lock:
            self._writer.emit(ph, self._pid(tensor_name), self._ts_us(),
                              name=name, args=args or None)

    # -- the reference's Timeline API --------------------------------------
    def negotiate_start(self, tensor_name: str, request_type: str) -> None:
        """NEGOTIATING: first worker announced the tensor (reference:
        timeline.cc NegotiateStart, driven from controller
        IncrementTensorCount, controller.cc:708-721)."""
        self._emit(tensor_name, "B", f"NEGOTIATE_{request_type}")

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        self._emit(tensor_name, "i", f"RANK_{rank}_READY")

    def negotiate_end(self, tensor_name: str) -> None:
        self._emit(tensor_name, "E")

    def start(self, tensor_name: str, op_name: str) -> None:
        """TOP_LEVEL: the collective began executing."""
        self._emit(tensor_name, "B", op_name)

    def activity_start(self, tensor_name: str, activity: str) -> None:
        self._emit(tensor_name, "B", activity)

    def activity_end(self, tensor_name: str) -> None:
        self._emit(tensor_name, "E")

    def end(self, tensor_name: str, op_name: Optional[str] = None) -> None:
        self._emit(tensor_name, "E")

    def counters(self, values: dict) -> None:
        """Chrome ``"C"`` (counter) events — one series per key, all on
        pid 0 with a shared timestamp, so runtime counters (queue depth,
        cache hits, fused bytes, ...) graph as stacked curves above the
        per-tensor lanes in the same clock domain. The runtime calls this
        once per cycle; merge_traces preserves the events across pid
        remapping (docs/metrics.md)."""
        with self._lock:
            ts = self._ts_us()
            for name, value in values.items():
                self._writer.emit("C", 0, ts, name=name,
                                  args={"value": value})

    def mark_cycle_start(self) -> None:
        """Optional per-cycle instant markers (reference: timeline.h:98,
        HOROVOD_TIMELINE_MARK_CYCLES)."""
        if self._mark_cycles:
            with self._lock:
                self._cycle += 1
                self._writer.emit("i", 0, self._ts_us(),
                                  name=f"CYCLE_{self._cycle}", s="g")

    def close(self) -> None:
        # under the emit lock: no emitter may race the native writer's
        # teardown (hvd_tl_close frees the C++ ring)
        with self._lock:
            self._writer.close()


# ---------------------------------------------------------------------------
# Trace merging (the reference writes host+device into ONE Chrome trace,
# timeline.cc + cuda_operations.cc:69-93; here separate producers share the
# epoch clock domain and this merges their files)
# ---------------------------------------------------------------------------

def _load_trace_events(path: str) -> list:
    """Read a Chrome trace: plain or gzipped, 'JSON Array' or
    '{"traceEvents": [...]}' object format."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        # tolerate the truncated array a crashed writer leaves behind
        # (Chrome tracing does the same)
        data = json.loads(text.rstrip().rstrip(",") + "]")
    events = data.get("traceEvents", []) if isinstance(data, dict) else data
    return [e for e in events if isinstance(e, dict) and "ph" in e]


def merge_traces(out_path: str, inputs: list, align: bool = False) -> int:
    """Merge Chrome trace files into one (``tpurun --merge-trace``).

    Each input's pids are offset into a private range (a label metadata
    event names the source file) so per-rank timelines and device traces
    coexist; timestamps are preserved — every producer in this package
    stamps epoch microseconds, so events interleave truthfully. Traces
    from producers with a different zero (``align=True``) are rebased so
    each file's earliest event sits at a common origin instead.

    Returns the number of events written.
    """
    merged = []
    pid_base = 0
    for path in inputs:
        events = _load_trace_events(path)
        pids = [e.get("pid", 0) for e in events]
        max_pid = max(pids, default=0)
        tss = [e["ts"] for e in events if isinstance(e.get("ts"),
                                                     (int, float))]
        base_ts = min(tss, default=0.0)
        # label EVERY pid this file uses (a single label at one pid would
        # orphan device traces whose events sit on nonzero pids)
        label = f"[{path.rsplit('/', 1)[-1]}]"
        for orig_pid in sorted(set(pids)):
            merged.append({"ph": "M", "pid": orig_pid + pid_base, "ts": 0,
                           "name": "process_labels",
                           "args": {"labels": label}})
        for e in events:
            e = dict(e)
            e["pid"] = e.get("pid", 0) + pid_base
            if align and isinstance(e.get("ts"), (int, float)):
                e["ts"] = e["ts"] - base_ts
            merged.append(e)
        pid_base += max_pid + 2
    merged.sort(key=lambda e: (e.get("ts") or 0))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged}, f)
    return len(merged)
