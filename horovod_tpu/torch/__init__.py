"""horovod_tpu.torch — PyTorch binding for the TPU-native framework.

Rebuild of the reference's torch API (reference: horovod/torch/__init__.py
:1-404): ``import horovod_tpu.torch as hvd`` gives the same surface as the
reference — ``hvd.init()``, ``hvd.DistributedOptimizer`` with per-parameter
gradient hooks firing async allreduces as gradients become ready,
``hvd.broadcast_parameters`` / ``hvd.broadcast_optimizer_state`` for the
checkpoint-on-rank-0 convention, and the full sync/async collective op set
with autograd support.

Torch runs on CPU; the collectives run on the XLA data plane through the
dynamic enqueue runtime (negotiation, response cache, tensor fusion —
SURVEY.md §2.1).
"""

import collections
import contextlib


import torch

from horovod_tpu.core.basics import (  # noqa: F401 — re-exported lifecycle
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    mesh,
    is_homogeneous,
    mpi_built,
    gloo_built,
    nccl_built,
    xla_built,
    mpi_enabled,
    mpi_threads_supported,
)
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    poll,
    reducescatter,
    reducescatter_async,
    sparse_allreduce_async,
    synchronize,
)


class _DistributedOptimizer(torch.optim.Optimizer):
    """Optimizer wrapper that allreduces gradients as they become ready.

    Reference: horovod/torch/__init__.py:47-203. Each parameter gets a
    post-accumulate-grad hook; after ``backward_passes_per_step`` backward
    passes the hook fires an async in-place allreduce on the gradient, and
    ``step()`` synchronizes all outstanding handles before applying
    updates, overlapping communication with the remainder of backward.
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1, sparse_as_dense=False):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{i}", v)
                for i, v in enumerate(
                    v for param_group in self.param_groups
                    for v in param_group["params"])
            ]

        # The name is the cross-rank negotiation key: dups break fusion
        # (reference: horovod/torch/__init__.py:66-80).
        all_names = [name for name, _ in named_parameters]
        if len(set(all_names)) < len(all_names):
            seen, dups = set(), set()
            for name in all_names:
                (dups if name in seen else seen).add(name)
            raise ValueError(
                f"parameter names must be unique, duplicates: {sorted(dups)}")
        named_set = {p for _, p in named_parameters}
        for group in self.param_groups:
            for p in group["params"]:
                if p not in named_set:
                    raise ValueError(
                        "named_parameters was specified but one or more "
                        "optimizer parameters were not named")

        self._parameter_names = {v: k for k, v in named_parameters}
        self.backward_passes_per_step = backward_passes_per_step
        self._allreduce_delay = {}
        self._handles = {}
        self._grad_accs = []  # keep hook owners alive (legacy path)
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        if size() > 1:
            self._register_hooks()

    def _register_hooks(self):
        """reference: horovod/torch/__init__.py:108-126 (expand_as
        grad_fn trick); torch>=2.1 has a first-class API for it."""
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    if hasattr(p, "register_post_accumulate_grad_hook"):
                        p.register_post_accumulate_grad_hook(
                            self._make_post_hook(p))
                    else:  # pragma: no cover — old torch
                        p_tmp = p.expand_as(p)
                        grad_acc = p_tmp.grad_fn.next_functions[0][0]
                        grad_acc.register_hook(self._make_hook(p))
                        self._grad_accs.append(grad_acc)

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(p)
        if p.grad.is_sparse:
            # embedding-style sparse grads: allgather exchange (BASELINE
            # config #5) unless the user asked to densify
            if self._sparse_as_dense:
                p.grad = p.grad.to_dense()
            else:
                return sparse_allreduce_async(p.grad, average=True,
                                              name=name)
        return allreduce_async_(p.grad, average=True, name=name,
                                compression=self._compression)

    def _make_post_hook(self, p):
        def hook(param):
            self._mark_ready(p)

        return hook

    def _make_hook(self, p):  # pragma: no cover — old torch
        def hook(*ignore):
            self._mark_ready(p)

        return hook

    def _mark_ready(self, p):
        """reference: horovod/torch/__init__.py:127-143."""
        if p in self._handles and self._handles[p] is not None:
            if self._allreduce_delay[p] <= 0:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally.")
        assert not p.grad.requires_grad
        assert self._allreduce_delay[p] > 0
        self._allreduce_delay[p] -= 1
        if self._allreduce_delay[p] == 0:
            self._handles[p] = self._allreduce_grad_async(p)

    def synchronize(self):
        """Wait for all outstanding allreduces and restore dtypes
        (reference: horovod/torch/__init__.py:145-183)."""
        missing = [p for p in self._requires_update
                   if p not in self._handles]
        for p in missing:
            self._handles[p] = self._allreduce_grad_async(p)
            self._allreduce_delay[p] = 0
        for p, handle in self._handles.items():
            if handle is None:
                continue
            output = synchronize(handle)
            self._allreduce_delay[p] = self.backward_passes_per_step
            if output.is_sparse:
                # sparse result replaces the grad wholesale (no dense
                # storage to copy into)
                p.grad = output.to(p.grad.dtype)
            elif output is not p.grad:
                p.grad.data = output.to(p.grad.dtype)
        self._handles.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """For callers that invoked ``synchronize()`` manually before
        ``step()`` (reference: horovod/torch/__init__.py:185-193)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called after optimizer.synchronize() "
                    "but outside the optimizer.skip_synchronize() context — "
                    "gradients will be allreduced a second time, slowing "
                    "training; wrap step() in skip_synchronize()")
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        """API-misuse race detection (reference:
        horovod/torch/__init__.py:197-202, SURVEY.md §5.2): zeroing grads
        while async allreduces are reading them corrupts the average."""
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(). "
                "This is prohibited as it can cause a race condition.")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         sparse_as_dense=False):
    """Wrap a torch optimizer for distributed gradient averaging
    (reference: horovod/torch/__init__.py:205-253). Sparse gradients
    (``nn.Embedding(sparse=True)``) are exchanged by allgather of
    values+indices — BASELINE config #5's embedding exchange — unless
    ``sparse_as_dense`` densifies them first."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, sparse_as_dense)


def broadcast_parameters(params, root_rank=0):
    """Broadcast parameters from root to all workers — model init / resume
    (reference: horovod/torch/__init__.py:255-297). Accepts a
    ``state_dict()`` or an iterable of (name, tensor)."""
    if isinstance(params, dict):
        params = sorted(params.items())
    elif isinstance(params, collections.abc.Iterable):
        params = list(params)
    else:
        raise ValueError("invalid params of type: %s" % type(params))

    handles = []
    for name, p in params:
        if p is None:
            continue
        if not isinstance(p, torch.Tensor):
            # non-tensor state_dict entries (e.g. num_batches_tracked ints)
            continue
        handles.append(broadcast_async_(p.data, root_rank, name=name))
    for handle in handles:
        synchronize(handle)


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcast the optimizer state from root to all workers (reference:
    horovod/torch/__init__.py:299-403 — the reference wraps scalars into
    tensors and broadcasts per-entry with a type-restoration callback;
    here the structure travels once as pickled bytes and tensor state is
    broadcast tensor-wise)."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    # 1. Non-tensor structure (param_groups + scalar state) plus tensor
    #    metadata via one object broadcast.
    skeleton = {
        "param_groups": state_dict["param_groups"],
        "state_scalars": {
            pid: {k: v for k, v in s.items()
                  if not isinstance(v, torch.Tensor)}
            for pid, s in state_dict["state"].items()
        },
        "state_meta": {
            pid: {k: (tuple(v.shape), str(v.dtype))
                  for k, v in s.items() if isinstance(v, torch.Tensor)}
            for pid, s in state_dict["state"].items()
        },
    }
    skeleton = broadcast_object(skeleton, root_rank,
                                name="optimizer.state_skeleton")

    if rank() != root_rank:
        state_dict["param_groups"] = skeleton["param_groups"]
        for pid, scalars in skeleton["state_scalars"].items():
            state_dict["state"].setdefault(pid, {}).update(scalars)

    # 2. Tensor state broadcast tensor-wise (dtype-preserving); non-root
    #    ranks allocate from the skeleton's metadata when missing.
    handles = []
    for pid, meta in skeleton["state_meta"].items():
        for key, (shape, dtype_str) in sorted(meta.items()):
            entry = state_dict["state"].setdefault(pid, {})
            t = entry.get(key)
            if not isinstance(t, torch.Tensor):
                dtype = getattr(torch, dtype_str.replace("torch.", ""))
                t = torch.zeros(shape, dtype=dtype)
                entry[key] = t
            handles.append(
                broadcast_async_(t.data, root_rank,
                                 name=f"optimizer.state.{pid}.{key}"))
    for h in handles:
        synchronize(h)
    optimizer.load_state_dict(state_dict)


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object (used for epochs / RNG
    state in resume flows; reference examples:
    pytorch_imagenet_resnet50.py resume_from_epoch broadcast)."""
    import pickle

    name = name or "broadcast_object"
    if size() == 1:
        return obj
    if rank() == root_rank:
        payload = pickle.dumps(obj)
        sz = torch.tensor([len(payload)], dtype=torch.int64)
    else:
        sz = torch.zeros(1, dtype=torch.int64)
    broadcast_(sz, root_rank, name=f"{name}.size")
    if rank() == root_rank:
        buf = torch.frombuffer(bytearray(payload), dtype=torch.uint8).clone()
    else:
        buf = torch.zeros(int(sz.item()), dtype=torch.uint8)
    broadcast_(buf, root_rank, name=f"{name}.bytes")
    if rank() == root_rank:
        return obj
    return pickle.loads(buf.numpy().tobytes())
