"""Gradient compression algorithms for the torch binding.

TPU-native rebuild of the reference's torch compression (reference:
horovod/torch/compression.py:1-78): compressors shrink the tensor before it
hits the wire/ICI and restore it after. fp16 compression halves allreduce
bytes; on TPU the natural wire dtype is bfloat16 (same byte savings, MXU
native, no overflow rescaling needed), so both are offered.
"""

import torch


class Compressor:
    """Interface for compressing and decompressing a tensor
    (reference: horovod/torch/compression.py:23-35)."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) for later decompression."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        """Restore the tensor to its pre-compression dtype."""
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default: no compression (reference: compression.py:38-49)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to float16 before the collective
    (reference: compression.py:52-75)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if tensor.is_floating_point() and dtype != torch.float16:
            return tensor.to(torch.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.to(ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-idiomatic variant: bfloat16 wire dtype — same 2x byte saving as
    fp16 with float32's exponent range (no overflow on large gradients)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if tensor.is_floating_point() and dtype != torch.bfloat16:
            return tensor.to(torch.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.to(ctx)
        return tensor


class Compression:
    """Optional gradient compression algorithm used during allreduce
    (reference: horovod/torch/compression.py:68-78)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
