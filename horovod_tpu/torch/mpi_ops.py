"""Torch collective ops backed by the TPU-native runtime.

Rebuild of the reference's torch op layer (reference:
horovod/torch/mpi_ops.py:1-439 and the native binding
horovod/torch/mpi_ops_v2.cc:52-232): sync/async op pairs returning integer-
free handles, ``poll``/``synchronize``, in-place variants, and autograd
Functions so collectives are differentiable.

Torch CPU tensors cross into the framework as numpy views (zero-copy;
bfloat16 bridged through ml_dtypes via an int16 reinterpret) and the
collective itself runs on the XLA data plane — the dynamic enqueue runtime
(negotiation + response cache + tensor fusion) when a name is given, exactly
like the reference's EnqueueTensorAllreduce path (reference:
horovod/common/operations.cc:736-843).
"""

import threading

import ml_dtypes
import numpy as np
import torch

from horovod_tpu.ops import collectives as _c

Average = _c.Average
Sum = _c.Sum
Min = _c.Min
Max = _c.Max
Product = _c.Product

# Per-process op counters for auto-generated names (reference:
# horovod/torch/mpi_ops_v2.cc GetOpName — "allreduce.noname.<handle>").
# Assumes all ranks issue unnamed ops in the same order, as the reference
# does; the negotiation layer tolerates cross-rank reordering of *named*
# tensors.
_op_counters = {}
_counter_lock = threading.Lock()


def _op_name(op_kind, name):
    if name is not None:
        return name
    with _counter_lock:
        n = _op_counters.get(op_kind, 0)
        _op_counters[op_kind] = n + 1
    return f"{op_kind}.noname.{n}"


# ---------------------------------------------------------------------------
# torch <-> numpy bridging
# ---------------------------------------------------------------------------

def _to_numpy(tensor: torch.Tensor) -> np.ndarray:
    """Zero-copy view of a CPU torch tensor as numpy; bfloat16 is
    reinterpreted through int16 into ml_dtypes.bfloat16 (numpy has no
    native bfloat16)."""
    t = tensor.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    if t.dtype == torch.bfloat16:
        return t.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _from_numpy(array, like: torch.Tensor) -> torch.Tensor:
    """Array (numpy or jax) back to a torch tensor with ``like``'s dtype."""
    a = np.asarray(array)
    if a.dtype == ml_dtypes.bfloat16:
        out = torch.from_numpy(a.view(np.int16).copy()).view(torch.bfloat16)
    else:
        a = np.ascontiguousarray(a)
        if not a.flags.writeable:  # e.g. a view of a jax.Array buffer
            a = a.copy()
        out = torch.from_numpy(a)
    return out.to(like.dtype) if out.dtype != like.dtype else out


# ---------------------------------------------------------------------------
# Handles
# ---------------------------------------------------------------------------

class TorchHandle:
    """Completion future for a torch collective (reference:
    horovod/torch/handle_manager.cc:21-51 — here the handle owns its result
    instead of indexing a global table, so nothing leaks)."""

    __slots__ = ("_inner", "_output", "_postprocess", "_done")

    def __init__(self, inner, output: torch.Tensor, postprocess=None):
        self._inner = inner
        self._output = output
        self._postprocess = postprocess
        self._done = False

    def poll(self) -> bool:
        return self._done or self._inner.poll()

    def wait(self) -> torch.Tensor:
        if not self._done:
            result = _c.synchronize(self._inner)
            value = _from_numpy(result, self._output)
            if self._postprocess is not None:
                value = self._postprocess(value)
            if value.numel() == self._output.numel():
                # True in-place: write into the existing storage so views
                # sharing it (e.g. state_dict() entries aliasing model
                # parameters) observe the result — the reference's C++
                # binding writes into the tensor buffer the same way.
                self._output.data.copy_(value.reshape(self._output.shape))
            else:  # ragged allgather: output size unknown until completion
                self._output.data = value
            self._done = True
        return self._output


class _ReadyHandle:
    """Handle for an already-complete result (world size 1 fast path)."""

    __slots__ = ("_output",)

    def __init__(self, output):
        self._output = output

    def poll(self):
        return True

    def wait(self):
        return self._output


def poll(handle) -> bool:
    """True if the collective backing ``handle`` completed (reference:
    horovod/torch/mpi_ops.py:93-105)."""
    return handle.poll()


def synchronize(handle) -> torch.Tensor:
    """Block until the collective completes; returns the output tensor
    (reference: horovod/torch/mpi_ops.py:107-124)."""
    return handle.wait()


# ---------------------------------------------------------------------------
# Core async ops
# ---------------------------------------------------------------------------

def _world_size() -> int:
    from horovod_tpu.core import basics

    return basics._ensure_init().size


def allreduce_async(tensor, average=True, name=None, compression=None):
    """Async allreduce into a NEW tensor; returns a handle (reference:
    horovod/torch/mpi_ops.py:126-160)."""
    from horovod_tpu.torch.compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    output = torch.empty_like(tensor)
    post = (lambda t: compression.decompress(t, ctx)) if ctx is not None \
        else None
    if _world_size() == 1:
        value = compression.decompress(compressed.clone(), ctx)
        output.data = value.to(tensor.dtype)
        return _ReadyHandle(output)
    inner = _c.allreduce_async(
        _to_numpy(compressed), average=average,
        name=_op_name("allreduce", name))
    return TorchHandle(inner, output, post)


def allreduce_async_(tensor, average=True, name=None, compression=None):
    """Async IN-PLACE allreduce: result lands in ``tensor`` (reference:
    horovod/torch/mpi_ops.py:190-216)."""
    from horovod_tpu.torch.compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    post = (lambda t: compression.decompress(t, ctx)) if ctx is not None \
        else None
    if _world_size() == 1:
        if ctx is not None:
            tensor.data = compression.decompress(compressed, ctx).to(
                tensor.dtype)
        return _ReadyHandle(tensor)
    inner = _c.allreduce_async(
        _to_numpy(compressed), average=average,
        name=_op_name("allreduce", name))
    return TorchHandle(inner, tensor, post)


class SparseHandle:
    """Completion future for a sparse (COO) allreduce — the allgather
    exchange of BASELINE config #5 (values+indices travel; duplicates
    sum on coalesce, so densify(allgather(sparse)) ==
    allreduce(densify(sparse)); reference: the TF binding's
    IndexedSlices gather path, horovod/tensorflow/__init__.py:64-75 —
    the reference's torch binding never grew this and densifies
    instead)."""

    __slots__ = ("_h_idx", "_h_vals", "_shape", "_average", "_done",
                 "_result")

    def __init__(self, h_idx, h_vals, shape, average):
        self._h_idx = h_idx
        self._h_vals = h_vals
        self._shape = shape
        self._average = average
        self._done = False
        self._result = None

    def poll(self) -> bool:
        return self._done or (self._h_idx.poll() and self._h_vals.poll())

    def wait(self) -> torch.Tensor:
        if not self._done:
            idx = synchronize(self._h_idx)     # (nnz_total, sparse_ndim)
            vals = synchronize(self._h_vals)   # (nnz_total, *dense_dims)
            if self._average:
                vals = vals / _world_size()
            self._result = torch.sparse_coo_tensor(
                idx.t().contiguous(), vals, self._shape).coalesce()
            self._done = True
        return self._result


def sparse_allreduce_async(tensor, average=True, name=None):
    """Async allreduce of a torch sparse COO tensor via the allgather
    exchange: every rank's (indices, values) are gathered (ragged dim 0),
    duplicates sum on coalesce — an exact allreduce of the represented
    dense tensor without densifying it (BASELINE config #5's
    allgather/sparse embedding exchange)."""
    t = tensor.coalesce()
    name = _op_name("sparse_allreduce", name)
    if _world_size() == 1:
        # average over one rank is identity, so values pass through
        return _ReadyHandle(torch.sparse_coo_tensor(
            t.indices(), t.values(), t.shape).coalesce())
    h_idx = allgather_async(t.indices().t().contiguous(),
                            name=f"{name}.indices")
    h_vals = allgather_async(t.values().contiguous(),
                             name=f"{name}.values")
    return SparseHandle(h_idx, h_vals, t.shape, average)


def allgather_async(tensor, name=None):
    """Async allgather: concatenates each worker's tensor along dim 0
    (reference: horovod/torch/mpi_ops.py:219-246). Supports ragged dim 0."""
    world = _world_size()
    if world == 1:
        return _ReadyHandle(tensor.clone())
    out_shape = (0,) + tuple(tensor.shape[1:])  # fixed up at wait
    output = torch.empty(out_shape, dtype=tensor.dtype)
    inner = _c.allgather_async(_to_numpy(tensor),
                               name=_op_name("allgather", name))
    return TorchHandle(inner, output)


def broadcast_async(tensor, root_rank, name=None):
    """Async broadcast into a NEW tensor (reference:
    horovod/torch/mpi_ops.py:256-283)."""
    if _world_size() == 1:
        return _ReadyHandle(tensor.clone())
    output = torch.empty_like(tensor)
    inner = _c.broadcast_async(_to_numpy(tensor), root_rank,
                               name=_op_name("broadcast", name))
    return TorchHandle(inner, output)


def broadcast_async_(tensor, root_rank, name=None):
    """Async IN-PLACE broadcast (reference: mpi_ops.py:313-340)."""
    if _world_size() == 1:
        return _ReadyHandle(tensor)
    inner = _c.broadcast_async(_to_numpy(tensor), root_rank,
                               name=_op_name("broadcast", name))
    return TorchHandle(inner, tensor)


def _multiprocess_runtime() -> bool:
    from horovod_tpu.core import basics

    st = basics._ensure_init()
    return _c._multiprocess_world(st) and _c._runtime_capable(st)


def reducescatter_async(tensor, op=None, name=None):
    """Async reduce-scatter: reduce across workers, worker i keeps shard i
    of dim 0 (TPU extension mirroring the core API — the reference's
    binding has no reducescatter; role reference:
    ops/nccl_operations.cc:150-346). ``op`` is one of
    Sum/Average/Min/Max/Product; omitted means Average — the SAME
    default as the core API's ``_resolve_op`` (a binding defaulting to
    Sum would silently return world-times-larger results to code
    migrating between surfaces). dim 0 must divide evenly by the world
    size. In the single-controller world (replicated model) the result
    is worker 0's shard."""
    world = _world_size()
    if tensor.shape[0] % world:
        raise ValueError(
            f"reducescatter dim 0 ({tensor.shape[0]}) must divide evenly "
            f"by size ({world})")
    if world == 1:
        return _ReadyHandle(tensor.clone())
    red_op = _c.Average if op is None else op
    x = _to_numpy(tensor)
    out_shape = (tensor.shape[0] // world,) + tuple(tensor.shape[1:])
    if _multiprocess_runtime():
        from horovod_tpu.runtime.runtime import get_runtime

        inner = get_runtime().enqueue_reducescatter(
            _op_name("reducescatter", name), _c._to_plane(x),
            reduce_op=_c._OP_NAMES[red_op])
        return TorchHandle(inner,
                           torch.empty(out_shape, dtype=tensor.dtype))
    result = _c._replicated_rs_a2a("reducescatter", x, world, red_op)
    return _ReadyHandle(_from_numpy(result, tensor))


def reducescatter(tensor, op=None, name=None):
    """Sync reduce-scatter (see :func:`reducescatter_async`)."""
    return synchronize(reducescatter_async(tensor, op=op, name=name))


def alltoall_async(tensor, name=None):
    """Async all-to-all: split dim 0 into ``size`` chunks, send chunk j to
    worker j, receive one chunk from every worker (TPU extension
    mirroring the core API; enables Ulysses-style sequence exchange).
    dim 0 must divide evenly by the world size. In the single-controller
    world (replicated model) the result is worker 0's received tensor."""
    world = _world_size()
    if tensor.shape[0] % world:
        raise ValueError(
            f"alltoall dim 0 ({tensor.shape[0]}) must divide evenly by "
            f"size ({world})")
    if world == 1:
        return _ReadyHandle(tensor.clone())
    x = _to_numpy(tensor)
    if _multiprocess_runtime():
        from horovod_tpu.runtime.runtime import get_runtime

        inner = get_runtime().enqueue_alltoall(
            _op_name("alltoall", name), _c._to_plane(x))
        return TorchHandle(inner, torch.empty_like(tensor))
    result = _c._replicated_rs_a2a("alltoall", x, world, None)
    return _ReadyHandle(_from_numpy(result, tensor))


def alltoall(tensor, name=None):
    """Sync all-to-all (see :func:`alltoall_async`)."""
    return synchronize(alltoall_async(tensor, name=name))


# ---------------------------------------------------------------------------
# Autograd-aware sync ops
# ---------------------------------------------------------------------------

class _AllreduceFunction(torch.autograd.Function):
    """grad(allreduce) = allreduce(grad) (reference:
    horovod/torch/mpi_ops.py:118-131)."""

    @staticmethod
    def forward(ctx, tensor, average, name):
        ctx.average = average
        ctx.name = name
        return synchronize(allreduce_async(tensor, average, name))

    @staticmethod
    def backward(ctx, grad_output):
        name = f"{ctx.name}.grad" if ctx.name else None
        return synchronize(
            allreduce_async(grad_output, ctx.average, name)), None, None


class _AllgatherFunction(torch.autograd.Function):
    """grad(allgather) = this rank's slice of allreduce(grad)
    (reference: horovod/torch/mpi_ops.py:247-253)."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0]
        ctx.name = name
        return synchronize(allgather_async(tensor, name))

    @staticmethod
    def backward(ctx, grad_output):
        # Offsets are only needed here, so the sizes gather runs in
        # backward — forward-only callers never pay for it.
        from horovod_tpu.core import basics

        st = basics._ensure_init()
        if st.size == 1:
            offset = 0
        else:
            sizes = _c.synchronize(_c.allgather_async(
                np.array([ctx.dim0], np.int64),
                name=_op_name("allgather", ctx.name) + ".sizes"))
            sizes = np.asarray(sizes).reshape(-1)
            offset = int(np.sum(sizes[: st.rank]))
        name = f"{ctx.name}.grad" if ctx.name else None
        summed = synchronize(allreduce_async(grad_output, average=False,
                                             name=name))
        return summed[offset: offset + ctx.dim0], None


class _BroadcastFunction(torch.autograd.Function):
    """grad(broadcast) = allreduce(grad), zeroed on non-root ranks
    (reference: horovod/torch/mpi_ops.py:283-311)."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        ctx.name = name
        return synchronize(broadcast_async(tensor, root_rank, name))

    @staticmethod
    def backward(ctx, grad_output):
        from horovod_tpu.core import basics

        name = f"{ctx.name}.grad" if ctx.name else None
        summed = synchronize(allreduce_async(grad_output, average=False,
                                             name=name))
        if basics._ensure_init().rank != ctx.root_rank:
            summed = torch.zeros_like(summed)
        return summed, None, None


def allreduce(tensor, average=True, name=None, compression=None):
    """Differentiable sync allreduce (reference: mpi_ops.py:126-160)."""
    from horovod_tpu.torch.compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    reduced = _AllreduceFunction.apply(compressed, average, name)
    return compression.decompress(reduced, ctx)


def allreduce_(tensor, average=True, name=None):
    """Sync in-place allreduce (reference: mpi_ops.py:190-216)."""
    return synchronize(allreduce_async_(tensor, average, name))


def allgather(tensor, name=None):
    """Differentiable sync allgather (reference: mpi_ops.py:219-253)."""
    return _AllgatherFunction.apply(tensor, name)


def broadcast(tensor, root_rank, name=None):
    """Differentiable sync broadcast (reference: mpi_ops.py:256-311)."""
    return _BroadcastFunction.apply(tensor, root_rank, name)


def broadcast_(tensor, root_rank, name=None):
    """Sync in-place broadcast (reference: mpi_ops.py:313-340)."""
    return synchronize(broadcast_async_(tensor, root_rank, name))
