"""Request-level distributed tracing + the SLO plane.

Every observability layer before this one is step- or process-centric:
metrics (metrics.py) aggregate, the flight recorder (flight_recorder.py)
keeps crash-time evidence, the step profiler (profiler.py) attributes
one *training* step. None of them can answer the serving question "where
did request X's p99 go" — queue wait, KV-transport hop, admission delay,
prefill, decode-block contention, or a requeue after a replica death.

This module is the Dapper-style answer:

* **Trace context.** Every request gets a ``trace_id`` at submit
  (:func:`new_trace_id`); the id rides the request through both queue
  transports (``Request.trace_id`` is part of the KV wire format, so the
  context crosses process boundaries inside the ``serve.req.<rank>``
  record) and into every flight-recorder event on the serve path.
* **Spans.** Each lifecycle phase — submit, queue wait, prefill, decode
  block, response, plus the training plane's collectives — records one
  span: a small dict appended to a ``maxlen``-bounded deque (GIL-atomic,
  no lock, same hot-path philosophy as the flight recorder ring). Spans
  are recorded at END time; an abandoned phase simply never appears.
  Spans serialize into the profiler dump (``request_spans``) and merge
  into the Perfetto trace as per-request lanes with flow arrows joining
  one ``trace_id`` across ranks on the ``/_time``-corrected clock
  (profiler.merge_profile_dir).
* **SLOs.** Declared objectives — ``HOROVOD_SLO_TTFT_MS``,
  ``HOROVOD_SLO_LATENCY_MS``, ``HOROVOD_SLO_AVAILABILITY`` — tracked as
  rolling good/bad windows with error-budget and burn-rate gauges
  (``horovod_slo_*``), a ``GET /slo`` route (metrics.py), burn-rate
  threshold crossings as flight-recorder events (surfaced by ``tpurun
  --postmortem``), and per-request span summaries attached to the
  slowest-request exemplars.

Knobs: ``HOROVOD_TRACE`` (default on; ``0`` disables; an integer > 1
sets the span ring capacity, default 4096), ``HOROVOD_SLO_TTFT_MS`` /
``HOROVOD_SLO_LATENCY_MS`` (latency objectives, ms),
``HOROVOD_SLO_AVAILABILITY`` (compliance target for all three
objectives, default 0.999), ``HOROVOD_SLO_WINDOW`` (rolling window, in
requests, default 512), ``HOROVOD_SLO_BURN_ALERT`` (burn-rate crossing
that emits an ``slo_burn_rate`` flight event, default 14 — the classic
fast-burn page threshold). docs/tracing.md is the full model.
"""

from __future__ import annotations

import os
import uuid
from collections import deque
from typing import Dict, List, Optional

from horovod_tpu.analysis import witness
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils.env import (DEFAULT_SLO_WINDOW,
                                   DEFAULT_TRACE_CAPACITY, HOROVOD_SLO_AVAILABILITY,
                                   HOROVOD_SLO_BURN_ALERT,
                                   HOROVOD_SLO_LATENCY_MS, HOROVOD_SLO_TTFT_MS,
                                   HOROVOD_SLO_WINDOW, HOROVOD_TRACE,
                                   _get_float, _get_int, parse_trace)

SCHEMA = "horovod-tracing-v1"
OBJECTIVES = ("ttft", "latency", "availability")
# slowest-request exemplars kept (each carries its span summary)
_EXEMPLARS_MAX = 8

_SPANS_TOTAL = _metrics().counter(
    "horovod_trace_spans_total",
    "Spans recorded into the tracing ring buffer.")
_SLO_EVENTS = _metrics().counter(
    "horovod_slo_events_total",
    "Requests scored against each SLO objective, by verdict.",
    labelnames=("objective", "verdict"))
_SLO_BURN = _metrics().gauge(
    "horovod_slo_burn_rate",
    "Observed bad-event rate over the rolling window divided by the "
    "rate the objective allows (1.0 = burning budget exactly at the "
    "sustainable rate).",
    labelnames=("objective",))
_SLO_BUDGET = _metrics().gauge(
    "horovod_slo_error_budget_remaining",
    "Fraction of the rolling window's error budget still unspent "
    "(1.0 = clean window, 0.0 = budget exhausted).",
    labelnames=("objective",))
_SLO_ALERTS = _metrics().counter(
    "horovod_slo_burn_alerts_total",
    "Burn-rate threshold crossings (HOROVOD_SLO_BURN_ALERT).",
    labelnames=("objective",))


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id (the wire format everywhere)."""
    return uuid.uuid4().hex[:16]


class Tracer:
    """Bounded span ring. ``record`` is the hot path: build one small
    dict, append to a maxlen deque — atomic under the GIL, no lock, old
    spans overwritten in O(1)."""

    def __init__(self) -> None:
        enabled, capacity = parse_trace(os.environ.get(HOROVOD_TRACE))
        self.enabled = enabled
        self.capacity = capacity
        self.rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        self._spans: deque = deque(maxlen=capacity)

    def configure(self, rank: Optional[int] = None) -> None:
        """Re-read the env knobs (called from ``hvd.init()``, including
        elastic re-init where the rank may have changed)."""
        enabled, capacity = parse_trace(os.environ.get(HOROVOD_TRACE))
        self.enabled = enabled
        if capacity != self.capacity:
            self._spans = deque(self._spans, maxlen=capacity)
            self.capacity = capacity
        if rank is not None:
            self.rank = rank

    # -- hot path ------------------------------------------------------------
    def record(self, name: str, t0: float, dur: float,
               trace_id: str = "", **attrs) -> None:
        """Record one finished span. ``t0`` is epoch seconds (the
        package-wide trace clock domain, correctable by the rendezvous
        ``/_time`` offset at merge time); ``dur`` is seconds."""
        if not self.enabled:
            return
        span = {"trace_id": trace_id, "name": name, "t": t0,
                "dur": dur, "rank": self.rank}
        span.update(attrs)
        self._spans.append(span)  # GIL-atomic; maxlen evicts the oldest
        _SPANS_TOTAL.inc()

    def spans(self) -> List[dict]:
        return list(self._spans)

    def spans_recorded(self) -> int:
        return int(_SPANS_TOTAL.value)


class SLOTracker:
    """Rolling good/bad windows per objective + burn-rate alerting.

    One window per objective, ``HOROVOD_SLO_WINDOW`` requests deep. The
    compliance target for every objective is ``HOROVOD_SLO_AVAILABILITY``
    (e.g. 0.999 → "99.9% of requests complete, 99.9% of completions meet
    each latency objective"), so the allowed bad fraction — the error
    budget — is ``1 - target``. Burn rate is the observed bad fraction
    divided by the allowed one: 1.0 spends the budget exactly at the
    sustainable rate, 14 is the classic fast-burn page. Crossing
    ``HOROVOD_SLO_BURN_ALERT`` upward emits ONE ``slo_burn_rate``
    flight-recorder event (re-armed when the rate falls back under), so
    a sustained burn is one postmortem line, not a storm."""

    def __init__(self) -> None:
        self._lock = witness.make_lock("SLOTracker._lock")
        self.configure()

    def configure(self) -> None:
        window = max(1, _get_int(HOROVOD_SLO_WINDOW, DEFAULT_SLO_WINDOW))
        with self._lock:
            self.ttft_ms = _get_float(HOROVOD_SLO_TTFT_MS, 1000.0)
            self.latency_ms = _get_float(HOROVOD_SLO_LATENCY_MS, 10000.0)
            self.target = min(1.0 - 1e-9, max(
                0.0, _get_float(HOROVOD_SLO_AVAILABILITY, 0.999)))
            self.burn_alert = _get_float(HOROVOD_SLO_BURN_ALERT, 14.0)
            self.window = window
            # guarded-by: _lock
            self._windows: Dict[str, deque] = {
                obj: deque(maxlen=window) for obj in OBJECTIVES}
            self._alerting: Dict[str, bool] = {
                obj: False for obj in OBJECTIVES}
            self._latencies: deque = deque(maxlen=window)   # ms
            self._ttfts: deque = deque(maxlen=window)       # ms
            self._exemplars: List[dict] = []
            self._requests = 0
            self._bad = {obj: 0 for obj in OBJECTIVES}  # cumulative

    # -- recording -----------------------------------------------------------
    def record_request(self, ttft_s: float, latency_s: float,
                       ok: bool = True, trace_id: str = "", rank: int = 0,
                       requeues: int = 0,
                       phases: Optional[Dict[str, float]] = None) -> None:
        """Score one finished request against every objective.

        ``ok=False`` (rejected / never served) is an availability bad
        event and skips the latency objectives — an unserved request has
        no meaningful TTFT. ``phases`` (name -> seconds) feeds the
        slowest-phase attribution on slow-request exemplars."""
        verdicts = {"availability": ok}
        if ok:
            verdicts["ttft"] = ttft_s * 1000.0 <= self.ttft_ms
            verdicts["latency"] = latency_s * 1000.0 <= self.latency_ms
        alerts = []
        with self._lock:
            self._requests += 1
            for obj, good in verdicts.items():
                self._windows[obj].append(good)
                if not good:
                    self._bad[obj] += 1
                burn = self._burn_rate_locked(obj)
                if burn >= self.burn_alert and not self._alerting[obj]:
                    self._alerting[obj] = True
                    alerts.append((obj, burn))
                elif burn < self.burn_alert:
                    self._alerting[obj] = False
            if ok:
                self._latencies.append(latency_s * 1000.0)
                self._ttfts.append(ttft_s * 1000.0)
                self._note_exemplar_locked(
                    trace_id, ttft_s, latency_s, rank, requeues, phases)
        for obj, good in verdicts.items():
            _SLO_EVENTS.labels(objective=obj,
                               verdict="good" if good else "bad").inc()
            _SLO_BURN.labels(objective=obj).set(self.burn_rate(obj))
            _SLO_BUDGET.labels(objective=obj).set(
                self.error_budget_remaining(obj))
        # flight emission outside the lock: emit is lock-free but cheap
        # hygiene all the same (never do foreign work under a lock)
        for obj, burn in alerts:
            _SLO_ALERTS.labels(objective=obj).inc()
            from horovod_tpu import flight_recorder

            flight_recorder.emit(
                "slo_burn_rate", objective=obj, burn_rate=round(burn, 2),
                threshold=self.burn_alert, window=self.window,
                trace_id=trace_id)

    def _note_exemplar_locked(self, trace_id: str, ttft_s: float,
                              latency_s: float, rank: int, requeues: int,
                              phases: Optional[Dict[str, float]]) -> None:
        # guarded-by: _lock. Keep the _EXEMPLARS_MAX slowest requests,
        # each with its span summary (slowest phase + requeue count) —
        # the "why was THIS one slow" attachment on the /slo route.
        slowest_phase = None
        if phases:
            slowest_phase = max(phases, key=lambda k: phases[k])
        self._exemplars.append({
            "trace_id": trace_id,
            "latency_ms": round(latency_s * 1000.0, 3),
            "ttft_ms": round(ttft_s * 1000.0, 3),
            "rank": rank,
            "requeues": requeues,
            "slowest_phase": slowest_phase,
            "phases_ms": {k: round(v * 1000.0, 3)
                          for k, v in (phases or {}).items()},
        })
        self._exemplars.sort(key=lambda e: e["latency_ms"], reverse=True)
        del self._exemplars[_EXEMPLARS_MAX:]

    # -- math ----------------------------------------------------------------
    def _bad_fraction_locked(self, objective: str) -> float:
        window = self._windows[objective]
        if not window:
            return 0.0
        return sum(1 for good in window if not good) / len(window)

    def _burn_rate_locked(self, objective: str) -> float:
        allowed = 1.0 - self.target
        return self._bad_fraction_locked(objective) / allowed

    def burn_rate(self, objective: str) -> float:
        with self._lock:
            return self._burn_rate_locked(objective)

    def error_budget_remaining(self, objective: str) -> float:
        with self._lock:
            return max(0.0, 1.0 - self._burn_rate_locked(objective))

    @staticmethod
    def _percentile(values: List[float], q: float) -> Optional[float]:
        if not values:
            return None
        values = sorted(values)
        idx = min(len(values) - 1, int(round(q * (len(values) - 1))))
        return values[idx]

    def state(self) -> dict:
        """JSON-ready document for the ``GET /slo`` route."""
        with self._lock:
            lat = list(self._latencies)
            ttft = list(self._ttfts)
            doc = {
                "schema": SCHEMA,
                "objectives": {
                    "ttft_ms": self.ttft_ms,
                    "latency_ms": self.latency_ms,
                    "availability": self.target,
                },
                "window_requests": self.window,
                "requests_scored": self._requests,
                "burn_alert_threshold": self.burn_alert,
                "slo": {
                    obj: {
                        "window_observed": len(self._windows[obj]),
                        "bad_fraction": round(
                            self._bad_fraction_locked(obj), 6),
                        "burn_rate": round(self._burn_rate_locked(obj), 4),
                        "error_budget_remaining": round(max(
                            0.0, 1.0 - self._burn_rate_locked(obj)), 4),
                        "alerting": self._alerting[obj],
                        "bad_total": self._bad[obj],
                    } for obj in OBJECTIVES},
                "latency_ms_percentiles": {
                    "p50": self._percentile(lat, 0.50),
                    "p99": self._percentile(lat, 0.99)},
                "ttft_ms_percentiles": {
                    "p50": self._percentile(ttft, 0.50),
                    "p99": self._percentile(ttft, 0.99)},
                "slow_request_exemplars": list(self._exemplars),
            }
        doc["spans_recorded"] = _tracer.spans_recorded()
        doc["rank"] = _tracer.rank
        return doc


_tracer = Tracer()
_slo = SLOTracker()

# readiness flags for the /healthz route (metrics.py). hvd.init() marks
# initialized; the serve plane marks started (a replica/handle exists)
# and heartbeat-seen (the first replica heartbeat fired) — an external
# load balancer must not route to a worker whose replicas never came up.
_init_ready = False
_serve_started = False
_serve_heartbeat_seen = False


def tracer() -> Tracer:
    return _tracer


def slo() -> SLOTracker:
    return _slo


def enabled() -> bool:
    return _tracer.enabled


def record(name: str, t0: float, dur: float, trace_id: str = "",
           **attrs) -> None:
    """Record one finished span (module-level hot-path entry point)."""
    _tracer.record(name, t0, dur, trace_id=trace_id, **attrs)


def spans() -> List[dict]:
    return _tracer.spans()


def configure(rank: Optional[int] = None) -> None:
    """Adopt the rank, re-read knobs, register the flight-recorder state
    provider and mark the process initialized (called from
    ``hvd.init()``)."""
    global _init_ready
    _tracer.configure(rank=rank)
    _slo.configure()
    _init_ready = True
    from horovod_tpu import flight_recorder

    flight_recorder.set_state_provider("slo", slo_state)


def mark_initialized(ready: bool = True) -> None:
    global _init_ready
    _init_ready = ready


def note_serve_started() -> None:
    global _serve_started
    _serve_started = True


def note_replica_heartbeat() -> None:
    global _serve_heartbeat_seen
    _serve_heartbeat_seen = True


def slo_state() -> dict:
    """``GET /slo`` document (also the flight-recorder "slo" state
    provider, so every postmortem dump carries the SLO posture)."""
    return _slo.state()


def healthz_state() -> dict:
    """``GET /healthz`` readiness document. ``ready`` gates the HTTP
    status: 200 only after ``hvd.init()`` ran and — when this process is
    serving — after the first replica heartbeat, so external load
    balancers can gate traffic on it (docs/metrics.md)."""
    ready = _init_ready and (not _serve_started or _serve_heartbeat_seen)
    return {"ready": ready,
            "initialized": _init_ready,
            "serving": _serve_started,
            "first_replica_heartbeat": _serve_heartbeat_seen}


# ---------------------------------------------------------------------------
# Chrome-trace conversion (profiler.merge_profile_dir)
# ---------------------------------------------------------------------------

def spans_to_chrome(span_list: List[dict], tid: int = 2) -> List[dict]:
    """Request spans as Chrome duration ("X") events on their own lane
    (tid 2 keeps them clear of step markers tid 0 / flight instants
    tid 1), epoch-us clock — merge_profile_dir shifts them onto the
    launcher's clock per rank."""
    out = []
    for span in span_list:
        t = span.get("t")
        dur = span.get("dur")
        if not isinstance(t, (int, float)) or \
                not isinstance(dur, (int, float)):
            continue
        args = {k: v for k, v in span.items()
                if k not in ("t", "dur", "name")}
        out.append({"ph": "X", "pid": 0, "tid": tid, "ts": t * 1e6,
                    "dur": max(dur, 0.0) * 1e6,
                    "name": str(span.get("name", "span")),
                    "cat": "request", "args": args})
    return out


def flow_events(anchors: List[dict]) -> List[dict]:
    """Perfetto flow arrows joining one ``trace_id``'s spans across
    lanes. ``anchors`` are merged-clock span anchors — dicts with
    ``trace_id``, ``pid``, ``tid``, ``ts`` (already offset-corrected
    merged-trace us) and ``dur`` — typically collected by
    merge_profile_dir while it lays out the per-rank request lanes.
    Per trace: the earliest span starts the flow ("s"), the latest
    finishes it ("f", bound to the enclosing slice), everything between
    is a step ("t")."""
    by_trace: Dict[str, List[dict]] = {}
    for a in anchors:
        tid = a.get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(a)
    out = []
    for trace_id, group in by_trace.items():
        if len(group) < 2:
            continue  # a single-span trace has nothing to join
        group.sort(key=lambda a: a["ts"])
        last = len(group) - 1
        for i, a in enumerate(group):
            ph = "s" if i == 0 else ("f" if i == last else "t")
            ev = {"ph": ph, "id": trace_id, "cat": "request",
                  "name": "request", "pid": a["pid"], "tid": a["tid"],
                  # flow points bind to the slice under them: anchor the
                  # start at the span's end (the hand-off moment) and
                  # steps/finish at the span's start (the receipt)
                  "ts": a["ts"] + (a.get("dur", 0.0) if i == 0 else 0.0)}
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)
    return out


def format_slo_report(dumps: List[dict]) -> str:
    """Cross-rank SLO section for ``tpurun --postmortem``: per-rank burn
    rates/budgets from each dump's "slo" state (empty string when no
    dump carries one — pre-tracing dumps render unchanged)."""
    rows = []
    for d in sorted(dumps, key=lambda d: d.get("launch_rank", 0)):
        state = (d.get("state") or {}).get("slo")
        if not isinstance(state, dict) or not state.get("slo"):
            continue
        rank = d.get("launch_rank", d.get("rank", "?"))
        parts = []
        for obj in OBJECTIVES:
            rec = state["slo"].get(obj) or {}
            parts.append("%s burn=%.2f budget=%.0f%%%s" % (
                obj, rec.get("burn_rate", 0.0),
                100.0 * rec.get("error_budget_remaining", 1.0),
                " ALERT" if rec.get("alerting") else ""))
        rows.append("rank %s: %d scored  %s" % (
            rank, state.get("requests_scored", 0), "  ".join(parts)))
        for ex in (state.get("slow_request_exemplars") or ())[:3]:
            rows.append(
                "  slow request %s: %.1f ms (ttft %.1f ms, slowest "
                "phase %s, %d requeue%s)" % (
                    ex.get("trace_id", "?"), ex.get("latency_ms", 0.0),
                    ex.get("ttft_ms", 0.0),
                    ex.get("slowest_phase") or "?",
                    ex.get("requeues", 0),
                    "" if ex.get("requeues", 0) == 1 else "s"))
    if not rows:
        return ""
    return "\n".join(["=== SLO report ==="] + rows)
