"""Reusable training-step construction for the example/benchmark workloads.

The reference's examples all follow one pattern (reference: SURVEY.md §2.8,
examples/pytorch_synthetic_benchmark.py:37-100): init → scale LR by size →
wrap optimizer → broadcast initial state → step loop. This module packages
that pattern for flax models so the benchmark harness, the graft entry
point, and the examples share one implementation.

Two SPMD styles are supported, matching ``DistributedOptimizer``:

* ``global-batch`` (default): the step is ``jit``-compiled over the global
  mesh with the batch sharded along ``(cross, local)``; XLA inserts the
  gradient all-reduce from the shardings. This is the TPU-idiomatic hot
  path.
* ``shard_map``: explicit per-device microbatches with the wrapper's
  ``lax.pmean`` — semantically identical, useful when per-device code is
  needed (e.g. sequence parallelism).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.core import basics, mesh as mesh_mod
from horovod_tpu.parallel import dp


@dataclasses.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: int = 0


def create_train_state(model, optimizer, input_shape,
                       rng: Optional[jax.Array] = None,
                       broadcast: bool = True,
                       input_dtype=jnp.float32) -> TrainState:
    """Initialize model + optimizer state and broadcast from rank 0
    (the reference's init convention, reference: examples/*.py).

    ``input_dtype=jnp.int32`` initializes token models (transformers)."""
    if rng is None:
        rng = jax.random.PRNGKey(0)
    variables = init_on_host(model, rng, input_shape, input_dtype)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    if broadcast:
        params = dp.broadcast_parameters(params)
        batch_stats = dp.broadcast_parameters(batch_stats)
    opt_state = optimizer.init(params)
    return TrainState(params=params, batch_stats=batch_stats,
                      opt_state=opt_state)


def init_on_host_fn(build, x):
    """Run a once-only init ``build(x)`` on the LOCAL CPU backend; the
    results move to the accelerator on first use (device_put/jit
    argument transfer).

    Init runs exactly once, so paying a remote accelerator's full
    compile+dispatch for it is pure overhead — on the axon-tunnel chip,
    Inception-V3's init program cost ~4.5 min remote vs 42 s local CPU
    + 6 s transfer (measured r5). On a CPU default backend this is the
    ordinary path. Pallas kernels in the model (flash attention) cannot
    lower for CPU — they run in interpret mode for this one trace
    (param VALUES don't depend on the attention output); anything else
    refusing CPU lowering falls back to the accelerator init."""
    import os

    if jax.default_backend() != "cpu":
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            cpu = None
        if cpu is not None:
            prev = os.environ.get("HOROVOD_PALLAS_INTERPRET")
            os.environ["HOROVOD_PALLAS_INTERPRET"] = "1"
            try:
                with jax.default_device(cpu):
                    return build(x)
            except Exception:
                # CPU-lowering refusals surface as ValueError,
                # NotImplementedError, or XlaRuntimeError depending on
                # the op — any failure here falls back to the
                # accelerator init, where a genuine model bug will
                # re-raise on its own terms
                pass
            finally:
                if prev is None:
                    os.environ.pop("HOROVOD_PALLAS_INTERPRET", None)
                else:
                    os.environ["HOROVOD_PALLAS_INTERPRET"] = prev
    return build(x)


def init_on_host(model, rng, input_shape, input_dtype=jnp.float32):
    """``model.init`` on the local CPU backend (see init_on_host_fn)."""
    import numpy as np

    # a numpy sample is backend-neutral (a device-committed zeros array
    # would fight the default_device context)
    return init_on_host_fn(
        lambda x: model.init(rng, x, train=False),
        np.zeros(input_shape, np.dtype(input_dtype)))


def _default_loss_fn(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def _make_one_step(model, optimizer, loss_fn, grad_release=None):
    """Shared un-jitted train-step body: fwd + grad + optimizer update,
    tolerating models with or without batch statistics.

    With a :class:`~horovod_tpu.parallel.buckets.GradReleasePlan` the
    parameter tree is tagged before the forward pass, so each fusion
    bucket's allreduce releases during backward (eager lane) or stages at
    its backward position (traced lane); the optimizer update then runs
    inside a ``prereduced`` scope so ``DistributedOptimizer`` skips the
    post-hoc exchange."""
    from horovod_tpu.parallel import buckets as buckets_mod

    def one_step(params, batch_stats, opt_state, images, labels):
        def compute(params):
            if grad_release is not None:
                params = grad_release.tag(params)
            outputs, updates = model.apply(
                {"params": params, "batch_stats": batch_stats},
                images, train=True, mutable=["batch_stats"])
            return loss_fn(outputs, labels), updates.get("batch_stats", {})

        (loss, new_stats), grads = jax.value_and_grad(
            compute, has_aux=True)(params)
        if grad_release is not None:
            grads = grad_release.gather(grads)
            with buckets_mod.prereduced():
                updates, new_opt_state = optimizer.update(
                    grads, opt_state, params)
        else:
            updates, new_opt_state = optimizer.update(
                grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), new_stats, \
            new_opt_state

    return one_step


def _shardings():
    st = basics._ensure_init()
    mesh = st.mesh
    batch_sharding = NamedSharding(mesh, P(mesh_mod.GLOBAL_AXES))
    repl = NamedSharding(mesh, P())
    return batch_sharding, repl


def _resolve_grad_release(grad_release):
    """``None`` → honour ``HOROVOD_GRAD_BUCKET_RELEASE``; ``False`` →
    explicitly off; a plan instance → use it.

    When auto-creating a plan, ``HOROVOD_ZERO_STAGE >= 2`` flips it to
    reduce-scatter release so each bucket lands as the local 1/N gradient
    shard (see :mod:`horovod_tpu.parallel.zero`)."""
    from horovod_tpu.parallel import buckets as buckets_mod
    from horovod_tpu.parallel import zero as zero_mod

    if grad_release is None:
        if buckets_mod.release_enabled():
            return buckets_mod.GradReleasePlan(
                reduce_scatter=zero_mod.stage_from_env() >= 2)
        return None
    if grad_release is False:
        return None
    return grad_release


def make_train_step(model, optimizer,
                    loss_fn: Optional[Callable] = None,
                    donate: bool = True,
                    grad_release=None):
    """Build a jitted global-batch DP train step.

    The returned function has signature
    ``step(params, batch_stats, opt_state, images, labels) ->
    (loss, params, batch_stats, opt_state)`` and is compiled over the
    global mesh with inputs batch-sharded; gradient averaging across
    workers falls out of the shardings (see ``parallel/dp.py``).

    ``grad_release`` opts the step into bucket-wise gradient release
    (``None`` honours ``HOROVOD_GRAD_BUCKET_RELEASE``; pass a
    :class:`~horovod_tpu.parallel.buckets.GradReleasePlan` to control
    bucket sizing, or ``False`` to force the post-hoc exchange). On this
    jitted lane the hooks stage the collectives at their backward
    positions; overlap inside one XLA program is the scheduler's, the
    staging just stops it sinking them to the end.
    """
    batch_sharding, repl = _shardings()
    one_step = _make_one_step(model, optimizer, loss_fn or _default_loss_fn,
                              grad_release=_resolve_grad_release(grad_release))
    step_fn = jax.jit(
        one_step,
        in_shardings=(repl, repl, repl, batch_sharding, batch_sharding),
        out_shardings=(repl, repl, repl, repl),
        donate_argnums=(0, 1, 2) if donate else (),
    )
    return _with_integrity_guard(_with_profiler_hook(step_fn)), \
        batch_sharding


def make_train_round(model, optimizer,
                     loss_fn: Optional[Callable] = None,
                     steps: int = 1,
                     donate: bool = True,
                     grad_release=None):
    """Like :func:`make_train_step`, but one compiled program runs
    ``steps`` consecutive train steps via ``lax.scan`` (same batch each
    step — benchmark workloads), returning the last loss.

    One dispatch per round keeps host→device launch latency out of
    steady-state measurements — the same reason the reference times
    multi-batch rounds (reference:
    examples/pytorch_synthetic_benchmark.py:92-100), taken to its XLA
    conclusion: the whole round is a single device program.
    """
    batch_sharding, repl = _shardings()
    one_step = _make_one_step(model, optimizer, loss_fn or _default_loss_fn,
                              grad_release=_resolve_grad_release(grad_release))

    def round_fn(params, batch_stats, opt_state, images, labels):
        def body(carry, _):
            params, stats, opt_state = carry
            loss, params, stats, opt_state = one_step(
                params, stats, opt_state, images, labels)
            return (params, stats, opt_state), loss

        (params, batch_stats, opt_state), losses = jax.lax.scan(
            body, (params, batch_stats, opt_state), None, length=steps)
        return losses[-1], params, batch_stats, opt_state

    round_jit = jax.jit(
        round_fn,
        in_shardings=(repl, repl, repl, batch_sharding, batch_sharding),
        out_shardings=(repl, repl, repl, repl),
        donate_argnums=(0, 1, 2) if donate else (),
    )
    return _with_integrity_guard(_with_profiler_hook(round_jit)), \
        batch_sharding


def _with_integrity_guard(step_fn):
    """Watch the returned loss with the integrity spike guard
    (integrity/guards.py) when HOROVOD_INTEGRITY is on. The step's
    arguments are donated, so a flagged loss cannot un-apply the update
    that produced it — the remedy at this level is the guard's budget
    raise (``NumericalError`` after HOROVOD_INTEGRITY_SKIP_STEPS
    consecutive spikes), which the elastic runner answers with
    rollback-and-replay; the skip-step policy that *suppresses* updates
    lives in ``DistributedOptimizer``. Disabled integrity returns the
    callable untouched, like the profiler hook."""
    from horovod_tpu import integrity

    if not integrity.enabled():
        return step_fn
    from horovod_tpu.integrity import guards

    guard = guards.StepGuard(name="loss")

    def guarded(*args, **kwargs):
        result = step_fn(*args, **kwargs)
        loss = result[0] if isinstance(result, tuple) else result
        try:
            guard.observe(float(loss))
        except TypeError:
            pass  # non-scalar first output: nothing to observe
        return result

    guarded.__wrapped__ = step_fn
    guarded.__integrity_guard__ = guard
    return guarded


def _with_profiler_hook(step_fn):
    """Mark a step boundary per invocation when profiling is enabled
    (profiler.py auto-step: step time = call-to-call interval; the whole
    jitted body attributes as compute). Disabled profiling returns the
    jitted callable untouched — zero wrapper overhead and the jit object's
    own API (``.lower`` etc.) stays reachable."""
    from horovod_tpu import profiler

    if not profiler.enabled():
        return step_fn

    def profiled(*args, **kwargs):
        profiler.auto_step()
        return step_fn(*args, **kwargs)

    profiled.__wrapped__ = step_fn
    return profiled
