"""JAX version-compatibility shims.

The data plane targets a range of JAX releases; helpers here paper over
API drift so the rest of the codebase stays on one spelling.
"""

from __future__ import annotations

from jax import lax


def install() -> None:
    """Install attribute shims for renamed/moved JAX APIs (idempotent).

    Called once at package import. ``jax.shard_map`` graduated from
    ``jax.experimental.shard_map``; on releases that only ship the
    experimental spelling, alias it so the one modern spelling works
    everywhere (library and tests alike).
    """
    import jax

    if not hasattr(jax, "shard_map"):
        import functools

        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, *args, **kwargs):
            # the experimental spelling calls the replication check
            # ``check_rep``; the graduated API renamed it ``check_vma``
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(f, *args, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax, "typeof"):
        # jax.typeof(x) is the modern spelling of the abstract value;
        # callers here only probe optional attrs (e.g. ``vma``) on it
        jax.typeof = jax.core.get_aval

    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams"):
            # renamed from TPUCompilerParams when pallas graduated it
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except Exception:  # pallas TPU backend unavailable on this build
        pass


def axis_size(axis_name):
    """Size of a bound mesh axis (or tuple of axes) inside a trace.

    ``lax.axis_size`` where the installed JAX has it; otherwise a psum
    of the literal 1 over the axis — evaluated statically by tracing to
    the axis size, with the same contract (``NameError`` when the axis
    is not bound in the current trace).
    """
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def pvary(x, axis_names):
    """Mark ``x`` as device-varying over ``axis_names`` (tuple of axes).

    Newer JAX spells this ``lax.pcast(..., to="varying")`` (successor of
    ``lax.pvary``). Releases predating the varying/replicated type system
    have neither and need no cast — identity there.
    """
    fn = getattr(lax, "pcast", None)
    if fn is not None:
        return fn(x, axis_names, to="varying")
    fn = getattr(lax, "pvary", None)
    if fn is not None:
        return fn(x, axis_names)
    return x


_SDS_HAS_VMA = None


def sds(shape, dtype, *, vma=None):
    """``jax.ShapeDtypeStruct`` that forwards ``vma`` where supported.

    Releases predating the varying/replicated type system reject the
    kwarg; there the annotation is meaningless and is dropped.
    """
    import jax

    global _SDS_HAS_VMA
    if vma is None or _SDS_HAS_VMA is False:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        out = jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        _SDS_HAS_VMA = True
        return out
    except TypeError:
        _SDS_HAS_VMA = False
        return jax.ShapeDtypeStruct(shape, dtype)
