"""Environment-variable knob catalog and parsing.

The reference converges three config layers onto environment variables read
at init (reference: horovod/common/common.h:61-85, operations.cc:363-454,
utils/env_parser.cc). We keep the same knob names so launcher flags, config
files and user envs translate 1:1.
"""

from __future__ import annotations

import dataclasses
import os

# Knob names (reference: horovod/common/common.h:61-85 plus gloo/logging).
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_PROBE = "HOROVOD_AUTOTUNE_PROBE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HOROVOD_METRICS_PORT = "HOROVOD_METRICS_PORT"
HOROVOD_METRICS_DUMP = "HOROVOD_METRICS_DUMP"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
# two-level host collectives (runtime/hierarchy.py; docs/performance.md):
# ranks per slice (0 = derive groups from the rendezvous roster's
# hostnames) and the wire dtype of the slow cross-group hop
# (none | fp16 (bf16 on TPU) | ieee_fp16)
HOROVOD_HIERARCHY_GROUP_SIZE = "HOROVOD_HIERARCHY_GROUP_SIZE"
HOROVOD_HIERARCHY_COMPRESSION = "HOROVOD_HIERARCHY_COMPRESSION"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"
HOROVOD_MESH_SHAPE = "HOROVOD_MESH_SHAPE"
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
HOROVOD_CPU_OPERATIONS = "HOROVOD_CPU_OPERATIONS"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
HOROVOD_CYCLE_PIPELINE_DEPTH = "HOROVOD_CYCLE_PIPELINE_DEPTH"
HOROVOD_FUSION_BUCKET_QUANTUM = "HOROVOD_FUSION_BUCKET_QUANTUM"
HOROVOD_FLIGHT_RECORDER = "HOROVOD_FLIGHT_RECORDER"
HOROVOD_FLIGHT_RECORDER_DIR = "HOROVOD_FLIGHT_RECORDER_DIR"
HOROVOD_STRAGGLER_REPORT_SECONDS = "HOROVOD_STRAGGLER_REPORT_SECONDS"
HOROVOD_SHARDED_FUSED_KERNEL = "HOROVOD_SHARDED_FUSED_KERNEL"
HOROVOD_PROFILE = "HOROVOD_PROFILE"
HOROVOD_PROFILE_DIR = "HOROVOD_PROFILE_DIR"
HOROVOD_PROFILE_HISTORY = "HOROVOD_PROFILE_HISTORY"
HOROVOD_PROFILE_JAX = "HOROVOD_PROFILE_JAX"
# deadlock witness (analysis/witness.py): instrument runtime locks,
# record acquisition order, flag inversions / live deadlocks / long holds
HOROVOD_DEBUG_LOCKS = "HOROVOD_DEBUG_LOCKS"
HOROVOD_LOCK_HOLD_WARN_SECONDS = "HOROVOD_LOCK_HOLD_WARN_SECONDS"
# request-level tracing + SLO plane (tracing.py; docs/tracing.md)
HOROVOD_TRACE = "HOROVOD_TRACE"
HOROVOD_SLO_TTFT_MS = "HOROVOD_SLO_TTFT_MS"
HOROVOD_SLO_LATENCY_MS = "HOROVOD_SLO_LATENCY_MS"
HOROVOD_SLO_AVAILABILITY = "HOROVOD_SLO_AVAILABILITY"
HOROVOD_SLO_WINDOW = "HOROVOD_SLO_WINDOW"
HOROVOD_SLO_BURN_ALERT = "HOROVOD_SLO_BURN_ALERT"

# Knobs read at their point of use rather than parsed into Config —
# launcher/rendezvous wiring that exists before hvd.init() runs, elastic
# re-form parameters rewritten between generations, and test/debug
# switches. Registered here so tools/check_env_knobs.py can verify the
# complete catalog lives in this module: a knob missing from both Config
# and this tuple fails CI as UNREGISTERED.
ENV_DIRECT_KNOBS = (
    # identity / wiring injected by the launcher before init
    "HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
    "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE",
    "HOROVOD_CONTROLLER", "HOROVOD_COORDINATOR_ADDR", "HOROVOD_HOSTNAME",
    "HOROVOD_PROCESS_ID", "HOROVOD_SECRET_KEY", "HOROVOD_TASK_KEY",
    "HOROVOD_NP", "HOROVOD_NUM_PROCESSES",
    # rendezvous / gloo-compatible store
    "HOROVOD_GLOO_RENDEZVOUS_ADDR", "HOROVOD_GLOO_RENDEZVOUS_PORT",
    "HOROVOD_GLOO_TIMEOUT_SECONDS", "HOROVOD_RENDEZVOUS_HTTP_ADDR",
    "HOROVOD_RENDEZVOUS_HTTP_PORT", "HOROVOD_RENDEZVOUS_HEARTBEAT_TTL",
    "HOROVOD_RENDEZVOUS_LONG_POLL_SECONDS", "HOROVOD_PROBE_TIMEOUT",
    # launcher backends / host discovery
    "HOROVOD_LAUNCH_BACKEND", "HOROVOD_NIC_DISCOVERY",
    "HOROVOD_GCLOUD_PROJECT", "HOROVOD_GCLOUD_ZONE",
    # elastic re-form parameters (rewritten per generation)
    "HOROVOD_ELASTIC_MIN_WORKERS", "HOROVOD_ELASTIC_MAX_RETRIES",
    "HOROVOD_ELASTIC_BACKOFF_BASE_SECONDS",
    "HOROVOD_ELASTIC_BACKOFF_MAX_SECONDS",
    "HOROVOD_ELASTIC_DISCOVERY_INTERVAL_SECONDS",
    "HOROVOD_ELASTIC_HEARTBEAT_SECONDS",
    "HOROVOD_ELASTIC_REJOIN_TIMEOUT_SECONDS",
    "HOROVOD_ELASTIC_SETTLE_SECONDS",
    "HOROVOD_ELASTIC_SPILL_DIR", "HOROVOD_ELASTIC_SPILL_SYNC",
    # crash-consistent sharded checkpointing (ckpt/; docs/checkpointing.md)
    "HOROVOD_CKPT_DIR", "HOROVOD_CKPT_ASYNC", "HOROVOD_CKPT_KEEP",
    "HOROVOD_CKPT_REPLICATION", "HOROVOD_CKPT_VERIFY",
    "HOROVOD_CKPT_BARRIER_TIMEOUT_SECONDS", "HOROVOD_CKPT_FAULT",
    "HOROVOD_RESTART_ATTEMPT",
    # control-plane resilience (utils/resilience.py; docs/robustness.md)
    "HOROVOD_COLLECTIVE_TIMEOUT", "HOROVOD_NET_MAX_RETRIES",
    "HOROVOD_NET_BACKOFF_BASE_SECONDS", "HOROVOD_NET_BACKOFF_MAX_SECONDS",
    "HOROVOD_NET_DEADLINE_SECONDS", "HOROVOD_NET_ATTEMPT_TIMEOUT_SECONDS",
    # native/build/test switches
    "HOROVOD_NATIVE_CYCLE", "HOROVOD_TPU_WITHOUT_NATIVE",
    "HOROVOD_PALLAS_INTERPRET", "HOROVOD_FAULT_INJECT",
    # numerical integrity plane (integrity/; docs/integrity.md)
    "HOROVOD_INTEGRITY", "HOROVOD_INTEGRITY_INTERVAL",
    "HOROVOD_INTEGRITY_SPIKE_SIGMA", "HOROVOD_INTEGRITY_SKIP_STEPS",
    "HOROVOD_INTEGRITY_QUARANTINE", "HOROVOD_ROLLBACK_BUDGET",
    # online serving plane (serve/; docs/inference.md)
    "HOROVOD_SERVE_MAX_BATCH_TOKENS", "HOROVOD_SERVE_ADMISSION_MS",
    "HOROVOD_SERVE_QUEUE_CAPACITY", "HOROVOD_SERVE_DECODE_BLOCK",
    "HOROVOD_SERVE_SLOTS", "HOROVOD_SERVE_MAX_NEW_TOKENS",
    "HOROVOD_SERVE_QUARANTINE", "HOROVOD_SERVE_RESULT_TTL_S",
    # paged KV cache + prefix reuse (serve/paging.py; docs/inference.md)
    "HOROVOD_SERVE_PAGED", "HOROVOD_SERVE_PAGE_TOKENS",
    "HOROVOD_SERVE_PAGE_POOL", "HOROVOD_SERVE_PREFIX_CACHE",
    # bucket-wise gradient release (parallel/buckets.py;
    # docs/performance.md "backward overlap")
    "HOROVOD_GRAD_BUCKET_RELEASE", "HOROVOD_GRAD_BUCKET_BYTES",
    "HOROVOD_GRAD_BUCKET_WIRE",
    # fused BN+activation epilogue (ops/pallas/conv_bn_act.py)
    "HOROVOD_FUSED_BN_ACT",
    # memory telemetry plane (memory.py; docs/memory.md)
    "HOROVOD_MEMORY", "HOROVOD_MEMORY_SAMPLE_SECONDS",
    "HOROVOD_MEMORY_TOPK",
    # collective transport observatory (comms.py; docs/comms.md) + the
    # persisted probe roofline artifact (autotune/probe.py)
    "HOROVOD_COMMS", "HOROVOD_COMMS_WINDOW",
    "HOROVOD_COMMS_EWMA_ALPHA", "HOROVOD_COMMS_DEGRADED_FRACTION",
    "HOROVOD_PROBE_CACHE",
    # goodput ledger (goodput.py; docs/goodput.md)
    "HOROVOD_GOODPUT", "HOROVOD_GOODPUT_INCIDENTS",
    "HOROVOD_GOODPUT_REPORT_SECONDS",
    # ZeRO stage selection + stage-3 prefetch window (parallel/zero.py;
    # docs/performance.md "sharded training")
    "HOROVOD_ZERO_STAGE", "HOROVOD_ZERO_PREFETCH_BUCKETS",
)

DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024  # reference: operations.cc:379
DEFAULT_CYCLE_TIME_MS = 5.0  # reference: operations.cc:386
DEFAULT_CACHE_CAPACITY = 1024  # reference: global_state.h:88
DEFAULT_CYCLE_PIPELINE_DEPTH = 2
DEFAULT_FUSION_BUCKET_QUANTUM_BYTES = 64 * 1024
DEFAULT_FLIGHT_RECORDER_CAPACITY = 2048
DEFAULT_STRAGGLER_REPORT_SECONDS = 60.0
DEFAULT_PROFILE_HISTORY = 64
DEFAULT_LOCK_HOLD_WARN_SECONDS = 5.0
DEFAULT_TRACE_CAPACITY = 4096
DEFAULT_SLO_WINDOW = 512


def _get_int(name: str, default: int) -> int:
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return int(value)
    except ValueError:
        return default


def _get_float(name: str, default: float) -> float:
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return float(value)
    except ValueError:
        return default


def _get_bool(name: str, default: bool = False) -> bool:
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    return value.strip().lower() not in ("0", "false", "no", "off", "")


def parse_trace(value: "str | None") -> "tuple[bool, int]":
    """``HOROVOD_TRACE`` -> (enabled, span ring capacity). Same grammar
    as ``HOROVOD_FLIGHT_RECORDER``: unset or truthy = on at the default
    capacity; an integer > 1 is the capacity; 0/false/no/off disables."""
    if value is None or value.strip() == "":
        return True, DEFAULT_TRACE_CAPACITY
    v = value.strip().lower()
    if v in ("0", "false", "no", "off"):
        return False, DEFAULT_TRACE_CAPACITY
    try:
        n = int(v)
    except ValueError:
        return True, DEFAULT_TRACE_CAPACITY
    return True, (n if n > 1 else DEFAULT_TRACE_CAPACITY)


def parse_flight_recorder(value: "str | None") -> "tuple[bool, int]":
    """``HOROVOD_FLIGHT_RECORDER`` -> (enabled, ring capacity). Unset or
    truthy = on at the default capacity; an integer > 1 is the capacity;
    0/false/no/off disables."""
    if value is None or value.strip() == "":
        return True, DEFAULT_FLIGHT_RECORDER_CAPACITY
    v = value.strip().lower()
    if v in ("0", "false", "no", "off"):
        return False, DEFAULT_FLIGHT_RECORDER_CAPACITY
    try:
        n = int(v)
    except ValueError:
        return True, DEFAULT_FLIGHT_RECORDER_CAPACITY
    return True, (n if n > 1 else DEFAULT_FLIGHT_RECORDER_CAPACITY)


@dataclasses.dataclass
class Config:
    """Runtime knobs parsed once at ``hvd.init()``.

    Mirrors the env parsing block in the reference background thread init
    (reference: horovod/common/operations.cc:363-454).
    """

    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    timeline_file: str = ""
    timeline_mark_cycles: bool = False
    # None = endpoint disabled (no thread, no socket); 0 = ephemeral port
    metrics_port: "int | None" = None
    metrics_dump: str = ""
    autotune: bool = False
    autotune_probe: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    stall_check_disable: bool = False
    stall_check_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # two-level host collectives: ranks per slice (0 = host-derived
    # grouping from the rendezvous roster) and the slow-hop wire dtype
    # (none | fp16 | ieee_fp16); autotuner-writable via the synced blob
    hierarchy_group_size: int = 0
    hierarchy_compression: str = "none"
    # elastic mode: stall shutdown and peer loss raise catchable
    # WorkersDownError instead of tearing the process down
    elastic: bool = False
    # data-plane pipelining: responses in flight per cycle (1 = serial)
    cycle_pipeline_depth: int = DEFAULT_CYCLE_PIPELINE_DEPTH
    # size-bucket quantum for the fused program cache; payloads at or
    # under it keep exact sizes, larger ones pad to a power of two
    fusion_bucket_quantum: int = DEFAULT_FUSION_BUCKET_QUANTUM_BYTES
    # flight recorder: always-on bounded event ring + crash dumps
    flight_recorder: bool = True
    flight_recorder_capacity: int = DEFAULT_FLIGHT_RECORDER_CAPACITY
    flight_recorder_dir: str = ""
    # coordinator straggler report interval (0 disables the log line;
    # the lag gauge/skew histogram stay on either way)
    straggler_report_seconds: float = DEFAULT_STRAGGLER_REPORT_SECONDS
    # step profiler (profiler.py): per-step phase attribution, comm-hidden
    # fraction and MFU; a profile dir also turns profiling on
    profile: bool = False
    profile_dir: str = ""
    profile_history: int = DEFAULT_PROFILE_HISTORY
    # additionally capture a jax.profiler device trace into the profile dir
    profile_jax: bool = False
    # deadlock witness: runtime locks become order/hold-tracking DebugLocks
    # (analysis/witness.py; lock creation also reads the env directly, as
    # locks can be constructed before init parses this Config)
    debug_locks: bool = False
    lock_hold_warn_seconds: float = DEFAULT_LOCK_HOLD_WARN_SECONDS

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            fusion_threshold_bytes=_get_int(
                HOROVOD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES
            ),
            cycle_time_ms=_get_float(HOROVOD_CYCLE_TIME, DEFAULT_CYCLE_TIME_MS),
            cache_capacity=_get_int(HOROVOD_CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY),
            timeline_file=os.environ.get(HOROVOD_TIMELINE, ""),
            timeline_mark_cycles=_get_bool(HOROVOD_TIMELINE_MARK_CYCLES),
            metrics_port=(
                _get_int(HOROVOD_METRICS_PORT, 0)
                if os.environ.get(HOROVOD_METRICS_PORT, "") != "" else None),
            metrics_dump=os.environ.get(HOROVOD_METRICS_DUMP, ""),
            autotune=_get_bool(HOROVOD_AUTOTUNE),
            autotune_probe=_get_bool(HOROVOD_AUTOTUNE_PROBE),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG, ""),
            autotune_warmup_samples=_get_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3),
            autotune_steps_per_sample=_get_int(HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, 10),
            autotune_bayes_opt_max_samples=_get_int(
                HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20
            ),
            autotune_gaussian_process_noise=_get_float(
                HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8
            ),
            stall_check_disable=_get_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_check_time_seconds=_get_float(HOROVOD_STALL_CHECK_TIME_SECONDS, 60.0),
            stall_shutdown_time_seconds=_get_float(
                HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0
            ),
            hierarchical_allreduce=_get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=_get_bool(HOROVOD_HIERARCHICAL_ALLGATHER),
            hierarchy_group_size=_get_int(HOROVOD_HIERARCHY_GROUP_SIZE, 0),
            hierarchy_compression=os.environ.get(
                HOROVOD_HIERARCHY_COMPRESSION, "none"),
            elastic=_get_bool(HOROVOD_ELASTIC),
            cycle_pipeline_depth=_get_int(
                HOROVOD_CYCLE_PIPELINE_DEPTH, DEFAULT_CYCLE_PIPELINE_DEPTH
            ),
            fusion_bucket_quantum=_get_int(
                HOROVOD_FUSION_BUCKET_QUANTUM,
                DEFAULT_FUSION_BUCKET_QUANTUM_BYTES,
            ),
            flight_recorder=parse_flight_recorder(
                os.environ.get(HOROVOD_FLIGHT_RECORDER))[0],
            flight_recorder_capacity=parse_flight_recorder(
                os.environ.get(HOROVOD_FLIGHT_RECORDER))[1],
            flight_recorder_dir=os.environ.get(
                HOROVOD_FLIGHT_RECORDER_DIR, ""),
            straggler_report_seconds=_get_float(
                HOROVOD_STRAGGLER_REPORT_SECONDS,
                DEFAULT_STRAGGLER_REPORT_SECONDS,
            ),
            profile=(_get_bool(HOROVOD_PROFILE)
                     or os.environ.get(HOROVOD_PROFILE_DIR, "") != ""),
            profile_dir=os.environ.get(HOROVOD_PROFILE_DIR, ""),
            profile_history=_get_int(HOROVOD_PROFILE_HISTORY,
                                     DEFAULT_PROFILE_HISTORY),
            profile_jax=_get_bool(HOROVOD_PROFILE_JAX),
            debug_locks=_get_bool(HOROVOD_DEBUG_LOCKS),
            lock_hold_warn_seconds=_get_float(
                HOROVOD_LOCK_HOLD_WARN_SECONDS,
                DEFAULT_LOCK_HOLD_WARN_SECONDS),
        )


def parse_mesh_shape(value: str | None) -> tuple[int, int] | None:
    """Parse ``HOROVOD_MESH_SHAPE`` of the form "cross,local"."""
    if not value:
        return None
    parts = value.split(",")
    if len(parts) != 2:
        raise ValueError(
            f"{HOROVOD_MESH_SHAPE} must be 'cross,local', got {value!r}"
        )
    return int(parts[0]), int(parts[1])
