"""Leveled, per-rank-prefixed logging.

TPU-native equivalent of the reference's glog-style C++ logger
(reference: horovod/common/logging.cc:76-93, logging.h). Level and time
display are controlled by the same environment variables the reference
uses: ``HOROVOD_LOG_LEVEL`` (trace|debug|info|warning|error|fatal) and
``HOROVOD_LOG_HIDE_TIME``.
"""

from __future__ import annotations

import logging
import os
import sys
import threading

TRACE = 5  # below logging.DEBUG, mirrors the reference's LogLevel::TRACE
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_lock = threading.Lock()
_logger: logging.Logger | None = None


def _parse_level(value: str | None) -> int:
    # reference: horovod/common/logging.cc:76-85 (LogLevelStrToEnum)
    if value is None:
        return logging.WARNING
    return _LEVELS.get(value.strip().lower(), logging.WARNING)


class _RankFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        from horovod_tpu.core import state

        record.hvd_rank = state.global_state().rank if state.global_state().initialized else -1
        return True


def get_logger() -> logging.Logger:
    global _logger
    with _lock:
        if _logger is None:
            logger = logging.getLogger("horovod_tpu")
            logger.setLevel(_parse_level(os.environ.get("HOROVOD_LOG_LEVEL")))
            handler = logging.StreamHandler(sys.stderr)
            if os.environ.get("HOROVOD_LOG_HIDE_TIME"):
                fmt = "[%(hvd_rank)s]<%(levelname)s> %(message)s"
            else:
                fmt = "%(asctime)s [%(hvd_rank)s]<%(levelname)s> %(message)s"
            handler.setFormatter(logging.Formatter(fmt))
            handler.addFilter(_RankFilter())
            logger.addHandler(handler)
            logger.propagate = False
            _logger = logger
        return _logger


def trace(msg: str, *args) -> None:
    get_logger().log(TRACE, msg, *args)


def debug(msg: str, *args) -> None:
    get_logger().debug(msg, *args)


def info(msg: str, *args) -> None:
    get_logger().info(msg, *args)


def warning(msg: str, *args) -> None:
    get_logger().warning(msg, *args)


def error(msg: str, *args) -> None:
    get_logger().error(msg, *args)
