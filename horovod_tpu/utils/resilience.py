"""Control-plane resilience: unified retry/backoff + network chaos.

Every control-plane byte in this package — rendezvous KV HTTP ops,
flight-recorder dump shipping, elastic long-polls/heartbeats, and the
socket controller's negotiation verbs — crosses a real network that
drops packets, resets connections and stalls. The reference tolerates
this by construction (the gloo HTTPStore retries, stall detection bounds
a lost peer's damage); this module is the TPU-native port of that
posture, shared by all transports:

* :class:`RetryPolicy` — exponential backoff with FULL jitter
  (delay ~ U(0, min(max, base*2^k)), the AWS-architecture-blog variant
  that decorrelates synchronized retry storms), a per-attempt timeout
  hint for socket ops, an overall deadline, and retryable-error
  classification. Every retry increments
  ``horovod_net_retries_total{transport=...}`` and emits a
  flight-recorder ``net_retry`` event; exhaustion emits ``net_gave_up``.
* **Network chaos injection** — ``HOROVOD_FAULT_INJECT`` gains
  net-fault clauses (``;``-separated, composable with the process
  faults owned by ``elastic/fault_inject.py``)::

      partition:<rank>[:<secs>][:after=<secs>]   drop that rank's control
                                                 traffic (ops block for the
                                                 window; secs omitted = forever)
      kv_outage:<secs>[:after=<secs>|:on=reform] rendezvous server answers 503
      flaky:<prob>[:rank=<r>][:seconds=<t>]      probabilistic connection resets
      netdelay:<ms>[:rank=<r>]                   fixed per-op latency

  The injection seam (:func:`inject`) sits INSIDE the real transports,
  before each wire op, so chaos tests exercise the production
  retry/timeout/fencing code rather than a mock. An injected reset
  (:class:`ChaosError`) is raised before any byte moves, which is what
  makes transparent replay safe for the stream-oriented socket verbs.
* **Generation fencing** — the elastic runner publishes its membership
  generation here (:func:`set_generation`); transports stamp the
  generation they were built in and discard late replies/errors from a
  superseded epoch (:func:`current_generation`), and
  ``HOROVOD_COLLECTIVE_TIMEOUT`` (read via :func:`collective_timeout`)
  bounds how long any negotiate/dispatch round may block before the
  cycle aborts with a catchable ``WorkerStallError``.

This module lives in ``utils`` (the bottom layer): it must not import
runtime/elastic/run modules at module scope. Flight-recorder emission is
deferred to call time for the same reason.
"""

from __future__ import annotations

import dataclasses
import http.client
import os
import random
import socket
import time
from typing import Callable, List, Optional
from urllib.error import HTTPError, URLError

from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.utils import logging as log
from horovod_tpu.utils.env import _get_float, _get_int

HOROVOD_NET_MAX_RETRIES = "HOROVOD_NET_MAX_RETRIES"
HOROVOD_NET_BACKOFF_BASE_SECONDS = "HOROVOD_NET_BACKOFF_BASE_SECONDS"
HOROVOD_NET_BACKOFF_MAX_SECONDS = "HOROVOD_NET_BACKOFF_MAX_SECONDS"
HOROVOD_NET_DEADLINE_SECONDS = "HOROVOD_NET_DEADLINE_SECONDS"
HOROVOD_NET_ATTEMPT_TIMEOUT_SECONDS = "HOROVOD_NET_ATTEMPT_TIMEOUT_SECONDS"
HOROVOD_COLLECTIVE_TIMEOUT = "HOROVOD_COLLECTIVE_TIMEOUT"

_NET_RETRIES = _metrics().counter(
    "horovod_net_retries_total",
    "Control-plane transport ops retried after a transient failure.",
    labelnames=("transport",))
_NET_BACKOFF = _metrics().counter(
    "horovod_net_backoff_seconds_total",
    "Seconds spent sleeping in retry backoff, per transport.",
    labelnames=("transport",))
_NET_GAVE_UP = _metrics().counter(
    "horovod_net_gave_up_total",
    "Transport ops that exhausted their retry budget and re-raised.",
    labelnames=("transport",))
_CHAOS_INJECTED = _metrics().counter(
    "horovod_net_chaos_injected_total",
    "Network faults fired by the HOROVOD_FAULT_INJECT chaos harness.",
    labelnames=("kind",))

# HTTP statuses worth retrying: timeouts, throttles, and server-side
# failures (503 is the rendezvous kv_outage signal). 404 is NOT here —
# it is the rendezvous key-absent signal the long-poll protocol rides on.
RETRYABLE_HTTP_CODES = (408, 429, 500, 502, 503, 504)


class ChaosError(ConnectionResetError):
    """A connection reset injected by the chaos harness. Subclasses
    ``ConnectionResetError`` so production except-clauses and the
    retryable classification treat it exactly like the real thing."""


def _emit(kind: str, **fields) -> None:
    # deferred import: utils must not pull upper layers at module scope
    from horovod_tpu import flight_recorder

    flight_recorder.emit(kind, **fields)


def is_retryable(exc: BaseException) -> bool:
    """Default transient-vs-fatal classification for transport errors.

    Retryable: injected/real connection resets, refused/aborted
    connections, socket timeouts, HTTP-layer protocol errors, URL errors,
    and HTTP responses in :data:`RETRYABLE_HTTP_CODES`. Not retryable:
    HTTP 404 (the key-absent signal), other 4xx, and anything that is not
    a transport error (``KeyError``, ``ValueError``, ...)."""
    if isinstance(exc, HTTPError):
        return exc.code in RETRYABLE_HTTP_CODES
    return isinstance(exc, (ConnectionError, TimeoutError, socket.timeout,
                            http.client.HTTPException, URLError, OSError))


def note_retry(transport: str, phase: str, attempt: int, delay: float,
               exc: BaseException) -> None:
    """Account one retry: metrics + flight-recorder ``net_retry``."""
    _NET_RETRIES.labels(transport=transport).inc()
    _NET_BACKOFF.labels(transport=transport).inc(delay)
    _emit("net_retry", transport=transport, phase=phase, attempt=attempt,
          delay=round(delay, 4), error=str(exc)[:120])
    try:
        # goodput ledger: the backoff sleep about to happen is collective
        # stall badput (deferred import: utils must not pull upper layers
        # at module scope)
        from horovod_tpu import goodput

        goodput.record_span("collective_stall", delay)
    except Exception:
        pass
    log.debug("net retry: %s/%s attempt %d in %.3fs (%s)",
              transport, phase, attempt, delay, exc)


def give_up(transport: str, phase: str, attempt: int,
            exc: BaseException) -> None:
    """Account retry-budget exhaustion: metrics + ``net_gave_up``."""
    _NET_GAVE_UP.labels(transport=transport).inc()
    _emit("net_gave_up", transport=transport, phase=phase, attempts=attempt,
          error=str(exc)[:200])
    log.warning("net retries exhausted: %s/%s after %d attempt(s): %s",
                transport, phase, attempt, exc)


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff with full jitter, bounded by attempts AND an
    overall deadline.

    ``attempt_timeout`` is a cooperative per-attempt bound: callers pass
    it into their socket/urlopen timeouts (a blocking syscall cannot be
    preempted from here). ``sleep``/``rng`` are injectable so tests can
    assert the schedule without real waiting."""

    transport: str = "net"
    max_retries: int = 4
    base_delay: float = 0.1
    max_delay: float = 2.0
    deadline: float = 30.0
    attempt_timeout: float = 10.0
    sleep: Callable[[float], None] = time.sleep
    rng: Optional[random.Random] = None

    @classmethod
    def from_env(cls, transport: str = "net", **overrides) -> "RetryPolicy":
        kw = dict(
            max_retries=_get_int(HOROVOD_NET_MAX_RETRIES, 4),
            base_delay=_get_float(HOROVOD_NET_BACKOFF_BASE_SECONDS, 0.1),
            max_delay=_get_float(HOROVOD_NET_BACKOFF_MAX_SECONDS, 2.0),
            deadline=_get_float(HOROVOD_NET_DEADLINE_SECONDS, 30.0),
            attempt_timeout=_get_float(
                HOROVOD_NET_ATTEMPT_TIMEOUT_SECONDS, 10.0),
        )
        kw.update(overrides)
        return cls(transport=transport, **kw)

    def delay_for(self, attempt: int) -> float:
        """Full-jitter delay for retry ``attempt`` (1-based):
        ``U(0, min(max_delay, base_delay * 2**(attempt-1)))``."""
        cap = min(self.max_delay,
                  self.base_delay * (2.0 ** max(attempt - 1, 0)))
        r = (self.rng or random).random()
        return cap * r

    def retryable(self, exc: BaseException) -> bool:
        return is_retryable(exc)

    def call(self, fn: Callable, *args, phase: str = "",
             deadline: Optional[float] = None,
             classify: Optional[Callable[[BaseException], bool]] = None,
             **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying transient failures until
        ``max_retries`` or the overall deadline is exhausted, then
        re-raise the last error. Non-retryable errors pass through
        untouched on the first occurrence."""
        start = time.monotonic()
        budget = self.deadline if deadline is None else deadline
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as exc:
                if not (classify or is_retryable)(exc):
                    raise
                attempt += 1
                delay = self.delay_for(attempt)
                elapsed = time.monotonic() - start
                if attempt > self.max_retries or elapsed + delay > budget:
                    give_up(self.transport, phase, attempt, exc)
                    raise
                note_retry(self.transport, phase, attempt, delay, exc)
                self.sleep(delay)


# -- collective timeout / generation fence ---------------------------------

def collective_timeout() -> float:
    """``HOROVOD_COLLECTIVE_TIMEOUT`` in seconds; 0 disables the deadline
    on in-flight negotiate/dispatch rounds."""
    return _get_float(HOROVOD_COLLECTIVE_TIMEOUT, 0.0)


# process-local membership generation mirror. The elastic runner is the
# writer (on every successful re-form); transports snapshot it at
# construction and refuse to deliver results/errors once superseded, so
# late replies from the old epoch are discarded instead of corrupting
# the new one.
_generation = 0


def set_generation(gen: int) -> None:
    global _generation
    _generation = int(gen)


def current_generation() -> int:
    return _generation


# -- network chaos ---------------------------------------------------------

NET_FAULT_KINDS = ("partition", "kv_outage", "flaky", "netdelay")


@dataclasses.dataclass(frozen=True)
class NetFault:
    kind: str
    rank: Optional[int] = None  # None = every rank
    seconds: float = float("inf")  # fault window length
    prob: float = 0.0  # flaky: per-op reset probability
    delay_ms: float = 0.0  # netdelay: per-op latency
    after: float = 0.0  # window start, seconds after arming
    on: str = ""  # kv_outage trigger: "" (timer) | "reform"
    # netdelay scope: "" = every wire op (legacy), "cross" = only the
    # slow inter-group hop — the sleep scales with the number of
    # group-boundary crossings the seam declares (a flat ring crosses
    # 2(w-1) times per allreduce, the hierarchical cross hop 2(G-1),
    # the intra hop 0), so a simulated DCN penalizes each path by the
    # bytes it actually puts on the slow link.
    hop: str = ""


def is_net_clause(clause: str) -> bool:
    """True when a ``HOROVOD_FAULT_INJECT`` clause names a network fault
    (owned here) rather than a process fault (owned by
    ``elastic/fault_inject.py``)."""
    return clause.strip().split(":", 1)[0].strip().lower() in NET_FAULT_KINDS


def parse_net_faults(text: Optional[str]) -> List[NetFault]:
    """Parse the net-fault clauses out of ``HOROVOD_FAULT_INJECT``
    (``;``-separated; process-fault clauses are skipped). Raises
    ``ValueError`` on a malformed net clause."""
    faults: List[NetFault] = []
    for clause in (text or "").split(";"):
        clause = clause.strip()
        if not clause or not is_net_clause(clause):
            continue
        parts = [p.strip() for p in clause.split(":")]
        kind = parts[0].lower()
        positional: List[str] = []
        named = {}
        for p in parts[1:]:
            if "=" in p:
                k, v = p.split("=", 1)
                named[k.strip().lower()] = v.strip()
            else:
                positional.append(p)
        try:
            after = float(named.pop("after", 0.0))
            if kind == "partition":
                faults.append(NetFault(
                    kind, rank=int(positional[0]),
                    seconds=(float(positional[1]) if len(positional) > 1
                             else float("inf")),
                    after=after))
            elif kind == "kv_outage":
                faults.append(NetFault(
                    kind, seconds=float(positional[0]), after=after,
                    on=named.pop("on", "").lower()))
            elif kind == "flaky":
                prob = min(max(float(positional[0]), 0.0), 1.0)
                faults.append(NetFault(
                    kind, prob=prob,
                    rank=(int(named.pop("rank")) if "rank" in named
                          else None),
                    seconds=float(named.pop("seconds", float("inf"))),
                    after=after))
            elif kind == "netdelay":
                hop = named.pop("hop", "").lower()
                if hop not in ("", "cross"):
                    raise ValueError(f"unknown hop {hop!r} "
                                     "(expected hop=cross)")
                faults.append(NetFault(
                    kind, delay_ms=float(positional[0]),
                    rank=(int(named.pop("rank")) if "rank" in named
                          else None),
                    after=after, hop=hop))
        except (IndexError, ValueError) as exc:
            raise ValueError(
                f"HOROVOD_FAULT_INJECT: malformed net-fault clause "
                f"{clause!r}: {exc}") from exc
        if named:
            raise ValueError(
                f"HOROVOD_FAULT_INJECT: unknown key(s) {sorted(named)} in "
                f"net-fault clause {clause!r}")
    return faults


class _Chaos:
    """Armed per-process chaos state: parsed faults, the frozen launch
    rank (re-forms renumber HOROVOD_RANK; faults must not re-target), a
    deterministic per-rank RNG, and the arming time the fault windows
    are measured from."""

    def __init__(self, faults: List[NetFault], rank: int):
        self.faults = faults
        self.rank = rank
        self.t0 = time.monotonic()
        self.rng = random.Random(0xC0FFEE + rank)
        self._partition_announced = False


_chaos_state: Optional[_Chaos] = None
_chaos_loaded = False


def _chaos() -> Optional[_Chaos]:
    global _chaos_state, _chaos_loaded
    if not _chaos_loaded:
        _chaos_loaded = True
        try:
            faults = parse_net_faults(os.environ.get("HOROVOD_FAULT_INJECT"))
        except ValueError as exc:
            log.error("%s", exc)
            faults = []
        if faults:
            rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
            _chaos_state = _Chaos(faults, rank)
            log.warning("network chaos armed on rank %d: %s", rank,
                        "; ".join(f.kind for f in faults))
    return _chaos_state


def reload_chaos() -> None:
    """Re-arm chaos from the current environment (tests)."""
    global _chaos_state, _chaos_loaded
    _chaos_state = None
    _chaos_loaded = False


def inject(transport: str, phase: str = "",
           crossings: Optional[int] = None) -> None:
    """The chaos seam: called inside the real transports before each
    control-plane wire op. Applies netdelay/flaky/partition faults whose
    window covers now; a no-op when no chaos is armed.

    ``crossings``: how many times this wire op crosses the hierarchy
    group boundary (the simulated slow DCN link). Data-plane seams that
    model topology declare it — flat ring allreduce ``2*(w-1)``, the
    hierarchical cross hop ``2*(G-1)``, the intra hop ``0``. A
    ``netdelay:...:hop=cross`` fault sleeps ``delay_ms`` PER crossing and
    skips seams that declare none (or don't model topology at all), so
    the injected DCN taxes each path proportionally to the traffic it
    actually puts on the slow link. Plain ``netdelay`` ignores
    ``crossings`` (legacy per-op latency)."""
    ch = _chaos()
    if ch is None:
        return
    now = time.monotonic() - ch.t0
    for f in ch.faults:
        in_window = f.after <= now <= f.after + f.seconds
        targeted = f.rank is None or f.rank == ch.rank
        if f.kind == "netdelay" and targeted and in_window:
            if f.hop == "cross":
                if not crossings:  # seam off the slow link (or untyped)
                    continue
                _CHAOS_INJECTED.labels(kind="netdelay").inc()
                time.sleep(f.delay_ms * crossings / 1000.0)
                continue
            _CHAOS_INJECTED.labels(kind="netdelay").inc()
            time.sleep(f.delay_ms / 1000.0)
        elif transport in ("ring", "hier_intra", "hier_cross"):
            # the data-plane seams (executor host-ring ops and the
            # hierarchical intra/cross hops) carry delay faults only:
            # flaky resets and partitions model CONTROL traffic loss,
            # which the retry/elastic layers own — raising them mid-ring
            # would fail collectives no real transport fault produces
            # (the ring retries at the message layer)
            continue
        elif f.kind == "flaky" and targeted and in_window:
            if ch.rng.random() < f.prob:
                _CHAOS_INJECTED.labels(kind="flaky").inc()
                _emit("chaos_inject", fault="flaky", transport=transport,
                      phase=phase)
                raise ChaosError(
                    f"chaos: injected connection reset "
                    f"({transport}/{phase})")
        elif f.kind == "partition" and f.rank == ch.rank and now >= f.after:
            end = f.after + f.seconds
            if not ch._partition_announced:
                ch._partition_announced = True
                _CHAOS_INJECTED.labels(kind="partition").inc()
                _emit("chaos_inject", fault="partition", rank=ch.rank,
                      seconds=f.seconds)
                log.error("chaos: partitioning rank %d control traffic "
                          "for %s", ch.rank,
                          "ever" if end == float("inf")
                          else "%.0fs" % f.seconds)
            # dropped traffic reads as a blocked op to this rank and as
            # silence to its peers — sleep out the window (forever for a
            # permanent partition; the harness reaps the process)
            while True:
                remaining = end - (time.monotonic() - ch.t0)
                if remaining <= 0:
                    break
                time.sleep(min(remaining, 0.2))
