"""Build / install horovod_tpu.

Analogue (in spirit) of the reference's env-flag-driven build
(reference: setup.py:331-560 — HOROVOD_WITH[OUT]_* knobs selecting which
native pieces to build). The TPU build has exactly one native artifact —
the C++ runtime library (TCP transport + host collectives + timeline
writer, horovod_tpu/cpp/) — compiled with the system toolchain; there is
no CUDA/NCCL probe to do.

Env knobs:
  HOROVOD_TPU_WITHOUT_NATIVE=1   skip building the C++ library (it can
                                 still be built lazily at first use; the
                                 framework degrades to pure-Python
                                 transports if no toolchain exists)
  CXX / CXXFLAGS                 forwarded to make
"""

import os
import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        if os.environ.get("HOROVOD_TPU_WITHOUT_NATIVE", "") not in ("1", "true"):
            cpp_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "horovod_tpu", "cpp")
            try:
                subprocess.run(["make", "-C", cpp_dir], check=True)
            except (OSError, subprocess.CalledProcessError) as exc:
                print(f"warning: native library build failed ({exc}); "
                      "the framework will retry lazily at first use")
        super().run()


setup(
    name="horovod_tpu",
    version=open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "horovod_tpu", "version.py"))
    .read().split('"')[1],
    description="TPU-native distributed data-parallel training framework",
    packages=find_packages(include=["horovod_tpu", "horovod_tpu.*"]),
    package_data={"horovod_tpu": ["cpp/*.cc", "cpp/Makefile"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy"],
    extras_require={
        "torch": ["torch"],
        "spark": ["pyspark"],
    },
    entry_points={"console_scripts": [
        "tpurun = horovod_tpu.run.run:main",
    ]},
    cmdclass={"build_py": BuildWithNative},
)
