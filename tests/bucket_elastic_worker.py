"""Worker for the bucket-release elastic cell (ISSUE 12 satellite).

World=3 over the real socket/native transport. Every step runs a
bucketed eager backward (one GradReleasePlan bucket per leaf, so three
releases hit the wire per step). At BUCKET_KILL_STEP the kill rank dies
*mid-backward* — inside its second bucket release, with the first
bucket's allreduce already negotiated/in flight. The survivors' gather
then fails with WorkersDownError on the orphaned bucket tokens;
``@elastic.run`` re-forms them into a 2-worker generation, rolls back to
the last commit, and the SAME plan object (its per-step state reset by
the gather failure path) finishes the run. The final line reports
outstanding fusion-buffer leases — a failed bucket token must return its
slab, so ``leases_leaked`` has to be 0.

Invariant: the loss is a plain sum, so each leaf's averaged gradient is
exactly ones and ``w == step`` at every commit, across the re-form.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.parallel import buckets as buckets_mod

TOTAL_STEPS = int(os.environ.get("ELASTIC_TOTAL_STEPS", "6"))
KILL_STEP = int(os.environ.get("BUCKET_KILL_STEP", "3"))
KILL_RANK = int(os.environ.get("BUCKET_KILL_RANK", "1"))
ORIG_RANK = int(os.environ.get("HOROVOD_RANK", "0"))

PLAN = buckets_mod.GradReleasePlan(bucket_bytes=256)  # one leaf per bucket

_die_mid_backward = False
_real_release = buckets_mod.GradReleasePlan._release


def _release_and_maybe_die(self, bucket, values):
    _real_release(self, bucket, values)
    if _die_mid_backward and bucket.index >= 1:
        # bucket 0 is already on the wire and later buckets are still
        # differentiating: abrupt death with tokens genuinely in flight
        os._exit(17)


buckets_mod.GradReleasePlan._release = _release_and_maybe_die


def bucketed_grad(params):
    def loss(p):
        return sum(x.sum() for x in
                   jax.tree_util.tree_leaves(PLAN.tag(p)))

    return PLAN.gather(jax.grad(loss)(params))


@elastic.run
def train(state):
    global _die_mid_backward
    while state.step < TOTAL_STEPS:
        _die_mid_backward = (ORIG_RANK == KILL_RANK
                             and state.step == KILL_STEP
                             and elastic.restarts() == 0)
        params = {"a": jnp.ones((96,), jnp.float32),
                  "b": jnp.ones((96,), jnp.float32),
                  "c": jnp.ones((96,), jnp.float32)}
        g = bucketed_grad(params)
        _die_mid_backward = False
        state.params["w"] = state.params["w"] + np.asarray(g["a"][:4])
        state.step += 1
        state.commit()
    return state


def main() -> int:
    hvd.init()
    state = elastic.ArrayState(
        params={"w": np.zeros(4, np.float32)}, optimizer=None, step=0)
    train(state)

    from horovod_tpu.runtime.runtime import get_runtime

    mgr = get_runtime().executor.fusion_buffers
    with mgr._lock:
        free = sum(a.nbytes for lst in mgr._free.values() for a in lst)
    leaked = mgr.allocated_bytes() - free
    w = float(state.params["w"][0])
    print(f"DONE rank={hvd.rank()} size={hvd.size()} step={state.step} "
          f"w={w:g} generation={elastic.restarts()} "
          f"wire_released={PLAN.wire_stats()['released']} "
          f"leases_leaked={leaked}", flush=True)
    if state.step != TOTAL_STEPS or abs(w - TOTAL_STEPS) > 1e-5:
        return 3
    if leaked != 0:
        return 4
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
