"""Test fixtures: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's "distributed without a cluster" strategy (reference:
test/ run under ``mpirun -np 2 -H localhost:2``, SURVEY.md §4): collective
semantics, fusion, caching and error propagation are tested on one host by
faking the device topology — here with
``--xla_force_host_platform_device_count=8`` CPU devices instead of
multiple MPI processes.

NOTE: the environment's sitecustomize force-selects the TPU platform via
``jax.config.update('jax_platforms', ...)``, so setting ``JAX_PLATFORMS``
alone is not enough — we re-update the config before any backend is used.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

# Launcher-driven tests spawn `tpurun ... python examples/foo.py`
# subprocesses that import horovod_tpu from PYTHONPATH (pytest's rootdir
# insertion only covers THIS process). Prepend the repo so the tests are
# hermetic whether or not the package is pip-installed.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in os.environ.get("PYTHONPATH", "").split(os.pathsep):
    os.environ["PYTHONPATH"] = (
        _REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow'); big worlds "
        "and soaks that need a multi-core box")


@pytest.fixture
def hvd():
    """Initialized framework on a 2x4 (cross x local) mesh, torn down after
    the test so each test sees a fresh world."""
    import horovod_tpu as hvd_mod

    hvd_mod.shutdown()
    hvd_mod.init(mesh_shape=(2, 4))
    yield hvd_mod
    hvd_mod.shutdown()


@pytest.fixture
def hvd_flat():
    """Initialized framework on a 1x8 mesh (single-host view)."""
    import horovod_tpu as hvd_mod

    hvd_mod.shutdown()
    hvd_mod.init(mesh_shape=(1, 8))
    yield hvd_mod
    hvd_mod.shutdown()
