"""Worker script for the elastic fault-injection acceptance test.

Launched by tests/test_elastic_multiprocess.py with world=3, socket
controller, the pytest process hosting the rendezvous HTTP store, and
``HOROVOD_FAULT_INJECT=kill:rank=1:step=3``: rank 1 dies inside its
step-3 commit; the survivors' next collective fails with
WorkersDownError, ``@elastic.run`` re-forms them into a 2-worker
generation, rolls back to the last commit (step 3) and finishes all
TOTAL_STEPS steps.

Invariant printed at the end: one Average-allreduce of ones adds exactly
1.0 per step regardless of world size, so ``w == step`` at every commit
— surviving a membership change with w intact proves the rollback+sync
path, not just the re-form.
"""

import os
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic

TOTAL_STEPS = int(os.environ.get("ELASTIC_TOTAL_STEPS", "8"))


@elastic.run
def train(state):
    while state.step < TOTAL_STEPS:
        grad = hvd.allreduce(np.ones(4, np.float32), average=True,
                             name="elastic_grad")
        state.params["w"] = state.params["w"] + np.asarray(grad)
        state.step += 1
        state.commit()
    return state


def main() -> int:
    hvd.init()
    state = elastic.ArrayState(
        params={"w": np.zeros(4, np.float32)}, optimizer=None, step=0)
    train(state)

    w = float(state.params["w"][0])
    restarts = elastic.restarts()
    from horovod_tpu.elastic.runner import _RESTARTS_TOTAL

    print(f"DONE rank={hvd.rank()} size={hvd.size()} step={state.step} "
          f"w={w:g} generation={restarts} "
          f"elastic_restarts_total={_RESTARTS_TOTAL.value:g}",
          flush=True)
    # straggler attribution (coordinator only has samples; empty elsewhere)
    lag = hvd.metrics().get("horovod_straggler_lag_seconds", {})
    for row in lag.get("values", ()):
        print(f"LAG rank={row['labels'].get('rank')} "
              f"value={row['value']:.6f}", flush=True)
    if state.step != TOTAL_STEPS or abs(w - TOTAL_STEPS) > 1e-5:
        return 3
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
