"""Worker script for multi-process runtime tests (launched by
test_multiprocess.py with the launcher env contract set).

Plays the role of one rank in the reference's mpirun-launched op tests
(reference: test/test_tensorflow.py run under ``mpirun -np 2``): computes
collectives through the public named-async API and asserts against locally
computed expectations.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import horovod_tpu as hvd  # noqa: E402


def main():
    scenario = sys.argv[1]
    rank = int(os.environ["HOROVOD_RANK"])
    world = int(os.environ["HOROVOD_SIZE"])
    if scenario == "pod_soak":
        # per-rank timeline paths must exist BEFORE init (the launcher
        # hands every rank the same env; the rank-suffixed path is the
        # worker's to derive)
        os.environ["HOROVOD_TIMELINE"] = os.path.join(
            os.environ["SOAK_DIR"], f"timeline.{rank}.json")
    hvd.init()

    if scenario == "collectives":
        # named allreduce: mean over ranks
        for step in range(3):  # steady state -> cache fast path
            h = hvd.allreduce_async(
                np.full((5,), float(rank), np.float32), name="grad/w")
            out = hvd.synchronize(h)
            np.testing.assert_allclose(
                np.asarray(out), np.mean(np.arange(world, dtype=np.float32)))
        # sum + int dtype
        h = hvd.allreduce_async(np.full((3,), rank + 1, np.int32),
                                name="grad/int", average=False)
        np.testing.assert_array_equal(
            np.asarray(hvd.synchronize(h)), sum(range(1, world + 1)))
        # ragged allgather: rank r contributes (r+1, 2)
        h = hvd.allgather_async(
            np.full((rank + 1, 2), rank, np.float32), name="ag/x")
        out = np.asarray(hvd.synchronize(h))
        expected = np.concatenate(
            [np.full((r + 1, 2), r, np.float32) for r in range(world)])
        np.testing.assert_allclose(out, expected)
        # broadcast root=1
        h = hvd.broadcast_async(
            np.full((4,), float(rank), np.float32), root_rank=1, name="bc/x")
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)), 1.0)
        # min/max/product ride the same wire (op-generalized ring kernels;
        # reference: op-type dispatch of torch/mpi_ops_v2.cc:52-76) —
        # bit-exact expectations
        h = hvd.allreduce_async(np.full((3,), float(rank + 1), np.float32),
                                name="red/min", op=hvd.Min)
        np.testing.assert_array_equal(np.asarray(hvd.synchronize(h)), 1.0)
        h = hvd.allreduce_async(np.full((3,), float(rank + 1), np.float32),
                                name="red/max", op=hvd.Max)
        np.testing.assert_array_equal(
            np.asarray(hvd.synchronize(h)), float(world))
        h = hvd.allreduce_async(np.full((3,), rank + 2, np.int32),
                                name="red/prod", op=hvd.Product)
        expect = int(np.prod(np.arange(2, world + 2, dtype=np.int64)))
        out = np.asarray(hvd.synchronize(h))
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, expect)
        # reducescatter: rank r contributes data[r] (world*2, 3); rank r
        # receives shard r of the element-wise sum
        data = np.stack([np.arange(world * 2 * 3, dtype=np.float32)
                         .reshape(world * 2, 3) + 10 * r
                         for r in range(world)])
        out = np.asarray(hvd.reducescatter(data[rank], op=hvd.Sum))
        full = data.sum(axis=0)
        np.testing.assert_allclose(out, full[rank * 2:(rank + 1) * 2])
        out = np.asarray(hvd.reducescatter(data[rank], op=hvd.Min))
        np.testing.assert_allclose(out, data.min(axis=0)[rank * 2:(rank + 1) * 2])
        # non-C-contiguous input must still reduce correctly (regression:
        # the in-place ring must not write into a stray ravel() copy)
        out = np.asarray(hvd.reducescatter(
            np.asfortranarray(data[rank]), op=hvd.Sum))
        np.testing.assert_allclose(out, full[rank * 2:(rank + 1) * 2])
        # alltoall: rank r sends chunk j of its tensor to rank j
        out = np.asarray(hvd.alltoall(data[rank]))
        expect_a2a = np.concatenate(
            [data[j, rank * 2:(rank + 1) * 2] for j in range(world)])
        np.testing.assert_allclose(out, expect_a2a)

        # byte-count optimality of the native kernels (VERDICT r2 ask 6):
        # one big reducescatter and one big alltoall must each send
        # exactly (w-1)/w of the payload from this rank — not the old
        # fallbacks' 2x (allreduce+slice) / Wx (star allgatherv)
        from horovod_tpu.core import state as _state
        net = _state.global_state().runtime.controller.net
        big = np.ones((world * 1024, 16), np.float32)
        before = net.data_bytes_sent()
        hvd.reducescatter(big, op=hvd.Sum)
        sent_rs = net.data_bytes_sent() - before
        optimal = big.nbytes * (world - 1) // world
        assert sent_rs == optimal, (sent_rs, optimal)
        before = net.data_bytes_sent()
        hvd.alltoall(big, name="bytes/a2a")
        sent_a2a = net.data_bytes_sent() - before
        assert sent_a2a == optimal, (sent_a2a, optimal)
        # cache populated
        from horovod_tpu.core import state
        rt = state.global_state().runtime
        assert len(rt.controller.cache) >= 3, len(rt.controller.cache)

    elif scenario == "skewed_arrival":
        # The negotiation protocol's reason to exist: workers announce the
        # same named tensor in DIFFERENT cycles. Rank r delays by r*0.4s —
        # far more than the 5ms cycle — so early announcers must wait
        # (uncached path), then repeat with the tensor cached (deferred-hit
        # path), then repeat with a changed shape (synchronized
        # invalidation path).
        import time

        for round_no, shape in [(0, (4,)), (1, (4,)), (2, (4,)), (3, (8,))]:
            time.sleep(0.4 * rank)
            h = hvd.allreduce_async(
                np.full(shape, float(rank), np.float32), name="skew/x")
            out = hvd.synchronize(h)
            np.testing.assert_allclose(
                np.asarray(out), np.mean(np.arange(world, dtype=np.float32)))
        # caches must still be bit-aligned: a fresh steady-state round on a
        # second tensor plus the first must take the fast path correctly
        for _ in range(2):
            h1 = hvd.allreduce_async(np.full((8,), float(rank), np.float32),
                                     name="skew/x")
            h2 = hvd.allreduce_async(np.full((2,), float(rank) * 2, np.float32),
                                     name="skew/y")
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(h1)),
                np.mean(np.arange(world, dtype=np.float32)))
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(h2)),
                2 * np.mean(np.arange(world, dtype=np.float32)))

    elif scenario == "autotune":
        # coordinator tunes, workers apply via the per-cycle param
        # broadcast; collectives stay correct while knobs change
        from horovod_tpu.runtime.runtime import get_runtime
        rt = get_runtime()
        if rank == 0:
            assert rt.param_manager is not None
        else:
            assert rt.param_manager is None
        # fixed iteration count on every rank — breaking early when this
        # rank observes convergence would shut down while peers still have
        # collectives in flight
        for i in range(250):
            h = hvd.allreduce_async(
                np.full((8,), float(rank), np.float32), name=f"at/{i % 3}")
            out = np.asarray(hvd.synchronize(h))
            np.testing.assert_allclose(
                out, np.mean(np.arange(world, dtype=np.float32)))
        assert not rt._autotune_active, "autotune did not converge"
        # every worker holds the frozen tuned config
        assert rt._st.config.cycle_time_ms > 0

    elif scenario == "large_allreduce":
        # chunks far larger than kernel socket buffers: the ring must run
        # full-duplex or it deadlocks (every rank blocked in send)
        n = 8 * 1024 * 1024  # 32 MB fp32
        h = hvd.allreduce_async(
            np.full((n,), float(rank), np.float32), name="big/x")
        out = np.asarray(hvd.synchronize(h))
        np.testing.assert_allclose(
            out[::65537], np.mean(np.arange(world, dtype=np.float32)))

    elif scenario == "spmd_allreduce":
        # launcher default mode: jax.distributed forms a global mesh and the
        # hot op rides XLA collectives, not the host ring (net is control
        # plane only). Verifies routing + numerics.
        import jax as _jax

        assert _jax.process_count() == world, (
            _jax.process_count(), world)
        from horovod_tpu.runtime.runtime import get_runtime
        rt = get_runtime()
        assert rt.executor._spmd_world
        assert rt.executor._proc_mesh is not None
        for step in range(3):
            h = hvd.allreduce_async(
                np.full((6,), float(hvd.rank()), np.float32), name="spmd/g")
            out = np.asarray(hvd.synchronize(h))
            np.testing.assert_allclose(
                out, np.mean(np.arange(world, dtype=np.float32)))
        h = hvd.allreduce_async(np.full((3,), 2.0, np.float32),
                                name="spmd/sum", average=False)
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   2.0 * world)
        # integer sum must be exact through the SPMD path
        h = hvd.allreduce_async(
            np.full((2,), 1 << 24, np.int32), name="spmd/int",
            average=False)
        out = np.asarray(hvd.synchronize(h))
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, (1 << 24) * world)
        # min/max/product through the XLA sub-mesh path
        h = hvd.allreduce_async(
            np.full((2,), float(hvd.rank() + 1), np.float32),
            name="spmd/min", op=hvd.Min)
        np.testing.assert_array_equal(np.asarray(hvd.synchronize(h)), 1.0)
        h = hvd.allreduce_async(
            np.full((2,), hvd.rank() + 2, np.int32),
            name="spmd/prod", op=hvd.Product)
        np.testing.assert_array_equal(
            np.asarray(hvd.synchronize(h)),
            int(np.prod(np.arange(2, world + 2, dtype=np.int64))))
        # 64-bit payloads can't ride the x32 XLA sub-mesh; the executor
        # must route them to the host ring EXACTLY (r5 — found live by
        # the verify drive: 2**40 came back as garbage pre-fix)
        h = hvd.allreduce_async(
            np.array([(1 << 40) + hvd.rank()], np.int64),
            name="spmd/i64", average=False)
        out = np.asarray(hvd.synchronize(h))
        assert out.dtype == np.int64
        assert out[0] == (1 << 40) * world + world * (world - 1) // 2, out
        h = hvd.allreduce_async(np.array([1e300], np.float64),
                                name="spmd/f64", average=False)
        out = np.asarray(hvd.synchronize(h))
        assert out.dtype == np.float64 and np.isfinite(out[0]), out
        np.testing.assert_allclose(out[0], 1e300 * world)

    elif scenario == "jit_train":
        # The canonical jax-surface-under-tpurun flow: jax.distributed has
        # formed one global mesh across processes; the jitted train step
        # is compiled over it with the batch sharded per process, and
        # gradient averaging falls out of the shardings as real
        # cross-process collectives.
        import jax as _jax
        import jax.numpy as jnp
        import optax

        from horovod_tpu import training
        from horovod_tpu.models.mnist import MnistConvNet

        assert _jax.process_count() == world

        model = MnistConvNet()
        opt = hvd.DistributedOptimizer(optax.sgd(0.05))
        state = training.create_train_state(model, opt, (1, 28, 28, 1))
        step, batch_sharding = training.make_train_step(model, opt)

        rng = np.random.RandomState(rank)  # DIFFERENT data per process
        p, s, o = state.params, state.batch_stats, state.opt_state
        for _ in range(3):
            local_x = rng.rand(4, 28, 28, 1).astype(np.float32)
            local_y = rng.randint(0, 10, 4).astype(np.int32)
            xb = _jax.make_array_from_process_local_data(
                batch_sharding, local_x)
            yb = _jax.make_array_from_process_local_data(
                batch_sharding, local_y)
            loss, p, s, o = step(p, s, o, xb, yb)
        assert np.isfinite(float(loss))
        # parameters must be identical on every process — broadcast from
        # rank 0 and compare (catches any silently-local gradient math)
        flat = np.concatenate([np.asarray(x).ravel()
                               for x in _jax.tree_util.tree_leaves(p)])
        h = hvd.broadcast_async(flat.astype(np.float32), 0, name="jt/check")
        root_flat = np.asarray(hvd.synchronize(h))
        np.testing.assert_allclose(root_flat, flat, rtol=1e-6, atol=1e-7)

    elif scenario == "kitchen_sink":
        # Everything at once: named grads in rank-skewed order, unnamed
        # eager ops, broadcast + ragged allgather in the same cycles, and
        # periodic shape changes — in BOTH launcher modes. Caught the
        # multi-controller eager-dispatch ordering bug (unnamed eager ops
        # must ride the runtime's single ordered lane, not dispatch global
        # programs from the caller thread).
        rngk = np.random.RandomState(1000 + rank)
        for step in range(20):
            order = rngk.permutation(6)
            hs = {}
            for i in order:
                hs[int(i)] = hvd.allreduce_async(
                    np.full((8 + i,), float(rank + i), np.float32),
                    name=f"ks/g{i}")
            u = hvd.allreduce(np.full((4,), float(rank), np.float32))
            np.testing.assert_allclose(
                np.asarray(u), np.mean(np.arange(world, dtype=np.float32)))
            b = hvd.broadcast_async(
                np.full((3,), float(rank), np.float32),
                root_rank=step % world, name="ks/b")
            g = hvd.allgather_async(
                np.full((rank + 1, 2), float(rank), np.float32),
                name="ks/ag")
            for i, h in hs.items():
                expect = np.mean([r + i for r in range(world)])
                np.testing.assert_allclose(
                    np.asarray(hvd.synchronize(h)), expect,
                    err_msg=f"step {step} grad {i}")
            np.testing.assert_allclose(np.asarray(hvd.synchronize(b)),
                                       float(step % world))
            ag = np.asarray(hvd.synchronize(g))
            expect = np.concatenate(
                [np.full((r + 1, 2), float(r), np.float32)
                 for r in range(world)])
            np.testing.assert_allclose(ag, expect)
            if step % 8 == 7:  # shape change -> synchronized invalidation
                h = hvd.allreduce_async(
                    np.ones((step,), np.float32), name="ks/shapeshift")
                np.testing.assert_allclose(
                    np.asarray(hvd.synchronize(h)), 1.0)

    elif scenario == "keras":
        # The keras-style Trainer under the launcher: fit/evaluate over
        # the jax.distributed global mesh, metric averaging across ranks.
        import jax as _jax
        import optax

        import horovod_tpu.keras as hvd_keras
        from horovod_tpu import callbacks
        from horovod_tpu.models.mnist import MnistConvNet

        assert _jax.process_count() == world
        rng = np.random.RandomState(0)  # same data everywhere
        x = rng.rand(64, 28, 28, 1).astype(np.float32)
        y = rng.randint(0, 10, 64).astype(np.int32)
        trainer = hvd_keras.Trainer(
            MnistConvNet(), optax.sgd(0.05 * hvd.size()), (1, 28, 28, 1))
        history = trainer.fit(
            x, y, epochs=2, batch_size=32,
            callbacks=[callbacks.MetricAverageCallback()])
        assert len(history["loss"]) == 2
        assert np.isfinite(history["loss"]).all()
        metrics = trainer.evaluate(x, y)
        assert np.isfinite(metrics["loss"])

    elif scenario == "shape_mismatch":
        # reference: error paths (test_tensorflow.py:314-384) — mismatched
        # shapes across ranks must error on every rank
        shape = (4,) if rank == 0 else (5,)
        h = hvd.allreduce_async(np.ones(shape, np.float32), name="bad/x")
        try:
            hvd.synchronize(h)
        except RuntimeError as e:
            assert "shape" in str(e).lower(), str(e)
        else:
            raise AssertionError("expected shape mismatch error")
        # the world must still be usable afterwards
        h = hvd.allreduce_async(np.ones((2,), np.float32), name="good/x",
                                average=False)
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   float(world))

    elif scenario == "stall_shutdown":
        # reference: test/test_stall.py — one rank never submits; stall
        # inspector triggers global shutdown
        if rank == 0:
            h = hvd.allreduce_async(np.ones((2,), np.float32), name="stall/x")
            try:
                hvd.synchronize(h)
                raise AssertionError("expected shutdown error")
            except RuntimeError as e:
                assert "shut down" in str(e).lower() or "fail" in str(e).lower(), str(e)
        else:
            # never submit; wait for the coordinator-triggered shutdown to
            # propagate through the status bits
            import time

            deadline = time.time() + 30
            from horovod_tpu.core import state
            rt = state.global_state().runtime
            # rank!=0 needs the runtime started to participate in cycles
            from horovod_tpu.runtime.runtime import get_runtime
            rt = get_runtime()
            while time.time() < deadline and rt._thread.is_alive():
                time.sleep(0.1)
            assert not rt._thread.is_alive(), "shutdown did not propagate"
    elif scenario == "peer_death":
        # A rank dying mid-training must fail the survivors' pending work
        # loudly, never hang (reference: any rank failure aborts the job —
        # gloo_run.py:256-262 at the launcher, SHUT_DOWN_ERROR to pending
        # callbacks at the runtime, operations.cc:480-486).
        h = hvd.allreduce_async(np.ones((4,), np.float32), name="pd/warm")
        hvd.synchronize(h)  # world is healthy once
        if rank == 1:
            os._exit(17)  # abrupt death: no shutdown handshake, no atexit
        import time

        deadline = time.time() + 60
        got_error = None
        while time.time() < deadline and got_error is None:
            try:
                h = hvd.allreduce_async(
                    np.ones((4,), np.float32), name=f"pd/{time.time_ns()}")
                hvd.synchronize(h)
                time.sleep(0.2)  # peer may not have died yet; retry
            except (RuntimeError, TimeoutError) as e:
                got_error = e
        assert got_error is not None, \
            "survivor never observed the peer's death"

    elif scenario == "unnamed_eager":
        # Unnamed eager collectives must really communicate in a
        # multi-process world (auto call-order names through the runtime,
        # like the reference's unnamed torch ops) — NOT return local-only
        # "replicated" math.
        out = hvd.allreduce(np.full((4,), float(rank), np.float32))
        np.testing.assert_allclose(
            np.asarray(out), np.mean(np.arange(world, dtype=np.float32)))
        out = hvd.allreduce(np.full((4,), float(rank), np.float32),
                            op=hvd.Sum)
        np.testing.assert_allclose(
            np.asarray(out), np.sum(np.arange(world, dtype=np.float32)))
        g = hvd.allgather(np.array([float(rank)], np.float32))
        np.testing.assert_allclose(
            np.asarray(g), np.arange(world, dtype=np.float32))
        b = hvd.broadcast(np.full((3,), float(rank), np.float32),
                          root_rank=1)
        np.testing.assert_allclose(np.asarray(b), 1.0)
        # eager min/max/product: same execution modes as sum/average now
        # (the r1 API-surface inconsistency is gone)
        out = hvd.allreduce(np.full((4,), float(rank + 1), np.float32),
                            op=hvd.Min)
        np.testing.assert_array_equal(np.asarray(out), 1.0)
        out = hvd.allreduce(np.full((4,), float(rank + 1), np.float32),
                            op=hvd.Max)
        np.testing.assert_array_equal(np.asarray(out), float(world))
        out = hvd.allreduce(np.full((4,), rank + 2, np.int32),
                            op=hvd.Product)
        np.testing.assert_array_equal(
            np.asarray(out),
            int(np.prod(np.arange(2, world + 2, dtype=np.int64))))
        # grouped: all tensors enqueue before any synchronize, so the
        # runtime fuses them within one cycle
        group = hvd.grouped_allreduce(
            [np.full((k + 1,), float(rank), np.float32) for k in range(4)],
            op=hvd.Sum)
        for k, g in enumerate(group):
            assert g.shape == (k + 1,)
            np.testing.assert_allclose(
                np.asarray(g), np.sum(np.arange(world, dtype=np.float32)))
    elif scenario == "soak":
        # Combined stress (VERDICT r1 #8): autotune param sync + cache
        # churn/invalidation + skewed arrival + torch hooks + eager
        # interleave, all SIMULTANEOUSLY for SOAK_SECONDS, then a
        # bit-alignment audit. Each ingredient has a dedicated test; this
        # proves they compose (the reference's tests run the whole runtime
        # under mpirun the same way, SURVEY.md §4).
        import time

        import torch
        import horovod_tpu.torch as thvd

        soak_seconds = float(os.environ.get("SOAK_SECONDS", "45"))
        rng = np.random.RandomState(1000 + rank)

        model = torch.nn.Linear(6, 3)
        for p in model.parameters():  # identical start on every rank
            torch.nn.init.constant_(p, 0.5)
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters())

        n_churn = 6  # 2x the cache capacity set by the test
        shapes = [(4,), (8,)]
        deadline = time.monotonic() + soak_seconds
        it = 0
        world_mean = np.mean(np.arange(world, dtype=np.float32))
        # time-bounded, but with an iteration floor so a heavily loaded
        # box still does real combined work (and a ceiling so a fast box
        # is bounded by the deadline, not the floor)
        min_iters = int(os.environ.get("SOAK_MIN_ITERS", "5"))
        debug = os.environ.get("SOAK_DEBUG")
        while True:
            # Collective termination: per-rank clocks diverge, and a rank
            # that exits one iteration before its peers strands their last
            # enqueues forever. The continue flag is itself a Min
            # allreduce over the new wire op — every rank stops at the
            # SAME iteration, the first one where any rank's deadline
            # passed.
            my_continue = 1.0 if (time.monotonic() < deadline
                                  or it < min_iters) else 0.0
            cont = hvd.synchronize(hvd.allreduce_async(
                np.full((1,), my_continue, np.float32),
                name="soak/continue", op=hvd.Min))
            if float(np.asarray(cont)[0]) < 1.0:
                break
            it += 1
            if debug:
                print(f"[r{rank}] iter {it} "
                      f"t={time.monotonic() - deadline + soak_seconds:.1f}",
                      file=sys.stderr, flush=True)
            # skewed arrival: per-rank jitter far beyond the cycle time
            time.sleep(float(rng.uniform(0, 0.02)))
            # cache churn: rotating names, period-flipping shapes
            # (invalidation), random submission order per rank
            order = rng.permutation(n_churn)  # per-rank order
            shape = shapes[(it // 7) % 2]
            handles = [
                hvd.allreduce_async(
                    np.full(shape, float(rank), np.float32),
                    name=f"soak/churn_{k}")
                for k in order
            ]
            # torch hook-driven step on per-rank data (its own named ops)
            x = torch.full((5, 6), float(rank + it % 3))
            opt.zero_grad()
            model(x).sum().backward()
            opt.step()
            # eager interleave: unnamed op through the same ordered lane
            out = hvd.allreduce(np.full((3,), float(rank), np.float32))
            np.testing.assert_allclose(np.asarray(out), world_mean,
                                       rtol=1e-5)
            for h in handles:
                np.testing.assert_allclose(
                    np.asarray(hvd.synchronize(h)), world_mean, rtol=1e-5)

        # parameters must not have diverged across ranks (hooks averaged
        # every gradient)
        digest = thvd.allgather(
            torch.cat([p.detach().reshape(-1)
                       for p in model.parameters()]).reshape(1, -1),
            name="soak/weights")
        for r in range(1, world):
            assert torch.equal(digest[0], digest[r]), \
                f"rank weights diverged after {it} iterations"
        # bit-alignment audit: every rank's cache must map the same names
        # to the same bits (the invariant cache churn attacks)
        from horovod_tpu.core import state as state_mod

        cache = state_mod.global_state().runtime.controller.cache
        bits = ";".join(
            f"{k}={cache.bit_for_name(f'soak/churn_{k}')}"
            for k in range(n_churn))
        assert it >= min_iters
        blobs = hvd.synchronize(hvd.allgather_async(
            np.frombuffer(bits.ljust(256).encode(), dtype=np.uint8)
            .reshape(1, -1).copy(), name="soak/bits"))
        rows = np.asarray(blobs)
        for r in range(1, world):
            assert np.array_equal(rows[0], rows[r]), (
                "cache bit maps diverged:\n"
                + rows[0].tobytes().decode()
                + "\nvs\n" + rows[r].tobytes().decode())
        print(f"soak: {it} iterations, bit map {bits!r}", flush=True)

    elif scenario == "lane_misuse":
        # SPMD mode only: a caller-thread global-mesh program while named
        # async ops are in flight is the documented cross-rank
        # program-order hazard (docs/troubleshooting.md) — it must RAISE
        # now, not hang. Legal path first: nothing in flight, eager
        # stacked dispatch is fine.
        import jax as _jax

        assert _jax.process_count() == world
        s = hvd.stack_per_worker(
            [np.full((2,), float(r), np.float32) for r in range(world)])
        out = hvd.allreduce(s, op=hvd.Sum)
        np.testing.assert_allclose(
            np.asarray(out), np.sum(np.arange(world, dtype=np.float32)))
        # a name only this rank announces can never complete -> stays in
        # flight deterministically
        h = hvd.allreduce_async(np.full((4,), 1.0, np.float32),
                                name=f"lane/only_rank_{rank}")
        try:
            hvd.allreduce(s, op=hvd.Sum)
        except hvd.OrderedLaneError:
            pass
        else:
            raise AssertionError("expected OrderedLaneError")
        # the public guard for user-owned pjit programs sees it too
        try:
            hvd.assert_collective_lane_clear()
        except hvd.OrderedLaneError:
            pass
        else:
            raise AssertionError("expected OrderedLaneError from guard")
        del h  # completed with SHUT_DOWN_ERROR at shutdown

    elif scenario == "cache_churn":
        # Tiny cache capacity + periodically changing shapes: constant
        # evictions (LRU bit recycling) and synchronized invalidations
        # while ranks submit in different orders. Any cross-worker
        # cache-bit misalignment — the invariant the native cache
        # (cpp/cycle.cc) must uphold — corrupts results immediately
        # (reference: response_cache.cc:232+ bit redistribution).
        rng_order = np.random.RandomState(100 + rank)  # per-rank order
        n_tensors = 12  # 3x the cache capacity set by the test
        for rounds in range(12):
            order = rng_order.permutation(n_tensors)
            handles = {}
            for t in order:
                # every 4th round, tensor shapes shift -> INVALID ->
                # synchronized invalidation + renegotiation
                size = 3 + int(t) + (rounds // 4)
                handles[int(t)] = hvd.allreduce_async(
                    np.full((size,), float(rank + t), np.float32),
                    name=f"cc/{t}", average=False)
            for t, h in handles.items():
                out = np.asarray(hvd.synchronize(h))
                expect = np.full(
                    (3 + t + (rounds // 4),),
                    sum(r + t for r in range(world)), np.float32)
                np.testing.assert_allclose(out, expect,
                                           err_msg=f"round {rounds} t {t}")
        from horovod_tpu.core import state
        cache = state.global_state().runtime.controller.cache
        assert len(cache) <= 4, len(cache)  # capacity respected

    elif scenario == "fusion_stress":
        # Many named tensors of mixed sizes/dtypes in flight per cycle —
        # the fusion bin-packer and response cache under load (reference:
        # test_tensorflow.py:152 fused many-small-tensors coverage). Ranks
        # submit in different orders; the negotiation must still converge
        # and every result must unfuse to the right buffer.
        # x64 on, so the float64 specs genuinely exercise a distinct
        # element size in the bin-packer rather than downcasting to f32.
        jax.config.update("jax_enable_x64", True)
        rng = np.random.RandomState(7)  # same on all ranks
        specs = []
        for t in range(60):
            dt = [np.float32, np.float64, np.int32][t % 3]
            shape = (int(rng.randint(1, 2000)),)
            specs.append((f"fs/{t}", dt, shape))
        for rounds in range(3):
            order = list(range(len(specs)))
            # rank-dependent submission order (reference: grads arrive in
            # different orders per rank)
            if rank % 2:
                order = order[::-1]
            handles = {}
            for t in order:
                name, dt, shape = specs[t]
                handles[t] = hvd.allreduce_async(
                    np.full(shape, float(rank + t), dt), name=name,
                    op=hvd.Sum)
            for t, h in handles.items():
                name, dt, shape = specs[t]
                out = np.asarray(hvd.synchronize(h))
                assert out.dtype == dt, (name, out.dtype, dt)
                expect = sum(float(r + t) for r in range(world))
                np.testing.assert_allclose(out, np.full(shape, expect),
                                           rtol=1e-6)
    elif scenario == "ring_sp":
        # Long-context path across REAL process boundaries: ring attention
        # ppermutes K/V around a process-spanning mesh; every shard must
        # match the dense reference.
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        import jax as _jax
        from horovod_tpu.ops.pallas import attention_reference

        assert _jax.process_count() == world
        mesh = hvd.mesh()
        B, H, S, D = 1, 2, 32, 16
        rngr = np.random.RandomState(0)  # same inputs on all ranks
        q = jnp.asarray(rngr.randn(B, H, S, D).astype(np.float32))
        k = jnp.asarray(rngr.randn(B, H, S, D).astype(np.float32))
        v = jnp.asarray(rngr.randn(B, H, S, D).astype(np.float32))

        def ring(q, k, v):
            return hvd.ring_attention(q, k, v, hvd.GLOBAL_AXES, True,
                                      None, 8, 8, 8, 8)

        spec = P(None, None, hvd.GLOBAL_AXES, None)
        out = _jax.jit(_jax.shard_map(
            ring, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))(q, k, v)
        ref = attention_reference(q, k, v, causal=True)
        shard = out.addressable_shards[0]
        got = np.asarray(_jax.device_get(shard.data))
        start = shard.index[2].start or 0
        np.testing.assert_allclose(
            got, np.asarray(ref)[:, :, start:start + got.shape[2]],
            rtol=2e-4, atol=2e-4)

    elif scenario == "pp_ep_xproc":
        # Pipeline (ppermute) and expert (all_to_all) parallelism across
        # REAL process boundaries, checked against local single-device
        # math computed from the same seeds.
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        import jax as _jax

        assert _jax.process_count() == world
        mesh = hvd.mesh()
        n_stage = mesh.shape[hvd.LOCAL_AXIS] * mesh.shape[hvd.CROSS_AXIS]
        # pipeline/moe take ONE mesh axis; pick the one spanning the world
        axis = (hvd.LOCAL_AXIS if mesh.shape[hvd.LOCAL_AXIS] == n_stage
                else hvd.CROSS_AXIS)
        rngp = np.random.RandomState(0)
        stage_ws = [rngp.randn(6, 6).astype(np.float32) * 0.3
                    for _ in range(n_stage)]
        stages = hvd.stack_stage_params([{"w": jnp.asarray(w)}
                                         for w in stage_ws])
        x = jnp.asarray(rngp.randn(4, 2, 6).astype(np.float32))

        def pp(stages, x):
            out = hvd.pipeline_apply(
                lambda p, h: jnp.tanh(h @ p["w"]), stages, x, axis)
            return hvd.last_stage_value(jnp.mean(out ** 2), axis)

        loss = _jax.jit(_jax.shard_map(
            pp, mesh=mesh, in_specs=(P(hvd.GLOBAL_AXES), P()),
            out_specs=P(), check_vma=False))(stages, x)
        # local reference: run the microbatches through all stages
        h = np.asarray(x)
        for w in stage_ws:
            h = np.tanh(h @ w)
        np.testing.assert_allclose(float(loss), float(np.mean(h ** 2)),
                                   rtol=1e-5)

        # expert parallelism: one expert per worker, all_to_all routing
        experts = hvd.stack_stage_params([
            {"w": jnp.asarray(rngp.randn(6, 6).astype(np.float32) * 0.3)}
            for _ in range(n_stage)])
        gate_w = jnp.asarray(rngp.randn(6, n_stage).astype(np.float32))
        xe = jnp.asarray(rngp.randn(n_stage * 4, 6).astype(np.float32))

        def ep(experts, gate_w, xe):
            y, probs = hvd.switch_moe(
                xe, xe @ gate_w, lambda p, h: jnp.tanh(h @ p["w"]),
                experts, axis, capacity=8)
            return jax.lax.pmean(jnp.mean(y ** 2), axis)

        mse = _jax.jit(_jax.shard_map(
            ep, mesh=mesh,
            in_specs=(P(hvd.GLOBAL_AXES), P(), P(hvd.GLOBAL_AXES)),
            out_specs=P(), check_vma=False))(experts, gate_w, xe)
        assert np.isfinite(float(mse))

    elif scenario == "dtype_matrix":
        # Reference-breadth dtype x op sweep over the REAL wire (r5;
        # reference: test/test_torch.py dtype sweeps ~1,382 LoC,
        # test_tensorflow.py:152-649 fused many-small + variable-size
        # allgather per dtype). Values deliberately include payloads
        # that corrupt if anything narrows to 32-bit (2**40 int64,
        # 1e300 float64) — the widening shim (runtime/executor.py
        # _widen_for_ring) and the enqueue conversion (_to_plane) are
        # exactly where such corruption would hide.
        import ml_dtypes

        dtypes = [np.uint8, np.int8, np.int16, np.uint16, np.int32,
                  np.uint32, np.int64, np.float16, ml_dtypes.bfloat16,
                  np.float32, np.float64, np.bool_]

        def per_rank_value(dti, r):
            if dti == np.bool_:
                return bool(r % 2)
            if dti.kind in "iu":
                big = (1 << 40) if dti.itemsize == 8 else 0
                return dti.type(big + 3 * (r + 1))
            big = 1e300 if dti == np.float64 else 0.0
            return dti.type(big + 1.5 * (r + 1))

        for dt in dtypes:
            dti = np.dtype(dt)
            tag = dti.name
            x = np.full((6,), per_rank_value(dti, rank), dti)
            # -- allreduce sum (exact, computed wide then cast like the
            #    ring kernels)
            out = np.asarray(hvd.synchronize(hvd.allreduce_async(
                x, name=f"dm/{tag}/ar", average=False)))
            assert out.dtype == dti, (tag, out.dtype)
            wide = np.int64 if dti.kind in "iu" else np.float64
            expect = np.sum([np.asarray(per_rank_value(dti, r),
                                        dtype=wide)
                             for r in range(world)]).astype(dti)
            np.testing.assert_array_equal(out, np.full((6,), expect),
                                          err_msg=f"allreduce {tag}")
            # -- allreduce min (op-generalized ring) for ordered dtypes
            if dti != np.bool_:
                out = np.asarray(hvd.synchronize(hvd.allreduce_async(
                    x, name=f"dm/{tag}/min", op=hvd.Min)))
                np.testing.assert_array_equal(
                    out, np.full((6,), per_rank_value(dti, 0), dti),
                    err_msg=f"min {tag}")
            # -- broadcast root 1
            out = np.asarray(hvd.synchronize(hvd.broadcast_async(
                x, root_rank=1, name=f"dm/{tag}/bc")))
            assert out.dtype == dti, (tag, out.dtype)
            np.testing.assert_array_equal(
                out, np.full((6,), per_rank_value(dti, 1), dti),
                err_msg=f"broadcast {tag}")
            # -- variable-size allgather: rank r contributes (r+1, 2)
            out = np.asarray(hvd.synchronize(hvd.allgather_async(
                np.full((rank + 1, 2), per_rank_value(dti, rank), dti),
                name=f"dm/{tag}/agv")))
            expect = np.concatenate(
                [np.full((r + 1, 2), per_rank_value(dti, r), dti)
                 for r in range(world)])
            assert out.dtype == dti, (tag, out.dtype)
            np.testing.assert_array_equal(out, expect,
                                          err_msg=f"allgather {tag}")
            if dti == np.bool_:
                continue  # rs/a2a arithmetic on bool is not a contract
            # -- reducescatter sum: dim 0 = world*2
            data = np.stack([
                (np.arange(world * 2 * 3) % 5 + 1).reshape(world * 2, 3)
                .astype(wide) * np.asarray(per_rank_value(dti, r), wide)
                for r in range(world)])
            mine = data[rank].astype(dti)
            out = np.asarray(hvd.reducescatter(mine, op=hvd.Sum))
            assert out.dtype == dti, (tag, out.dtype)
            full = np.sum([data[r].astype(wide) for r in range(world)],
                          axis=0).astype(dti)
            np.testing.assert_array_equal(
                out, full[rank * 2:(rank + 1) * 2],
                err_msg=f"reducescatter {tag}")
            # -- alltoall
            out = np.asarray(hvd.alltoall(mine, name=f"dm/{tag}/a2a"))
            assert out.dtype == dti, (tag, out.dtype)
            expect = np.concatenate(
                [data[j].astype(dti)[rank * 2:(rank + 1) * 2]
                 for j in range(world)])
            np.testing.assert_array_equal(out, expect,
                                          err_msg=f"alltoall {tag}")

        # -- fused many-small ACROSS dtypes: every tensor enqueued before
        #    any synchronize, so one cycle negotiates and bin-packs the
        #    whole burst in per-dtype fusion groups (reference:
        #    test_tensorflow.py fused many-small sweeps)
        handles = []
        for dt in dtypes:
            dti = np.dtype(dt)
            for i in range(6):
                arr = np.full((4,), per_rank_value(dti, rank), dti)
                handles.append((dti, i, hvd.allreduce_async(
                    arr, name=f"dmf/{dti.name}/{i}", average=False)))
        for dti, i, h in handles:
            out = np.asarray(hvd.synchronize(h))
            wide = np.int64 if dti.kind in "iu" else np.float64
            expect = np.sum([np.asarray(per_rank_value(dti, r),
                                        dtype=wide)
                             for r in range(world)]).astype(dti)
            np.testing.assert_array_equal(
                out, np.full((4,), expect),
                err_msg=f"fused burst {dti.name}/{i}")

    elif scenario == "torch_sink":
        # Torch hook-driven optimizer with gradient accumulation, eager
        # ops interleaved while async allreduces are in flight, and a
        # final cross-rank parameter-identity check.
        import torch
        import torch.nn.functional as F

        import horovod_tpu.torch as thvd

        torch.manual_seed(42)
        model = torch.nn.Sequential(
            torch.nn.Linear(16, 32), torch.nn.ReLU(),
            torch.nn.Linear(32, 4))
        opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)
        opt = thvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        thvd.broadcast_parameters(model.state_dict(), root_rank=0)
        thvd.broadcast_optimizer_state(opt, root_rank=0)
        rng = np.random.RandomState(rank)
        for step in range(10):
            for _ in range(2):
                x = torch.tensor(rng.rand(8, 16), dtype=torch.float32)
                y = torch.tensor(rng.randint(0, 4, (8,)), dtype=torch.long)
                F.cross_entropy(model(x), y).backward()
            m = thvd.allreduce(torch.tensor([float(rank)]),
                               name=f"ts/metric{step}")
            assert abs(float(m) - np.mean(range(world))) < 1e-6
            opt.step()
            opt.zero_grad()
        flat = torch.cat([p.data.flatten() for p in model.parameters()])
        root = thvd.broadcast(flat.clone(), root_rank=0, name="ts/final")
        assert torch.allclose(root, flat, rtol=1e-5, atol=1e-7)

    elif scenario == "torch":
        # The torch binding end-to-end under a real multi-process world
        # (reference: test/test_torch.py run under mpirun): hook-driven
        # DistributedOptimizer training convergence across ranks, plus
        # parameter/optimizer-state/object broadcast from rank 0.
        import torch

        import horovod_tpu.torch as thvd

        # distinct per-rank values average correctly
        x = torch.full((5,), float(rank))
        out = thvd.allreduce(x, name="t/ar")
        expected = float(np.mean(np.arange(world)))
        assert torch.allclose(out, torch.full((5,), expected)), out

        # ragged allgather
        g = thvd.synchronize(
            thvd.allgather_async(torch.full((rank + 1, 2), float(rank)),
                                 name="t/ag"))
        want = torch.cat(
            [torch.full((r + 1, 2), float(r)) for r in range(world)])
        assert torch.equal(g, want), g

        # model + optimizer: ranks start with different weights, broadcast
        # aligns them, hooks average gradients of per-rank data so all
        # ranks stay in lockstep
        torch.manual_seed(rank)  # deliberately different init per rank
        model = torch.nn.Linear(4, 2)
        opt = thvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9),
            named_parameters=model.named_parameters())
        thvd.broadcast_parameters(model.state_dict(), root_rank=0)
        thvd.broadcast_optimizer_state(opt, root_rank=0)
        torch.manual_seed(100 + rank)  # different data per rank
        for _ in range(3):
            data = torch.randn(8, 4)
            target = torch.randn(8, 2)
            loss = (model(data) - target).pow(2).mean()
            loss.backward()
            opt.step()
            opt.zero_grad()
        # weights must be bitwise-identical across ranks after sync steps
        digest = thvd.allgather(
            torch.cat([p.detach().reshape(-1) for p in model.parameters()])
            .reshape(1, -1), name="t/weights")
        for r in range(1, world):
            assert torch.equal(digest[0], digest[r]), "ranks diverged"

        # object broadcast (resume-epoch convention)
        obj = {"epoch": 7, "rank_was": 0} if rank == 0 else None
        got = thvd.broadcast_object(obj, root_rank=0, name="t/obj")
        assert got == {"epoch": 7, "rank_was": 0}, got

        # sparse embedding exchange (BASELINE config #5): each rank
        # touches different rows; the allgather-based sparse allreduce
        # must equal the dense average
        emb = torch.nn.Embedding(10, 4, sparse=True)
        thvd.broadcast_parameters(emb.state_dict(), root_rank=0)
        ids = torch.tensor([rank, rank + 1, 5])  # overlap on 5
        opt2 = thvd.DistributedOptimizer(
            torch.optim.SGD(emb.parameters(), lr=1.0),
            named_parameters=emb.named_parameters())
        w_before = emb.weight.detach().clone()
        emb(ids).sum().backward()
        opt2.synchronize()
        g = emb.weight.grad.coalesce().to_dense()
        dense = torch.zeros(10, 4)
        for r in range(world):
            for row in (r, r + 1, 5):
                dense[row] += 1.0
        np.testing.assert_allclose(np.asarray(g), np.asarray(dense / world),
                                   rtol=1e-6)
        with opt2.skip_synchronize():
            opt2.step()
        # sparse SGD applies the averaged rows; all ranks identical
        dig = thvd.allgather(emb.weight.detach().reshape(1, -1),
                             name="t/emb")
        for r in range(1, world):
            assert torch.equal(dig[0], dig[r]), "embedding diverged"
        np.testing.assert_allclose(
            np.asarray(emb.weight.detach()),
            np.asarray(w_before - dense / world), rtol=1e-5)

    elif scenario == "lane_hazard":
        # The user-owned-global-program interleaving hazard (VERDICT r2
        # ask 8): rank 0 has a named op in flight while "its caller
        # thread runs its own global program" (simulated by sleeping —
        # the runtime only sees silence); rank 1 never announces the
        # tensor. The lane watchdog must print its diagnostic within
        # one stall-check period (the test asserts on our output).
        import time as _time

        # both ranks bring the runtime up (the comm is created lazily on
        # first use) and agree on a warmup tensor first
        hvd.allreduce(np.ones(2, np.float32), name="hazard/warm")
        if rank == 0:
            h = hvd.allreduce_async(np.ones(4, np.float32),
                                    name="hazard/x")
            _time.sleep(2.5)  # > 2 stall periods of 0.5s
            try:
                hvd.synchronize(h)
            except Exception:
                pass  # peers shut down; the hang became an error — fine
        else:
            _time.sleep(2.5)

    elif scenario == "tensorflow":
        # The TF binding end-to-end under a real multi-process world
        # (reference: test/test_tensorflow.py run under mpirun): eager
        # collectives, custom gradients, DistributedGradientTape +
        # DistributedOptimizer lockstep training, broadcast_variables,
        # IndexedSlices gather path, object broadcast.
        import tensorflow as tf

        import horovod_tpu.tensorflow as tfhvd

        # distinct per-rank values: average and sum
        x = tf.fill([5], float(rank))
        out = tfhvd.allreduce(x, average=True)
        expected = float(np.mean(np.arange(world)))
        np.testing.assert_allclose(out.numpy(), np.full(5, expected),
                                   rtol=1e-6)
        out = tfhvd.allreduce(x, average=False)
        np.testing.assert_allclose(out.numpy(),
                                   np.full(5, float(sum(range(world)))),
                                   rtol=1e-6)

        # ragged allgather
        g = tfhvd.allgather(tf.fill([rank + 1, 2], float(rank)))
        want = np.concatenate(
            [np.full((r + 1, 2), float(r)) for r in range(world)])
        np.testing.assert_allclose(g.numpy(), want)

        # broadcast from a non-zero root
        b = tfhvd.broadcast(tf.fill([3], float(rank)), root_rank=1)
        np.testing.assert_allclose(b.numpy(), np.full(3, 1.0))

        # gradient THROUGH a collective (custom_gradient):
        # y = sum(allreduce_sum(x)) -> dy/dx = allreduce_sum(ones) = world
        xv = tf.Variable([1.0, 2.0])
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(tfhvd._allreduce(xv))
        gx = tape.gradient(y, xv)
        np.testing.assert_allclose(gx.numpy(), [world, world], rtol=1e-6)

        # DistributedGradientTape: per-rank loss scale (rank+1) ->
        # averaged gradient = mean over ranks of 2*(rank+1)*v
        v = tf.Variable([1.0, 3.0])
        with tf.GradientTape() as tape:
            loss = (rank + 1) * tf.reduce_sum(v * v)
        dtape = tfhvd.DistributedGradientTape(tape)
        grads = dtape.gradient(loss, [v])
        scale = np.mean([r + 1 for r in range(world)])
        np.testing.assert_allclose(grads[0].numpy(), 2 * scale * v.numpy(),
                                   rtol=1e-6)

        # broadcast_variables aligns different inits; DistributedOptimizer
        # keeps ranks in lockstep over different per-rank data
        tf.random.set_seed(rank)
        w = tf.Variable(tf.random.normal([4, 2]))
        bias = tf.Variable(tf.random.normal([2]))
        tfhvd.broadcast_variables([w, bias], root_rank=0)
        opt = tfhvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
        tf.random.set_seed(100 + rank)  # different data per rank
        for _ in range(3):
            data = tf.random.normal([8, 4])
            target = tf.random.normal([8, 2])
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean(
                    tf.square(tf.matmul(data, w) + bias - target))
            grads = tape.gradient(loss, [w, bias])
            opt.apply_gradients(zip(grads, [w, bias]))
        digest = tfhvd.allgather(tf.reshape(
            tf.concat([tf.reshape(w, [-1]), tf.reshape(bias, [-1])], 0),
            [1, -1]))
        for r in range(1, world):
            np.testing.assert_array_equal(digest[0].numpy(),
                                          digest[r].numpy(),
                                          err_msg="ranks diverged")

        # IndexedSlices -> gather path (embedding-style sparse grads)
        s = tf.IndexedSlices(tf.fill([2, 3], float(rank + 1)),
                             tf.constant([rank, rank + 1]),
                             tf.constant([world + 1, 3]))
        r = tfhvd.allreduce(s, average=False)
        assert r.values.shape[0] == 2 * world, r.values.shape
        got_idx = np.sort(r.indices.numpy())
        want_idx = np.sort(np.concatenate(
            [[rr, rr + 1] for rr in range(world)]))
        np.testing.assert_array_equal(got_idx, want_idx)

        # object broadcast (resume-epoch convention)
        obj = {"epoch": 7, "rank_was": 0} if rank == 0 else None
        got = tfhvd.broadcast_object(obj, root_rank=0, name="tf/obj")
        assert got == {"epoch": 7, "rank_was": 0}, got

        # dtype sweep with DISTINCT per-rank values through the TF layer
        # (reference: test_tensorflow.py:314-460 sweeps dtypes x dims
        # across ranks; the single-controller tests can only assert
        # replicated-world identities)
        for tf_dt, avg in [(tf.float32, True), (tf.float64, True),
                           (tf.bfloat16, True), (tf.int32, False),
                           (tf.int64, False)]:
            for dim in (1, 2):
                shape = (3,) * dim
                x = tf.cast(tf.fill(shape, rank + 1), tf_dt)
                out = tfhvd.allreduce(x, average=avg, name=None)
                assert out.dtype == tf_dt, (tf_dt, out.dtype)
                vals = [r + 1 for r in range(world)]
                want = np.mean(vals) if avg else np.sum(vals)
                np.testing.assert_allclose(
                    np.asarray(tf.cast(out, tf.float64).numpy()),
                    np.full(shape, want), rtol=1e-2)
                # allgather the same dtype: distinct rank rows
                ga = tfhvd.allgather(tf.cast(
                    tf.fill((1,) + shape, rank), tf_dt))
                assert ga.shape[0] == world
                np.testing.assert_allclose(
                    np.asarray(tf.cast(ga, tf.float64).numpy())[..., 0]
                    .reshape(world, -1)[:, 0], np.arange(world))

        # fused many-small-tensors burst THROUGH the TF tape (VERDICT r3
        # ask 5/6): 48 small grads in one DistributedGradientTape.gradient
        # call must ride few fused cycles, not 48 rings — asserted on the
        # deterministic exchange-calls counter, not wall clock
        from horovod_tpu.core import state as _state

        net = _state.global_state().runtime.controller.net
        n_small = 48
        # identical weights everywhere, per-rank LOSS scale: the averaged
        # gradient is then 2 * mean(rank+1) * w — cross-rank averaging is
        # observable while the expectation stays closed-form
        weights = [tf.Variable(tf.fill([7 + (i % 5)], float(i + 1)))
                   for i in range(n_small)]
        with tf.GradientTape() as tape:
            loss = tf.add_n([tf.reduce_sum(w * w) * (rank + 1)
                             for w in weights])
        dtape = tfhvd.DistributedGradientTape(tape)
        ex0 = net.exchange_calls()
        grads = dtape.gradient(loss, weights)
        ex1 = net.exchange_calls()
        mean_scale = np.mean([r + 1 for r in range(world)])
        for i, (w, g) in enumerate(zip(weights, grads)):
            np.testing.assert_allclose(
                g.numpy(), 2 * mean_scale * w.numpy(), rtol=1e-5)
        # unfused would cost 2*(world-1) ring exchanges PER gradient =
        # 2*(w-1)*48; fused bin-packing collapses the burst into a
        # handful of buffers. Generous bound: a quarter of unfused.
        unfused = 2 * (world - 1) * n_small
        burst = ex1 - ex0
        assert burst <= unfused // 4, \
            f"TF tape burst not fused: {burst} exchanges (unfused={unfused})"
        print(f"tf-tape-burst exchanges={burst} unfused={unfused}",
              flush=True)

    elif scenario == "tensorflow_graph":
        # TF1 graph-mode path across a real multi-process world
        # (reference: horovod/tensorflow/__init__.py:125-192 —
        # broadcast_global_variables + BroadcastGlobalVariablesHook under
        # MonitoredTrainingSession): per-rank divergent initializers must
        # converge to rank 0's values through the session-run broadcast.
        import tensorflow as tf

        import horovod_tpu.tensorflow as tfhvd

        g = tf.Graph()
        with g.as_default():
            assert not tf.executing_eagerly()
            v1 = tf.compat.v1.get_variable(
                "v1", initializer=np.full((3, 2), float(rank + 1),
                                          np.float32))
            v2 = tf.compat.v1.get_variable(
                "v2", initializer=np.asarray([10.0 * (rank + 1)],
                                             np.float32))
            # int64 variable: exercises the 64-bit bit-pair path through
            # the graph bridge
            step = tf.compat.v1.get_variable(
                "global_step", initializer=np.int64(1000 + rank),
                dtype=tf.int64)
            hook = tfhvd.BroadcastGlobalVariablesHook(root_rank=0)
            with tf.compat.v1.train.MonitoredTrainingSession(
                    hooks=[hook]) as sess:
                got1, got2, gots = sess.run([v1, v2, step])
            np.testing.assert_allclose(got1, np.full((3, 2), 1.0))
            np.testing.assert_allclose(got2, [10.0])
            assert gots == 1000, gots

        # direct graph op (no hook): explicit broadcast_variables from a
        # NON-zero root inside a plain compat.v1 Session
        g2 = tf.Graph()
        with g2.as_default():
            w = tf.compat.v1.get_variable(
                "w", initializer=np.arange(4, dtype=np.float32) + rank)
            op = tfhvd.broadcast_variables([w], root_rank=1)
            with tf.compat.v1.Session() as sess:
                sess.run(tf.compat.v1.global_variables_initializer())
                sess.run(op)
                got = sess.run(w)
            np.testing.assert_allclose(got,
                                       np.arange(4, dtype=np.float32) + 1)

    elif scenario == "tensorflow_errors":
        # Error paths THROUGH the TF binding (reference:
        # test_tensorflow.py:314-460 test_horovod_allreduce_error /
        # _type_error / _grad_cpu): a shape or dtype mismatched across
        # ranks must raise on EVERY rank, and the world must stay usable.
        import tensorflow as tf

        import horovod_tpu.tensorflow as tfhvd

        # shape mismatch across ranks
        x = tf.ones([4] if rank == 0 else [5])
        try:
            tfhvd.allreduce(x, average=False, name="bad/shape")
        except Exception as e:  # noqa: BLE001 — py_function wraps it
            assert "shape" in str(e).lower() or "mismatch" in str(e).lower(), \
                str(e)
        else:
            raise AssertionError("expected cross-rank shape error")

        # dtype mismatch across ranks under one wire name
        y = (tf.ones([3], tf.float32) if rank == 0
             else tf.ones([3], tf.int32))
        try:
            tfhvd.allreduce(y, average=False, name="bad/dtype")
        except Exception as e:  # noqa: BLE001
            msg = str(e).lower()
            assert "dtype" in msg or "type" in msg or "mismatch" in msg, \
                str(e)
        else:
            raise AssertionError("expected cross-rank dtype error")

        # the world must still be usable after both failures
        out = tfhvd.allreduce(tf.fill([2], float(rank)), average=False,
                              name="good/after")
        np.testing.assert_allclose(out.numpy(),
                                   np.full(2, float(sum(range(world)))))

    elif scenario == "pod_soak":
        # Pod dress rehearsal (VERDICT r3 ask 3): the whole stack in ONE
        # job the way a real pod run would see it — native wire, autotune
        # on (env from the test), per-rank timelines, torch + TF + JAX
        # collectives interleaved, a mid-run rank-0 checkpoint, a HARD
        # death (os._exit, no shutdown, simulating preemption), and a
        # resume run that restores, continues, and asserts lockstep.
        # Integration bugs live in the seams between these — each is
        # tested separately elsewhere.
        #
        # env: SOAK_DIR (artifact directory), SOAK_RESUME ("1" on the
        # second run). NOTE: HOROVOD_TIMELINE is set per-rank by the
        # TEST's wrapper env before hvd.init() ran above (mp_worker's
        # module init), so timelines are already recording here.
        import jax.numpy as jnp
        import torch

        import horovod_tpu.torch as thvd
        import horovod_tpu.tensorflow as tfhvd
        import tensorflow as tf
        from horovod_tpu import checkpoint as ckpt

        soak_dir = os.environ["SOAK_DIR"]
        resume = os.environ.get("SOAK_RESUME") == "1"
        ckpt_dir = os.path.join(soak_dir, "ckpt")

        # identical model state everywhere (broadcast aligns below)
        torch.manual_seed(1234 + rank)  # deliberately divergent init
        tmodel = torch.nn.Linear(6, 3)
        topt = thvd.DistributedOptimizer(
            torch.optim.SGD(tmodel.parameters(), lr=0.02),
            named_parameters=tmodel.named_parameters())
        thvd.broadcast_parameters(tmodel.state_dict(), root_rank=0)

        tf_w = tf.Variable(tf.fill([5], float(rank + 1)))
        tfhvd.broadcast_variables([tf_w], root_rank=0)

        jnp_w = np.full((4,), 1.0, np.float32)

        start_step = 0
        if resume:
            state0 = {"step": 0, "jnp_w": np.zeros((4,), np.float32)}
            restored, ckpt_step = ckpt.restore_latest(ckpt_dir, state0)
            assert ckpt_step == 5, f"resumed wrong checkpoint {ckpt_step}"
            start_step = int(restored["step"])
            jnp_w = np.asarray(restored["jnp_w"])
            assert start_step == 5, f"resumed wrong step {start_step}"

        def one_step(step):
            # JAX named collective (the runtime/wire path)
            h = hvd.allreduce_async(jnp_w * (rank + 1),
                                    name="soak/jnp_w")
            # torch hook path
            topt.zero_grad()
            loss = (tmodel(torch.ones(2, 6)).sum()) * (rank + 1)
            loss.backward()
            topt.step()
            # TF tape path
            with tf.GradientTape() as tape:
                tloss = tf.reduce_sum(tf_w * tf_w) * (rank + 1)
            dtape = tfhvd.DistributedGradientTape(tape)
            (g,) = dtape.gradient(tloss, [tf_w])
            tf_w.assign_sub(0.01 * g)
            return np.asarray(hvd.synchronize(h))

        stop_at = 5 if not resume else 10
        for step in range(start_step, stop_at):
            out = one_step(step)

        if not resume:
            ckpt.save(ckpt_dir, {"step": 5, "jnp_w": jnp_w}, step=5)
            # everyone waits until the save is published before dying —
            # an allreduce doubles as the barrier
            h = hvd.allreduce_async(np.ones(1, np.float32),
                                    name="soak/barrier")
            hvd.synchronize(h)
            print(f"CKPT_SAVED rank={rank}", flush=True)
            sys.stdout.flush()
            os._exit(137)  # hard preemption: no shutdown, no atexit

        # resume run: final lockstep assertions across every surface
        tdigest = np.concatenate(
            [p.detach().numpy().ravel() for p in tmodel.parameters()])
        full = np.concatenate([tdigest, tf_w.numpy(), out])
        h = hvd.allgather_async(full[None, :], name="soak/digest")
        dig = np.asarray(hvd.synchronize(h))
        for r in range(1, world):
            np.testing.assert_array_equal(dig[0], dig[r],
                                          err_msg="soak ranks diverged")
        print(f"SOAK_DONE rank={rank} steps={stop_at}", flush=True)

    elif scenario == "zero_parity":
        # ZeRO-1 sharded optimizer over the REAL wire: reduce-scatter +
        # update-on-shard + allgather must match the replicated update
        # computed locally from the same per-rank gradients. Integer-
        # valued f32 grads, so the ring sums (and /world for power-of-two
        # worlds) are exact and the SGD comparison is BIT-exact.
        import optax

        rng = np.random.RandomState(0)  # same tree on every rank
        params = {
            "a": np.asarray(rng.randint(-8, 8, (7,)), np.float32),
            "b": np.asarray(rng.randint(-8, 8, (5, 6)), np.float32),
        }
        # rank-DEPENDENT integer grads (known closed form across ranks)
        def grad_for(r, step):
            gr = np.random.RandomState(100 + step)
            base = {k: np.asarray(gr.randint(-4, 4, v.shape), np.float32)
                    for k, v in params.items()}
            return {k: v + np.float32(r) for k, v in base.items()}

        def mean_grad(step):
            acc = {k: np.zeros(v.shape, np.float64)
                   for k, v in params.items()}
            for r in range(world):
                g = grad_for(r, step)
                for k in acc:
                    acc[k] += g[k]
            return {k: (v / world).astype(np.float32) for k, v in
                    acc.items()}

        import jax.numpy as jnp

        sh = hvd.sharded_update(optax.sgd(0.25))
        jparams = {k: jnp.asarray(v) for k, v in params.items()}
        state = sh.init(jparams)
        p_sh = jparams
        expect = {k: v.copy() for k, v in params.items()}
        for step in range(3):
            g = {k: jnp.asarray(v)
                 for k, v in grad_for(rank, step).items()}
            upd, state = sh.update(g, state, p_sh)
            p_sh = optax.apply_updates(p_sh, upd)
            mg = mean_grad(step)
            for k in expect:
                expect[k] = expect[k] - np.float32(0.25) * mg[k]
        for k in expect:
            np.testing.assert_array_equal(
                np.asarray(p_sh[k]), expect[k],
                err_msg=f"sharded SGD diverged from replicated math "
                        f"on leaf {k} (rank {rank})")

        # fused flat AdamW over the wire vs replicated optax.adamw on
        # the mean grad (f32 round-off tolerance)
        ref = optax.adamw(1e-2, weight_decay=1e-3)
        ref_state = ref.init(jparams)
        sa = hvd.sharded_adamw(1e-2, weight_decay=1e-3)
        sa_state = sa.init(jparams)
        p_ref, p_sa = jparams, jparams
        for step in range(2):
            mg = {k: jnp.asarray(v) for k, v in mean_grad(step).items()}
            upd, ref_state = ref.update(mg, ref_state, p_ref)
            p_ref = optax.apply_updates(p_ref, upd)
            g = {k: jnp.asarray(v)
                 for k, v in grad_for(rank, step).items()}
            p_sa, sa_state = sa.apply(p_sa, sa_state, g)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_sa[k]), np.asarray(p_ref[k]),
                rtol=2e-5, atol=2e-6,
                err_msg=f"sharded adamw diverged on leaf {k}")
        # the state gauge must report the SHARD footprint, not the
        # replicated one (master+mu+nu f32 ~= 3 x params / world,
        # padding-inflated on these toy shapes)
        m = hvd.metrics().get("horovod_sharded_state_bytes")
        assert m and m["values"][0]["value"] > 0

    elif scenario == "debug_locks":
        # short training loop under the deadlock witness
        # (HOROVOD_DEBUG_LOCKS=1 set by the launcher): the runtime's own
        # locks are DebugLocks; assert the run is violation-free, the
        # observed acquisition order is consistent with the static
        # lock-order graph, and lock events reached the flight recorder.
        assert os.environ.get("HOROVOD_DEBUG_LOCKS") == "1"
        from horovod_tpu import flight_recorder
        from horovod_tpu.analysis import lockgraph, witness

        for step in range(4):
            hs = [hvd.allreduce_async(
                      np.full((64,), float(rank + step), np.float32),
                      name=f"grad/w{i}") for i in range(3)]
            hs.append(hvd.allgather_async(
                np.full((rank + 1, 2), rank, np.float32), name="ag/x"))
            for h in hs:
                hvd.synchronize(h)
        state = hvd.dump_debug_state()
        viols = witness.violations()
        assert not viols, f"witness violations on rank {rank}: {viols}"
        edges = witness.order_edges()
        assert edges, "expected at least one observed lock-order edge"
        pkg = os.path.dirname(os.path.dirname(
            os.path.abspath(hvd.__file__)))
        static = lockgraph.analyze_paths(
            [os.path.join(pkg, "horovod_tpu")], root=pkg)
        conflicts = witness.check_static_consistency(static.edges)
        assert not conflicts, f"static/runtime order conflict: {conflicts}"
        lock_events = [e for e in flight_recorder.recorder().events()
                       if str(e.get("kind", "")).startswith("lock_")]
        assert lock_events, "no lock_* events in the flight recorder"
        # the dump's state providers include the witness's view
        assert state["state"].get("locks", {}).get("enabled") is True

    elif scenario == "comms_degraded":
        # ISSUE 16 acceptance: a netdelay window on the host-ring data
        # plane must trip the comms-plane degradation detector exactly
        # once, naming the host_ring lane; the shutdown dump then
        # carries the ledger for the postmortem comms report.
        import time

        from horovod_tpu import comms, flight_recorder

        t = comms.tracker()
        # chaos t0 armed at the first inject seam during init, so it is
        # strictly before this scenario's entry stamp: the delay window
        # (never-closing, seconds=inf) is guaranteed open by
        # t_scn + after, and the fast phase below — seconds from t_scn —
        # is guaranteed clean as long as after= grants real headroom
        # over a loaded box's init tail
        t_scn = time.monotonic()
        # fast phase: enough host-ring ops to pass detector warmup and
        # set the lane's peak-observed roofline, all before the fault's
        # after= window opens
        for step in range(12):
            h = hvd.allreduce_async(
                np.full((4096,), float(rank), np.float32), name="cd/fast")
            hvd.synchronize(h)
        led = t.ledger()["lanes"].get("host_ring")
        assert led and led["ops_total"] >= 8, led
        assert not led["alerting"], led
        # wait out the fault-free window (anchored to the scenario
        # stamp, an upper bound on chaos t0), then run a FIXED number of
        # now-delayed ops — both ranks must issue the same collective
        # sequence in lockstep (a break-on-alert loop lets the first
        # alerting rank shut down while its peer still has an op in
        # flight). The EWMA (alpha 0.25) falls to 0.75^k of the fast
        # peak after k ~100x-slower records, crossing the 0.5 threshold
        # by k=3; 10 ops is deep margin
        wake = t_scn + float(os.environ.get("COMMS_DELAY_AFTER", "8.5"))
        time.sleep(max(0.0, wake - time.monotonic()))
        for step in range(10):
            h = hvd.allreduce_async(
                np.full((4096,), float(rank), np.float32), name="cd/slow")
            hvd.synchronize(h)
        evs = [e for e in flight_recorder.recorder().events()
               if e.get("kind") == "comms_degraded"
               and e.get("lane") == "host_ring"]
        assert len(evs) == 1, evs  # latched: ONE event per crossing
        assert evs[0]["op"] == "allreduce", evs
        assert evs[0]["utilization"] < evs[0]["threshold"], evs
        led = t.ledger()["lanes"]["host_ring"]
        assert led["alerting"] and led["degraded_count"] == 1, led
        assert led["last_degraded"]["op"] == "allreduce", led
        # leave a dump for the launcher's postmortem comms-report check
        hvd.dump_debug_state(reason="comms_degraded_test")
        print("COMMS_DEGRADED_OK", flush=True)

    else:
        raise SystemExit(f"unknown scenario {scenario}")

    hvd.shutdown()
    print(f"OK rank={rank}", flush=True)


if __name__ == "__main__":
    main()
