"""Worker script for the 2-rank profiler merge test (tests/
test_profiler.py): run a few explicitly profiled steps whose collective
goes over the real socket/native transport, then shut down — the
profiler dumps ``profile-rank-N.json`` into HOROVOD_PROFILE_DIR and
ships a copy to the rendezvous store, exactly what ``tpurun
--profile-dir`` harvests."""

import os
import sys

import numpy as np

import horovod_tpu as hvd

STEPS = int(os.environ.get("PROFILER_WORKER_STEPS", "4"))


def main() -> int:
    hvd.init()
    assert hvd.profiler.enabled(), "HOROVOD_PROFILE_DIR must enable it"
    for step in range(STEPS):
        with hvd.profiler.step(f"step {step}"):
            with hvd.profiler.annotate("host"):
                batch = np.ones(64, np.float32)
            out = hvd.allreduce(batch, average=True, name="prof_grad")
    assert float(np.asarray(out)[0]) == 1.0
    summary = hvd.profiler.summary()
    print(f"DONE rank={hvd.rank()} steps={summary['steps']} "
          f"wall={summary['wall_seconds']:.6f}", flush=True)
    hvd.shutdown()  # dumps + ships the profile
    return 0 if summary["steps"] == STEPS else 3


if __name__ == "__main__":
    sys.exit(main())
