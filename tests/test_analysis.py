"""hvd-analyze: static concurrency/collective analysis + runtime witness.

Unit-tests each analyzer pass on synthetic fixtures (known-bad lock
inversion, rank-conditional collective, unguarded mutation, clean file),
the baseline round-trip, the CLI contract, the runtime witness, and —
the CI teeth — that the repo itself analyzes clean against the
checked-in baseline (tier-1 enforced, same pattern as the env-knob
check)."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "hvd_analyze.py")

from horovod_tpu.analysis import baseline, divergence, lockgraph, witness  # noqa: E402
from horovod_tpu.analysis.report import Finding  # noqa: E402


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# lock-order graph


def test_lock_order_inversion_cycle_detected(tmp_path):
    path = _write(tmp_path, "inv.py", """
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
""")
    res = lockgraph.analyze_paths([path])
    assert "lock-order-cycle" in _rules(res.findings)
    assert ("S._a", "S._b") in res.edges and ("S._b", "S._a") in res.edges


def test_consistent_order_is_clean(tmp_path):
    path = _write(tmp_path, "ok.py", """
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a:
            with self._b:
                pass
""")
    res = lockgraph.analyze_paths([path])
    assert res.findings == []
    assert res.edges == [("S._a", "S._b")]


def test_blocking_call_under_lock(tmp_path):
    path = _write(tmp_path, "blk.py", """
import threading, time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = make_queue()

    def bad_get(self):
        with self._lock:
            return self._q.get()

    def ok_get(self):
        with self._lock:
            return self._q.get(timeout=1.0)

    def bad_sleep(self):
        with self._lock:
            time.sleep(1.0)

    def ok_outside(self):
        time.sleep(1.0)
        return self._q.get()
""")
    res = lockgraph.analyze_paths([path])
    blocked = [f for f in res.findings if f.rule == "blocking-under-lock"]
    assert {f.symbol for f in blocked} == {"S.bad_get", "S.bad_sleep"}


def test_blocking_propagates_interprocedurally(tmp_path):
    path = _write(tmp_path, "inter.py", """
import threading, time

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def _helper(self):
        time.sleep(2.0)

    def caller(self):
        with self._lock:
            self._helper()
""")
    res = lockgraph.analyze_paths([path])
    blocked = [f for f in res.findings if f.rule == "blocking-under-lock"]
    assert len(blocked) == 1 and blocked[0].symbol == "S.caller"
    assert "_helper" in blocked[0].message


def test_make_lock_names_become_ids(tmp_path):
    path = _write(tmp_path, "named.py", """
from horovod_tpu.analysis.witness import make_lock

class S:
    def __init__(self):
        self._lock = make_lock("Custom.name")

    def go(self):
        with self._lock:
            sock.recv(4)
""")
    res = lockgraph.analyze_paths([path])
    assert "Custom.name" in res.locks
    blocked = [f for f in res.findings if f.rule == "blocking-under-lock"]
    assert blocked and "Custom.name" in blocked[0].message


def test_guarded_by_mutation_outside_lock(tmp_path):
    path = _write(tmp_path, "guard.py", """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock

    def good(self, k, v):
        with self._lock:
            self._table[k] = v
            self._count += 1

    def bad(self, k, v):
        self._table[k] = v

    def also_bad(self):
        self._count += 1

    def mutator_call_bad(self):
        self._table.clear()

    def read_ok(self, k):
        return self._table.get(k)
""")
    res = lockgraph.analyze_paths([path])
    bad = [f for f in res.findings if f.rule == "unguarded-mutation"]
    assert {f.symbol for f in bad} == {"S.bad", "S.also_bad", "S.mutator_call_bad"}


def test_holds_lock_annotation_assumed(tmp_path):
    path = _write(tmp_path, "holds.py", """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def _bump_locked(self):  # holds-lock: _lock
        self._n += 1

    def outer(self):
        with self._lock:
            self._bump_locked()
""")
    res = lockgraph.analyze_paths([path])
    assert res.findings == []


def test_clean_file_zero_findings(tmp_path):
    path = _write(tmp_path, "clean.py", """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def snapshot(self):
        with self._lock:
            return list(self._items)
""")
    res = lockgraph.analyze_paths([path])
    assert res.findings == []


# ---------------------------------------------------------------------------
# divergence lint


def test_rank_conditional_collective_flagged(tmp_path):
    path = _write(tmp_path, "rc.py", """
def step(st, x):
    if st.rank == 0:
        x = allreduce(x, name="only-on-zero")
    return x
""")
    fs = divergence.analyze_paths([path])
    assert _rules(fs) == ["rank-conditional-collective"]


def test_symmetric_branches_not_flagged(tmp_path):
    path = _write(tmp_path, "sym.py", """
def fan(st, blob):
    if st.rank == 0:
        return bcast(blob)
    else:
        return bcast(None)

def fan_early_return(st, blob):
    if st.rank == 0:
        return bcast(blob)
    return bcast(None)
""")
    assert divergence.analyze_paths([path]) == []


def test_rank_early_exit_then_collective_flagged(tmp_path):
    path = _write(tmp_path, "exit.py", """
def save(st, x):
    if st.rank != 0:
        return None
    return broadcast(x, 0)
""")
    fs = divergence.analyze_paths([path])
    assert _rules(fs) == ["rank-conditional-collective"]
    assert "early exit" in fs[0].message


def test_size_conditional_collective_flagged(tmp_path):
    path = _write(tmp_path, "sz.py", """
def sync(st, x):
    if st.size > 1:
        x = broadcast(x, 0)
    return x
""")
    fs = divergence.analyze_paths([path])
    assert _rules(fs) == ["size-conditional-collective"]


def test_size_early_exit_guard_not_flagged(tmp_path):
    path = _write(tmp_path, "szguard.py", """
def sync(st, x):
    if st.size <= 1:
        return x
    return broadcast(x, 0)
""")
    assert divergence.analyze_paths([path]) == []


def test_broadcast_to_shape_op_not_flagged(tmp_path):
    """jnp.broadcast_to / np.broadcast_arrays share the broadcast* prefix
    but are pure shape utilities — a size-conditional use (e.g. the
    bucket wire's replicated-gradient staging) must not be flagged."""
    path = _write(tmp_path, "shapes.py", """
import jax.numpy as jnp

def stage(st, x):
    if st.size > 1:
        x = jnp.broadcast_to(x, (st.size,) + x.shape)
        x, y = jnp.broadcast_arrays(x, x)
    return x
""")
    assert divergence.analyze_paths([path]) == []


def test_nondeterministic_name_flagged(tmp_path):
    path = _write(tmp_path, "nd.py", """
import time, uuid

def a(x):
    return allreduce(x, name=f"grad.{id(x)}")

def b(x):
    return allgather(x, name="t-" + str(uuid.uuid4()))

def c(x):
    return broadcast(x, 0, name=f"bc.{time.time()}")

def fine(x, i):
    return allreduce(x, name=f"grad.{i}")
""")
    fs = divergence.analyze_paths([path])
    assert _rules(fs) == ["nondeterministic-collective-name"]
    assert {f.symbol for f in fs} == {"a", "b", "c"}


# ---------------------------------------------------------------------------
# baseline


def test_baseline_round_trip(tmp_path):
    f1 = Finding(rule="r1", file="a.py", line=3, symbol="A.x", message="m1",
                 detail="d1")
    f2 = Finding(rule="r2", file="b.py", line=7, symbol="B.y", message="m2",
                 detail="d2")
    path = str(tmp_path / "base.json")
    baseline.write(path, [f1, f2], reasons={f1.fingerprint: "reviewed: ok"})
    loaded = baseline.load(path)
    assert set(loaded) == {f1.fingerprint, f2.fingerprint}
    assert loaded[f1.fingerprint]["reason"] == "reviewed: ok"

    # all findings suppressed, none new/stale
    new, sup, stale = baseline.compare([f1, f2], loaded)
    assert (new, len(sup), stale) == ([], 2, [])

    # a fixed finding leaves a stale suppression; a fresh one is new
    f3 = Finding(rule="r3", file="c.py", line=1, symbol="C.z", message="m3")
    new, sup, stale = baseline.compare([f1, f3], loaded)
    assert [f.fingerprint for f in new] == [f3.fingerprint]
    assert [f.fingerprint for f in sup] == [f1.fingerprint]
    assert [e["fingerprint"] for e in stale] == [f2.fingerprint]


def test_baseline_fingerprint_ignores_lines():
    a = Finding(rule="r", file="f.py", line=10, symbol="S.m", message="x",
                detail="d")
    b = Finding(rule="r", file="f.py", line=99, symbol="S.m", message="x",
                detail="d")
    assert a.fingerprint == b.fingerprint


def test_baseline_requires_reasons(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        json.dump({"schema": baseline.SCHEMA,
                   "suppressions": [{"fingerprint": "abc", "reason": ""}]}, f)
    with pytest.raises(ValueError, match="no reason"):
        baseline.load(path)


def test_repo_baseline_reasons_are_reviewed():
    """Acceptance: the checked-in baseline holds only reviewed
    suppressions, each with a real reason string."""
    entries = baseline.load(os.path.join(REPO, "tools",
                                         "analysis_baseline.json"))
    assert entries, "expected a non-empty reviewed baseline"
    for fp, e in entries.items():
        assert e["reason"].startswith("reviewed:"), (
            f"baseline entry {fp} has an unreviewed reason: {e['reason']!r}")


# ---------------------------------------------------------------------------
# CLI (the CI enforcement — same pattern as check_env_knobs)


def test_cli_repo_is_clean_against_baseline():
    out = subprocess.run([sys.executable, CLI], capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_cli_json_reports_new_findings(tmp_path):
    _write(tmp_path, "bad.py", """
import threading, time

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self):
        with self._lock:
            time.sleep(5)
""")
    out = subprocess.run(
        [sys.executable, CLI, "--no-baseline", "--json", str(tmp_path)],
        capture_output=True, text=True)
    assert out.returncode == 1
    report = json.loads(out.stdout)
    assert [f["rule"] for f in report["new"]] == ["blocking-under-lock"]


def test_cli_update_baseline_then_clean(tmp_path):
    _write(tmp_path, "bad.py", """
def f(st, x):
    if st.rank == 0:
        x = allreduce(x)
    return x
""")
    base = str(tmp_path / "base.json")
    up = subprocess.run(
        [sys.executable, CLI, "--baseline", base, "--update-baseline",
         str(tmp_path)], capture_output=True, text=True)
    assert up.returncode == 0, up.stdout + up.stderr
    rerun = subprocess.run(
        [sys.executable, CLI, "--baseline", base, str(tmp_path)],
        capture_output=True, text=True)
    assert rerun.returncode == 0, rerun.stdout + rerun.stderr
    assert "suppressed:" in rerun.stdout
    # stale suppressions fail once the offending code is fixed
    (tmp_path / "bad.py").write_text("def f(st, x):\n    return x\n")
    stale = subprocess.run(
        [sys.executable, CLI, "--baseline", base, str(tmp_path)],
        capture_output=True, text=True)
    assert stale.returncode == 1
    assert "STALE" in stale.stderr


def test_cli_missing_path_is_usage_error():
    out = subprocess.run([sys.executable, CLI, "/nonexistent/dir"],
                         capture_output=True, text=True)
    assert out.returncode == 2


# ---------------------------------------------------------------------------
# runtime witness (DebugLock used directly; no env flip needed)


@pytest.fixture(autouse=True)
def _fresh_witness():
    witness.reset()
    yield
    witness.reset()


def test_witness_records_order_and_inversion():
    a = witness.DebugLock("W1.a")
    b = witness.DebugLock("W1.b")
    with a:
        with b:
            pass
    assert ("W1.a", "W1.b") in witness.order_edges()
    assert witness.violations() == []
    # reversed order on the same thread (locks free, so no deadlock —
    # but the order inversion is the latent bug)
    with b:
        with a:
            pass
    kinds = [v["kind"] for v in witness.violations()]
    assert kinds == ["lock-order-inversion"]
    v = witness.violations()[0]
    assert sorted(v["locks"]) == ["W1.a", "W1.b"]
    assert v["stack"] and v["prior_stack"]


def test_witness_self_deadlock_raises():
    a = witness.DebugLock("W2.a")
    with a:
        with pytest.raises(RuntimeError, match="self-deadlock"):
            a.acquire()
    assert [v["kind"] for v in witness.violations()] == ["self-deadlock"]


def test_witness_reentrant_lock_is_fine():
    a = witness.DebugLock("W3.a", reentrant=True)
    with a:
        with a:
            pass
    assert witness.violations() == []
    assert not a.locked()


def test_witness_hold_warning(monkeypatch):
    monkeypatch.setenv("HOROVOD_LOCK_HOLD_WARN_SECONDS", "0.05")
    a = witness.DebugLock("W4.a")
    with a:
        time.sleep(0.2)
    kinds = [v["kind"] for v in witness.violations()]
    assert "lock-hold" in kinds


def test_witness_detects_real_deadlock():
    a = witness.DebugLock("W5.a")
    b = witness.DebugLock("W5.b")
    ready = threading.Barrier(2)
    results = []

    def t1():
        with a:
            ready.wait()
            got = b.acquire(timeout=2.0)
            results.append(got)
            if got:
                b.release()

    def t2():
        with b:
            ready.wait()
            got = a.acquire(timeout=2.0)
            results.append(got)
            if got:
                a.release()

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start(); th2.start()
    th1.join(timeout=10); th2.join(timeout=10)
    assert not th1.is_alive() and not th2.is_alive()
    kinds = {v["kind"] for v in witness.violations()}
    assert "deadlock" in kinds
    dead = [v for v in witness.violations() if v["kind"] == "deadlock"][0]
    assert sorted(dead["locks"]) == ["W5.a", "W5.b"]


def test_witness_static_consistency():
    a = witness.DebugLock("W6.a")
    b = witness.DebugLock("W6.b")
    with b:
        with a:
            pass
    # static graph claims a before b; runtime observed b->a
    conflicts = witness.check_static_consistency([("W6.a", "W6.b")])
    assert conflicts and "W6.b->W6.a" in conflicts[0]
    # consistent static claim -> no conflict
    assert witness.check_static_consistency([("W6.b", "W6.a")]) == []


def test_make_lock_plain_by_default(monkeypatch):
    monkeypatch.delenv("HOROVOD_DEBUG_LOCKS", raising=False)
    lk = witness.make_lock("W7.plain")
    assert not isinstance(lk, witness.DebugLock)
    monkeypatch.setenv("HOROVOD_DEBUG_LOCKS", "1")
    dbg = witness.make_lock("W7.debug")
    assert isinstance(dbg, witness.DebugLock)


def test_debug_locks_end_to_end_single_process(tmp_path):
    """Single-process tier-1 witness smoke (the multiprocess variant
    lives in test_multiprocess.py): drive the real runtime's named-async
    lane under HOROVOD_DEBUG_LOCKS=1 in a subprocess, assert zero
    violations, static/runtime order consistency and lock_acquire
    events in the flight recorder."""
    script = tmp_path / "drive.py"
    script.write_text("""
import os, sys
import numpy as np
sys.path.insert(0, %r)
import horovod_tpu as hvd
from horovod_tpu import flight_recorder
from horovod_tpu.analysis import lockgraph, witness

hvd.init()
hs = [hvd.allreduce_async(np.ones((32,), np.float32), name=f"t{i}")
      for i in range(4)]
for h in hs:
    hvd.synchronize(h)
assert witness.violations() == [], witness.violations()
assert witness.order_edges(), "no observed lock edges"
static = lockgraph.analyze_paths([os.path.join(%r, "horovod_tpu")], root=%r)
assert witness.check_static_consistency(static.edges) == []
ev = [e for e in flight_recorder.recorder().events()
      if str(e.get("kind", "")).startswith("lock_")]
assert ev, "no lock events"
hvd.shutdown()
print("WITNESS_OK")
""" % (REPO, REPO, REPO))
    env = dict(os.environ)
    env.update({"HOROVOD_DEBUG_LOCKS": "1", "JAX_PLATFORMS": "cpu"})
    out = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "WITNESS_OK" in out.stdout


# ---------------------------------------------------------------------------
# the repo's own guarded-by coverage is real, not an empty ruleset


def test_repo_has_guarded_by_coverage():
    res = lockgraph.analyze_paths([os.path.join(REPO, "horovod_tpu")],
                                  root=REPO)
    guarded_files = {g.file for g in res.guards}
    for expected in ("horovod_tpu/runtime/executor.py",
                     "horovod_tpu/runtime/tensor_queue.py",
                     "horovod_tpu/runtime/fusion_buffer.py",
                     "horovod_tpu/runtime/response_cache.py",
                     "horovod_tpu/elastic/state.py"):
        assert expected in guarded_files, f"no guarded-by rules in {expected}"
    # and the witness-wrapped locks carry analyzer-visible ids
    for lock_id in ("Runtime._inflight_lock", "TensorQueue._lock",
                    "Executor._lock", "FusionBufferManager._lock",
                    "State._spill_lock", "GlobalState.lock",
                    "FlightRecorder._dump_lock"):
        assert lock_id in res.locks, f"lock {lock_id} not extracted"
