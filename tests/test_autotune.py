"""Autotuner tests: GP regression, Bayesian optimization, parameter
manager schedule, and end-to-end runtime integration.

The reference has no standalone autotuner tests (its C++ is tested through
the bindings); here the tuner is exercised directly plus through the
runtime the way HOROVOD_AUTOTUNE=1 would engage it.
"""

import dataclasses
import os

import numpy as np
import pytest

from horovod_tpu.autotune.bayesian_optimization import BayesianOptimization
from horovod_tpu.autotune.gaussian_process import GaussianProcessRegressor
from horovod_tpu.autotune.parameter_manager import (
    SAMPLES_PER_POINT, ParameterManager, Params)


class TestGaussianProcess:
    def test_interpolates_training_points(self):
        gp = GaussianProcessRegressor(alpha=1e-8)
        X = np.linspace(0, 1, 7)[:, None]
        y = np.sin(3 * X[:, 0])
        gp.fit(X, y)
        mu, std = gp.predict(X)
        np.testing.assert_allclose(mu, y, atol=1e-3)
        assert np.all(std < 0.1)

    def test_uncertainty_grows_off_data(self):
        gp = GaussianProcessRegressor(alpha=1e-8, length_scale=0.1)
        X = np.array([[0.0], [0.1]])
        gp.fit(X, np.array([1.0, 2.0]))
        _, std_near = gp.predict(np.array([[0.05]]))
        _, std_far = gp.predict(np.array([[3.0]]))
        assert std_far[0] > std_near[0]

    def test_predict_before_fit(self):
        gp = GaussianProcessRegressor()
        mu, std = gp.predict(np.array([[0.5]]))
        assert mu.shape == (1,) and std.shape == (1,)


class TestBayesianOptimization:
    def test_finds_maximum_of_concave_function(self):
        # f(x, y) = -(x-3)^2 - (y-7)^2, max at (3, 7)
        bo = BayesianOptimization(bounds=[(0, 10), (0, 10)], seed=1)
        for _ in range(25):
            x = bo.next_sample()
            y = -(x[0] - 3.0) ** 2 - (x[1] - 7.0) ** 2
            bo.add_sample(x, y)
        best_x, best_y = bo.best()
        assert best_y > -2.0, (best_x, best_y)  # within ~1.4 of optimum

    def test_respects_bounds(self):
        bo = BayesianOptimization(bounds=[(2, 4)], seed=0)
        for _ in range(10):
            x = bo.next_sample()
            assert 2.0 <= x[0] <= 4.0
            bo.add_sample(x, float(x[0]))


def _mk_manager(**kw):
    initial = Params(
        fusion_threshold_bytes=64 * 1024 * 1024, cycle_time_ms=5.0,
        cache_enabled=True, hierarchical_allreduce=False,
        hierarchical_allgather=False)
    kw.setdefault("warmup_samples", 1)
    kw.setdefault("steps_per_sample", 2)
    kw.setdefault("bayes_opt_max_samples", 6)
    return ParameterManager(initial, **kw)


class TestParameterManager:
    def test_warmup_discarded_then_samples_collected(self):
        pm = _mk_manager()
        # warmup sample (steps_per_sample updates) produces no tuning
        for _ in range(2):
            assert not pm.update(1000, 0.001)
        # now SAMPLES_PER_POINT samples must pass before the first tune
        n_updates = 2 * SAMPLES_PER_POINT
        changed = [pm.update(1000, 0.001) for _ in range(n_updates)]
        assert changed[-1]  # first categorical flip happened
        assert sum(changed) == 1

    def test_categorical_sweep_keeps_better_value(self):
        pm = _mk_manager()
        # cache_enabled=True default scores high; False scores low
        scores = {True: 100.0, False: 10.0}
        for _ in range(40):
            if not pm.active:
                break
            s = scores[pm.current.cache_enabled]
            pm.update(int(s * 1e6 * 0.001), 0.001)
            if pm._phase != "categorical" or pm._cat_index > 0:
                break
        assert pm.current.cache_enabled is True

    def test_converges_and_freezes_at_best(self):
        pm = _mk_manager(bayes_opt_max_samples=4)
        # peak throughput at fusion_threshold ~ 32MB, cycle ~ 3ms
        def score_of(p):
            mb = p.fusion_threshold_bytes / (1024 * 1024)
            return 100.0 - (mb - 32.0) ** 2 / 50 - (p.cycle_time_ms - 3) ** 2
        guard = 0
        while pm.active and guard < 2000:
            s = max(score_of(pm.current), 1.0)
            pm.update(int(s * 1e6 * 0.001), 0.001)
            guard += 1
        assert not pm.active
        assert not pm.current.active
        # frozen config equals the best recorded one
        assert pm.current.fusion_threshold_bytes == pm.best.fusion_threshold_bytes
        assert pm.best_score >= 1.0

    def test_csv_log_written(self, tmp_path):
        log = tmp_path / "autotune.csv"
        pm = _mk_manager(log_path=str(log))
        guard = 0
        while pm.active and guard < 2000:
            pm.update(50_000, 0.001)
            guard += 1
        text = log.read_text().strip().splitlines()
        # r5: the artifact is self-describing — the first line names the
        # knobs actually swept in THIS run (the hierarchical knobs leave
        # the sweep on the socket data plane; r4 review weak #5)
        assert text[0].startswith("# swept: ")
        assert "fusion_threshold_mb" in text[0]
        assert text[1].startswith("timestamp,fusion_threshold_mb")
        assert len(text) > 4  # one line per scored point

    def test_csv_log_names_swept_categoricals(self, tmp_path):
        log = tmp_path / "autotune.csv"
        pm = _mk_manager(log_path=str(log), sweep=("cache_enabled",))
        header = log.read_text().splitlines()[0]
        assert header == ("# swept: fusion_threshold_mb,cycle_time_ms,"
                          "grad_bucket_mb,pipeline_depth,"
                          "zero_prefetch_buckets,cache_enabled")
        assert pm.swept_knobs == ("fusion_threshold_mb", "cycle_time_ms",
                                  "grad_bucket_mb", "pipeline_depth",
                                  "zero_prefetch_buckets", "cache_enabled")

    def test_params_blob_roundtrip(self):
        p = Params(12345678, 7.25, False, True, False, active=True)
        assert Params.unpack(p.pack()) == p

    def test_params_blob_roundtrip_zero_prefetch(self):
        p = Params(12345678, 7.25, False, True, False, active=True,
                   zero_prefetch_buckets=4)
        assert Params.unpack(p.pack()) == p

    def test_search_box_has_prefetch_dim(self):
        from horovod_tpu.autotune.parameter_manager import (
            PREFETCH_BOUNDS, search_box_from_roofline)

        assert search_box_from_roofline(None)[4] == PREFETCH_BOUNDS
        assert search_box_from_roofline(
            {"allreduce_busbw_gbps": 2.0})[4] == PREFETCH_BOUNDS


class TestRuntimeIntegration:
    def test_autotune_with_cache_disabled(self, hvd, monkeypatch):
        """HOROVOD_CACHE_CAPACITY=0 + --autotune: the cache knob leaves the
        sweep (toggling it on would crash put() on a zero-capacity cache)
        and tuning still runs over the continuous knobs."""
        monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
        monkeypatch.setenv("HOROVOD_CACHE_CAPACITY", "0")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "0")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "2")
        hvd.shutdown()
        hvd.init()
        try:
            from horovod_tpu.runtime.runtime import get_runtime

            rt = get_runtime()
            assert "cache_enabled" not in rt.param_manager._sweep
            for i in range(80):
                h = hvd.allreduce_async(
                    np.full((8,), 1.0, np.float32), name=f"cz/{i % 2}")
                np.testing.assert_allclose(
                    np.asarray(hvd.synchronize(h)), 1.0)
                if not rt._autotune_active:
                    break
            assert not rt._autotune_active
        finally:
            hvd.shutdown()

    def test_autotune_engages_and_converges(self, hvd, monkeypatch):
        """HOROVOD_AUTOTUNE=1: the runtime scores cycles, tunes, broadcasts
        params, and keeps collectives correct while knobs change."""
        monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "0")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "2")
        hvd.shutdown()
        hvd.init()
        try:
            from horovod_tpu.runtime.runtime import get_runtime

            rt = get_runtime()
            assert rt.param_manager is not None
            seen_cycle_times = set()
            for i in range(120):
                h = hvd.allreduce_async(
                    np.full((16,), 1.0, np.float32), name=f"at/{i % 4}")
                out = np.asarray(hvd.synchronize(h))
                np.testing.assert_allclose(out, 1.0)
                seen_cycle_times.add(round(rt._cycle_time_s, 6))
                if not rt._autotune_active:
                    break
            assert not rt._autotune_active, "autotune did not converge"
            # params actually moved at least once during tuning
            assert len(seen_cycle_times) > 1
            # frozen config matches the manager's best
            assert (rt._st.config.fusion_threshold_bytes
                    == rt.param_manager.best.fusion_threshold_bytes)
        finally:
            hvd.shutdown()


class TestBandwidthProbe:
    """Hardware probes seeding the tuner (north star: autotuner backed by
    HBM/ICI bandwidth probes)."""

    def test_probes_return_positive_bandwidth(self, hvd_flat):
        from horovod_tpu.autotune import probe

        hbm = probe.probe_hbm_bandwidth(size_mb=4, iters=2)
        ar = probe.probe_allreduce_bandwidth(size_mb=2, iters=2)
        assert np.isfinite(hbm) and hbm > 0
        assert np.isfinite(ar) and ar > 0

    def test_recommended_threshold_scales_and_clamps(self):
        from horovod_tpu.autotune.probe import recommended_fusion_threshold

        # 100 GB/s, 5 ms cycle, half budget -> 250 MB (under the 256 MB
        # cap, so unclamped)
        t = recommended_fusion_threshold(100.0, 5.0)
        assert t == 100e9 * 0.0025
        # HBM cap: packing/unpacking bounds the feed rate at hbm/2
        t = recommended_fusion_threshold(100.0, 5.0, hbm_gbps=40.0)
        assert t == 20e9 * 0.0025
        # slow link clamps to the floor
        assert recommended_fusion_threshold(0.001, 5.0) == 1 << 20
        # absurdly fast link clamps to the ceiling
        assert recommended_fusion_threshold(1e6, 5.0) == 256 << 20

    def test_probe_seeds_runtime_config(self, monkeypatch):
        import horovod_tpu as hvd
        from horovod_tpu.autotune import probe
        from horovod_tpu.core import state as state_mod

        monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
        monkeypatch.setenv("HOROVOD_AUTOTUNE_PROBE", "1")
        monkeypatch.setattr(probe, "probe_hbm_bandwidth",
                            lambda **kw: 123.0)
        monkeypatch.setattr(probe, "probe_allreduce_bandwidth",
                            lambda mesh=None, **kw: 10.0)
        hvd.shutdown()
        hvd.init(mesh_shape=(1, 8))
        try:
            from horovod_tpu.runtime.runtime import get_runtime

            rt = get_runtime()
            expected = probe.recommended_fusion_threshold(
                10.0, rt._st.config.cycle_time_ms)
            assert rt._st.config.fusion_threshold_bytes == expected
        finally:
            hvd.shutdown()
